"""Memory-governor oracle sweep: all 22 TPC-H queries, twice — once with
unlimited memory, once under a budget tiny enough that the governor
denies **every** join-build and aggregation-state reservation — results
compared **bit-identically** between the legs.

The memory plane (docs/user-guide/memory.md) promises that spilling is
invisible to results: agg partial runs + sort-merge finalize, join
partitioned-build rehydrate, both emitting exactly what the in-memory
path emits.  This sweep is the oracle for that promise, and it also
asserts the negative space: the budget leg must actually have denied
reservations and written spill runs (a sweep where nothing spilled
proves nothing), and every reservation must be released by the end
(leak check: reserved bytes return to zero).

    python -m tools.memory_sweep            # writes MEMORY_SWEEP.json

Legs:

- ``unlimited``: shipped defaults (budget 0) — the bit-identity baseline
- ``budget``:    ``ballista.memory.host.budget.bytes=MEMSWEEP_BUDGET``
                 (default 1 MiB: below any SF1 build/agg footprint)

Env knobs: ``BENCH_DATA`` (default ``.bench_data/tpch-sf1``; when the
directory is missing the sweep generates SF ``MEMSWEEP_SCALE`` tables
in-process instead), ``SWEEP_QUERIES``, ``SWEEP_OUT``,
``MEMSWEEP_BUDGET``, ``MEMSWEEP_SCALE`` (default 0.01).
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(REPO, ".bench_data", "tpch-sf1"))
OUT = os.environ.get("SWEEP_OUT", os.path.join(REPO, "MEMORY_SWEEP.json"))
BUDGET = int(os.environ.get("MEMSWEEP_BUDGET", str(1 << 20)))
SCALE = float(os.environ.get("MEMSWEEP_SCALE", "0.01"))

LEGS = {
    "unlimited": {},
    "budget": {"ballista.memory.host.budget.bytes": str(BUDGET)},
}


def _register(ctx):
    from benchmarks.tpch import register_tables

    if os.path.exists(os.path.join(DATA_DIR, "lineitem.parquet")):
        register_tables(ctx, DATA_DIR)
        return DATA_DIR
    from benchmarks.datagen import generate_tables

    for name, table in generate_tables(SCALE, seed=1).items():
        ctx.register_table(name, table)
    return f"generated sf{SCALE}"


def _run_leg(leg: str, overrides: dict, queries, artifact: dict):
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.memory.governor import STATS as MEM_STATS
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from benchmarks.queries import QUERIES

    conf = {"ballista.batch.size": str(1 << 20), **overrides}
    ctx = BallistaContext.local(BallistaConfig(dict(conf)))
    frames = {}
    MEM_STATS.reset()
    try:
        artifact["data"] = _register(ctx)
        for q in queries:
            t0 = time.time()
            frames[q] = ctx.sql(QUERIES[q]).to_pandas()
            artifact.setdefault(f"q{q}", {})[f"{leg}_s"] = round(
                time.time() - t0, 1)
            print(f"[memsweep] {leg} q{q}: {time.time()-t0:.1f}s "
                  f"({len(frames[q])} rows)", flush=True)
    finally:
        ctx.shutdown()
    snap = MEM_STATS.snapshot()
    artifact[f"{leg}_governor"] = snap
    # leak check: every reservation a leg took must have been released
    for key, n in snap.items():
        if key.startswith("reserved_bytes."):
            assert n == 0, f"{leg}: {n} bytes leaked in {key}"
    return frames


def main() -> None:
    import pandas as pd

    from benchmarks.queries import QUERIES

    queries = sorted(
        int(x) for x in os.environ.get(
            "SWEEP_QUERIES", ",".join(map(str, sorted(QUERIES)))).split(",")
        if x.strip())

    t_all = time.time()
    artifact: dict = {"legs": list(LEGS), "budget_bytes": BUDGET}
    baseline = _run_leg("unlimited", LEGS["unlimited"], queries, artifact)
    frames = _run_leg("budget", LEGS["budget"], queries, artifact)

    gov = artifact["budget_governor"]
    assert gov.get("reserve_denied_total", 0) > 0, \
        f"budget leg denied nothing — sweep proved nothing: {gov}"
    assert gov.get("spill_runs_total", 0) > 0, \
        f"budget leg wrote no spill runs: {gov}"

    ok, mismatches = 0, []
    for q in queries:
        entry = artifact.setdefault(f"q{q}", {})
        try:
            # bit-identical: exact dtypes, exact values, exact order
            pd.testing.assert_frame_equal(
                baseline[q].reset_index(drop=True),
                frames[q].reset_index(drop=True), check_exact=True)
            entry["identical"] = True
            ok += 1
        except Exception as e:  # noqa: BLE001 — record and continue
            entry["identical"] = False
            entry["error"] = str(e)[:500]
            mismatches.append(q)
    artifact["identical"] = ok
    artifact["total"] = len(queries)
    artifact["wall_s"] = round(time.time() - t_all, 1)
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"[memsweep] {ok}/{len(queries)} bit-identical under a "
          f"{BUDGET}-byte budget ({gov['spill_runs_total']} spill runs, "
          f"{gov['spill_bytes_total']} bytes, "
          f"{gov['reserve_denied_total']} denials) -> {OUT}", flush=True)
    if mismatches:
        raise SystemExit(f"spill-path mismatch on queries: {mismatches}")


if __name__ == "__main__":
    main()
