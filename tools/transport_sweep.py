"""Transport-equivalence sweep: all 22 TPC-H queries, SF1, through a real
2-executor TCP cluster, once per transport configuration, results compared
**bit-identically** against the first leg.

The shuffle data plane (docs/user-guide/shuffle.md) has three transports —
co-located mmap, chunked+compressed streaming, legacy whole-file — chosen
per location at runtime.  This sweep is the oracle that the choice is
invisible: every query must return byte-for-byte identical frames no
matter which transport carried the shuffle.

    python -m tools.transport_sweep            # writes TRANSPORT_SWEEP.json

Legs (executor-side config):

- ``mmap``:   shipped defaults (host-match mmap + streaming + lz4)
- ``wire``:   host_match=false                 -> compressed chunked stream
- ``legacy``: host_match=false, streaming=false -> whole-file protocol

Env knobs: ``BENCH_DATA`` (default ``.bench_data/tpch-sf1``),
``SWEEP_QUERIES`` (default all 22), ``SWEEP_LEGS`` (first leg is the
bit-identity baseline), ``SWEEP_OUT`` (artifact path).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(REPO, ".bench_data", "tpch-sf1"))
OUT = os.environ.get("SWEEP_OUT", os.path.join(REPO, "TRANSPORT_SWEEP.json"))

LEGS = {
    "mmap": {},
    "wire": {"ballista.shuffle.local.host_match": "false"},
    "legacy": {"ballista.shuffle.local.host_match": "false",
               "ballista.shuffle.wire.streaming": "false"},
}


def _run_leg(leg: str, overrides: dict, queries, artifact: dict):
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.executor.server import ExecutorServer
    from arrow_ballista_tpu.net import dataplane as dp
    from arrow_ballista_tpu.scheduler.netservice import SchedulerNetService
    from arrow_ballista_tpu.utils.config import BallistaConfig
    from benchmarks.queries import QUERIES
    from benchmarks.tpch import register_tables

    conf = {
        "ballista.shuffle.partitions": "8",
        "ballista.batch.size": str(1 << 20),
        "ballista.job.timeout.seconds": "1800",
        **overrides,
    }
    tmp = tempfile.mkdtemp(prefix=f"transport-sweep-{leg}-")
    sched = SchedulerNetService("127.0.0.1", 0, config=BallistaConfig(dict(conf)))
    sched.start()
    executors = []
    frames = {}
    s0 = dp.STATS.snapshot()
    try:
        for i in range(2):
            work = os.path.join(tmp, f"exec{i}")
            os.makedirs(work)
            ex = ExecutorServer("127.0.0.1", sched.port, "127.0.0.1", 0,
                                work_dir=work, concurrent_tasks=2,
                                executor_id=f"sweep-{leg}-{i}",
                                config=BallistaConfig(dict(conf)))
            ex.start()
            executors.append(ex)
        ctx = BallistaContext.remote("127.0.0.1", sched.port,
                                     BallistaConfig(dict(conf)))
        try:
            register_tables(ctx, DATA_DIR)
            for q in queries:
                t0 = time.time()
                frames[q] = ctx.sql(QUERIES[q]).to_pandas()
                artifact.setdefault(f"q{q}", {})[f"{leg}_s"] = round(
                    time.time() - t0, 1)
                print(f"[sweep] {leg} q{q}: {time.time()-t0:.1f}s "
                      f"({len(frames[q])} rows)", flush=True)
        finally:
            ctx.shutdown()
    finally:
        for ex in executors:
            ex.stop(notify=False)
        sched.stop()
        shutil.rmtree(tmp, ignore_errors=True)
    s1 = dp.STATS.snapshot()
    artifact[f"{leg}_dataplane"] = {
        "bytes_local_mmap": s1["bytes_fetched"]["local_mmap"]
        - s0["bytes_fetched"]["local_mmap"],
        "bytes_remote": s1["bytes_fetched"]["remote"]
        - s0["bytes_fetched"]["remote"],
        "chunks": s1["chunks"] - s0["chunks"],
        "raw_bytes": s1["raw_bytes"] - s0["raw_bytes"],
        "wire_bytes": s1["wire_bytes"] - s0["wire_bytes"],
    }
    return frames


def main() -> None:
    import pandas as pd

    from benchmarks.queries import QUERIES

    if not os.path.exists(os.path.join(DATA_DIR, "lineitem.parquet")):
        raise SystemExit(f"no data at {DATA_DIR}; run benchmarks.tpch convert")

    queries = sorted(
        int(x) for x in os.environ.get(
            "SWEEP_QUERIES", ",".join(map(str, sorted(QUERIES)))).split(",")
        if x.strip())
    legs = [x for x in os.environ.get(
        "SWEEP_LEGS", "mmap,wire,legacy").split(",") if x.strip()]

    t_all = time.time()
    artifact: dict = {"data": DATA_DIR, "legs": legs}
    baseline_leg = legs[0]
    baseline = _run_leg(baseline_leg, LEGS[baseline_leg], queries, artifact)
    ok = 0
    mismatches = []
    for leg in legs[1:]:
        frames = _run_leg(leg, LEGS[leg], queries, artifact)
        for q in queries:
            entry = artifact.setdefault(f"q{q}", {})
            try:
                # bit-identical: exact dtypes, exact values, exact order
                pd.testing.assert_frame_equal(
                    baseline[q].reset_index(drop=True),
                    frames[q].reset_index(drop=True), check_exact=True)
                entry[f"{leg}_identical"] = True
            except Exception as e:  # noqa: BLE001 — record and continue
                entry[f"{leg}_identical"] = False
                entry[f"{leg}_error"] = str(e)[:500]
                mismatches.append((q, leg))
    for q in queries:
        entry = artifact[f"q{q}"]
        if all(entry.get(f"{leg}_identical") for leg in legs[1:]):
            ok += 1
    artifact["identical"] = ok
    artifact["total"] = len(queries)
    artifact["wall_s"] = round(time.time() - t_all, 1)
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"[sweep] {ok}/{len(queries)} bit-identical across {legs} -> {OUT}",
          flush=True)
    if mismatches:
        raise SystemExit(f"transport mismatch: {mismatches}")


if __name__ == "__main__":
    main()
