"""Query-lifecycle oracle + leak sweep.

Two legs, both against the full distributed (standalone) machinery:

- **oracle**: the TPC-H suite twice — once with no deadline, once under a
  generous server-side deadline no query can hit — every query
  **bit-identical** between the legs and ``jobs_deadline_exceeded_total``
  still zero afterwards.  The guardrail plane promises to be invisible
  until it fires; this sweep is the oracle for that promise.
- **leak**: ``LIFECYCLE_CYCLES`` (default 100) mixed
  cancel / deadline-expiry / poison cycles against ONE standalone
  context, then a residual audit: zero in-flight tasks, zero live cancel
  tokens, every slot reservation returned, no pending tasks, no active
  graphs, no queued or running admission permits, and an empty shuffle
  work-dir tree once the post-terminal cleanup fanout drains.  A
  lifecycle path that leaks one permit per cancel kills a serving fleet
  in an afternoon; 100 cycles makes even a rare leak loud.

    python -m tools.lifecycle_sweep         # writes LIFECYCLE_SWEEP.json

Env knobs: ``BENCH_DATA`` (default ``.bench_data/tpch-sf1``; when the
directory is missing the oracle leg generates SF ``LIFESWEEP_SCALE``
tables in-process instead), ``SWEEP_QUERIES``, ``LIFESWEEP_OUT``,
``LIFESWEEP_SCALE`` (default 0.01), ``LIFECYCLE_CYCLES`` (default 100),
``LIFESWEEP_DEADLINE_S`` (default 600: the generous budget).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA_DIR = os.environ.get(
    "BENCH_DATA", os.path.join(REPO, ".bench_data", "tpch-sf1"))
OUT = os.environ.get(
    "LIFESWEEP_OUT", os.path.join(REPO, "LIFECYCLE_SWEEP.json"))
SCALE = float(os.environ.get("LIFESWEEP_SCALE", "0.01"))
CYCLES = int(os.environ.get("LIFECYCLE_CYCLES", "100"))
DEADLINE_S = float(os.environ.get("LIFESWEEP_DEADLINE_S", "600"))


def _register(ctx):
    from benchmarks.tpch import register_tables

    if os.path.exists(os.path.join(DATA_DIR, "lineitem.parquet")):
        register_tables(ctx, DATA_DIR)
        return DATA_DIR
    from benchmarks.datagen import generate_tables

    for name, table in generate_tables(SCALE, seed=1).items():
        ctx.register_table(name, table)
    return f"generated sf{SCALE}"


def _standalone(overrides: dict):
    from arrow_ballista_tpu.client.context import BallistaContext
    from arrow_ballista_tpu.utils.config import BallistaConfig

    conf = {"ballista.batch.size": str(1 << 20),
            "ballista.shuffle.partitions": "4", **overrides}
    return BallistaContext.standalone(BallistaConfig(conf),
                                      concurrent_tasks=2, num_executors=2)


# --- oracle leg -----------------------------------------------------------

def _run_oracle_leg(leg: str, overrides: dict, queries, artifact: dict):
    from benchmarks.queries import QUERIES

    ctx = _standalone(overrides)
    frames = {}
    try:
        artifact["data"] = _register(ctx)
        for q in queries:
            t0 = time.time()
            frames[q] = ctx.sql(QUERIES[q]).to_pandas()
            artifact.setdefault(f"q{q}", {})[f"{leg}_s"] = round(
                time.time() - t0, 1)
            print(f"[lifesweep] {leg} q{q}: {time.time()-t0:.1f}s "
                  f"({len(frames[q])} rows)", flush=True)
        counters = ctx._standalone.scheduler.metrics.counters_snapshot()
        artifact[f"{leg}_deadline_exceeded"] = counters.get(
            "jobs_deadline_exceeded_total", 0)
    finally:
        ctx.shutdown()
    return frames


def oracle_sweep(artifact: dict) -> None:
    import pandas as pd

    from benchmarks.queries import QUERIES

    queries = sorted(
        int(x) for x in os.environ.get(
            "SWEEP_QUERIES", ",".join(map(str, sorted(QUERIES)))).split(",")
        if x.strip())
    baseline = _run_oracle_leg("plain", {}, queries, artifact)
    armed = _run_oracle_leg(
        "deadline",
        {"ballista.query.deadline.seconds": str(DEADLINE_S)},
        queries, artifact)
    assert artifact["deadline_deadline_exceeded"] == 0, \
        "a generous deadline fired — the reaper is trigger-happy"

    ok, mismatches = 0, []
    for q in queries:
        entry = artifact.setdefault(f"q{q}", {})
        try:
            # bit-identical: exact dtypes, exact values, exact order
            pd.testing.assert_frame_equal(
                baseline[q].reset_index(drop=True),
                armed[q].reset_index(drop=True), check_exact=True)
            entry["identical"] = True
            ok += 1
        except Exception as e:  # noqa: BLE001 — record and continue
            entry["identical"] = False
            entry["error"] = str(e)[:500]
            mismatches.append(q)
    artifact["identical"] = ok
    artifact["total"] = len(queries)
    print(f"[lifesweep] oracle: {ok}/{len(queries)} bit-identical under a "
          f"{DEADLINE_S:.0f}s deadline", flush=True)
    if mismatches:
        raise SystemExit(
            f"deadline-armed leg changed results on queries: {mismatches}")


# --- leak leg -------------------------------------------------------------

LEAK_SQL = "select g, sum(v) as s, count(*) as n from t group by g order by g"


def _residuals(sched, executors, work_dir=None):
    out = []
    if any(ex.active_tasks() for ex in executors):
        out.append("in-flight tasks")
    if any(ex.running_task_ids() for ex in executors):
        out.append("cancel tokens")
    if sched.cluster.total_available() != sched.cluster.total_slots():
        out.append("slot reservations")
    if sched.pending_task_count() != 0:
        out.append("pending tasks")
    if sched.jobs.active_graphs():
        out.append("active graphs")
    snap = sched.admission.snapshot()
    if snap["queued"] or snap["running"]:
        out.append("admission permits")
    if work_dir is not None and os.listdir(work_dir):
        out.append(f"work-dir entries: {sorted(os.listdir(work_dir))[:4]}")
    return out


def leak_sweep(artifact: dict) -> None:
    import numpy as np
    import pyarrow as pa

    from arrow_ballista_tpu import faults
    from arrow_ballista_tpu.utils.errors import ExecutionError

    def stall_plan(delay_ms):
        return faults.FaultPlan.from_obj({"seed": 11, "rules": [{
            "site": "executor.task.slow", "action": "delay",
            "delay_ms": delay_ms, "times": -1,
            "match": {"stage_id": 1}}]})

    def poison_plan():
        return faults.FaultPlan.from_obj({"seed": 3, "rules": [{
            "site": "executor.task.before_run", "action": "raise",
            "error": "io", "message": "poison split: unreadable block",
            "times": -1, "match": {"stage_id": 1, "partition": 0}}]})

    ctx = _standalone({})
    sched = ctx._standalone.scheduler
    executors = ctx._standalone.executors
    work_dir = ctx._standalone.work_dir
    # shrink the post-terminal shuffle-data fanout delay (default 30 s)
    # so the work-dir audit below observes a drained tree, not a queue
    sched.config.job_data_cleanup_delay_s = 0.2
    rng = np.random.default_rng(23)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 7, 4000).astype(np.int64)),
        "v": pa.array(rng.integers(0, 100, 4000).astype(np.int64)),
    }))
    from arrow_ballista_tpu.utils.config import BallistaConfig

    deadline_conf = BallistaConfig({
        "ballista.shuffle.partitions": "4",
        "ballista.query.deadline.seconds": "0.3"})
    counts = {"cancel": 0, "deadline": 0, "poison": 0}
    t_all = time.time()

    def drain(timeout=15.0):
        # injected executor.task.slow sleeps are uninterruptible: a
        # cancelled cycle's tasks outlive their job by up to the delay.
        # Wait them out so the next cycle's "is my task running yet?"
        # probe cannot latch onto a predecessor's stragglers.
        stop = time.monotonic() + timeout
        while any(ex.active_tasks() for ex in executors) \
                and time.monotonic() < stop:
            time.sleep(0.02)

    try:
        for i in range(CYCLES):
            if i % 10 == 9:
                kind = "deadline"
            elif i % 2 == 0:
                kind = "cancel"
            else:
                kind = "poison"
            counts[kind] += 1
            if kind == "cancel":
                drain()
                prev_job = ctx._standalone.last_job_id
                err = {}

                def run():
                    try:
                        ctx.sql(LEAK_SQL).to_pandas()
                        err["out"] = "completed"
                    except ExecutionError as e:
                        err["out"] = str(e)

                with faults.use_plan(stall_plan(1000)):
                    th = threading.Thread(target=run)
                    th.start()
                    stop = time.monotonic() + 10.0
                    while (ctx._standalone.last_job_id == prev_job
                           or not any(ex.active_tasks()
                                      for ex in executors)) \
                            and time.monotonic() < stop:
                        time.sleep(0.01)
                    ctx.cancel()
                    th.join(timeout=20.0)
                assert not th.is_alive(), f"cycle {i}: cancel hung"
                assert "cancelled" in err.get("out", ""), (i, err)
            elif kind == "deadline":
                with faults.use_plan(stall_plan(800)):
                    try:
                        ctx._standalone.execute_sql(
                            LEAK_SQL, ctx.catalog, config=deadline_conf)
                        raise AssertionError(
                            f"cycle {i}: stalled job beat a 0.3s deadline")
                    except ExecutionError as e:
                        assert "DeadlineExceeded" in str(e), (i, e)
            else:
                with faults.use_plan(poison_plan()):
                    try:
                        ctx.sql(LEAK_SQL).to_pandas()
                        raise AssertionError(
                            f"cycle {i}: poison query succeeded")
                    except ExecutionError as e:
                        assert "PoisonQuery" in str(e), (i, e)
            if (i + 1) % 20 == 0:
                print(f"[lifesweep] leak: {i+1}/{CYCLES} cycles "
                      f"({time.time()-t_all:.0f}s)", flush=True)
        # poison cycles must never have charged an executor
        q = sched.quarantine.snapshot()
        assert not q["quarantined"] and q["total_quarantined"] == 0, q
        # the fleet still serves: one healthy query, correct answer
        assert len(ctx.sql(LEAK_SQL).to_pandas()) == 7
        # the residual audit: poll out the post-terminal unwind, then
        # demand the fleet is exactly as empty as a fresh boot
        stop = time.monotonic() + 20.0
        while _residuals(sched, executors, work_dir) \
                and time.monotonic() < stop:
            time.sleep(0.05)
        leaks = _residuals(sched, executors, work_dir)
        assert not leaks, f"leaked after {CYCLES} cycles: {leaks}"
        counters = sched.metrics.counters_snapshot()
        artifact["leak_cycles"] = dict(counts)
        artifact["leak_counters"] = {
            k: counters.get(k, 0)
            for k in ("jobs_deadline_exceeded_total", "jobs_poisoned_total",
                      "job_cancelled_total", "zombie_tasks_reaped_total")}
        artifact["leak_wall_s"] = round(time.time() - t_all, 1)
        print(f"[lifesweep] leak: {CYCLES} cycles {counts} in "
              f"{artifact['leak_wall_s']}s, zero residuals", flush=True)
    finally:
        faults.clear()
        ctx.shutdown()


def main() -> None:
    t_all = time.time()
    artifact: dict = {"cycles": CYCLES, "deadline_s": DEADLINE_S}
    oracle_sweep(artifact)
    leak_sweep(artifact)
    artifact["wall_s"] = round(time.time() - t_all, 1)
    with open(OUT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"[lifesweep] {artifact['identical']}/{artifact['total']} "
          f"bit-identical, {CYCLES} leak cycles clean -> {OUT}", flush=True)


if __name__ == "__main__":
    main()
