#!/usr/bin/env python
"""Metrics-docs consistency check.

Instantiates the scheduler and executor metrics collectors, renders
their prometheus exposition, and asserts every emitted metric family
name (the ``# TYPE <name> <kind>`` lines) appears somewhere in
docs/user-guide/metrics.md.  Run directly (exit 1 on drift) or through
tests/test_observability.py so CI catches undocumented metrics.
"""
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC_PATH = os.path.join(REPO_ROOT, "docs", "user-guide", "metrics.md")


def emitted_metric_names():
    sys.path.insert(0, REPO_ROOT)
    from arrow_ballista_tpu.executor.metrics import ExecutorMetrics
    from arrow_ballista_tpu.scheduler.metrics import InMemoryMetricsCollector

    text = InMemoryMetricsCollector().gather() + ExecutorMetrics().gather()
    return sorted(set(re.findall(r"^# TYPE (\S+) \S+$", text, re.M)))


def missing_from_docs():
    with open(DOC_PATH) as f:
        doc = f.read()
    return [name for name in emitted_metric_names() if name not in doc]


def main() -> int:
    missing = missing_from_docs()
    if missing:
        print("metric names emitted by collectors but absent from "
              f"{os.path.relpath(DOC_PATH, REPO_ROOT)}:")
        for name in missing:
            print(f"  {name}")
        return 1
    print(f"{len(emitted_metric_names())} metric names all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
