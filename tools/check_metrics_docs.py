#!/usr/bin/env python
"""Metrics-docs consistency check — thin shim.

The check itself now lives in the static-analysis framework as the
``metrics-docs`` rule (arrow_ballista_tpu/analysis/rules.py); run the full
suite with ``python -m arrow_ballista_tpu.analysis``.  This script remains
for existing invocations and runs just that rule.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def emitted_metric_names():
    sys.path.insert(0, REPO_ROOT)
    from arrow_ballista_tpu.analysis.rules import MetricsDocsRule

    return MetricsDocsRule().emitted_metric_names()


def missing_from_docs():
    sys.path.insert(0, REPO_ROOT)
    from arrow_ballista_tpu.analysis import run_lints

    return [v.message for v in run_lints(REPO_ROOT, rule_names=["metrics-docs"])]


def main() -> int:
    missing = missing_from_docs()
    if missing:
        for msg in missing:
            print(msg)
        return 1
    print(f"{len(emitted_metric_names())} metric names all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
