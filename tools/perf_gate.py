#!/usr/bin/env python
"""Per-query perf regression gate over the driver's ``BENCH_r*.json`` rounds.

Each round file records one bench run: ``{"n": <round>, "cmd", "rc", "tail",
"parsed": {...}}`` where ``parsed`` carries the headline metric
(``value``/``unit``/``vs_baseline``) plus nested per-suite timing dicts
(``engine``, ``engine_mesh``, ``engine_sf10``, ``cpu.engine``, ...) whose
``q<N>_ms`` keys are per-query wall times.

The gate compares the newest round against the previous one, per query:

* wall-time metric (``*_ms``):      regression when new > old * (1 + tol)
* throughput metric (``rows/s``):   regression when new < old * (1 - tol)

It is **warn-only by default** (always exits 0) because container bench
numbers are noisy; ``--strict`` turns regressions into a nonzero exit for
environments with stable hardware.  Two classes of delta are *advisory*
(reported, never gated) even under ``--strict``, because they are noise
statistics on shared hardware: quantile-tail metrics (``*_p9x_*`` — a p99
over a few hundred smoke queries is a one-or-two-sample value) and
wall-time regressions below the absolute floor (``--min-delta-ms``,
default 10 ms — scheduler jitter dominates millisecond-scale micro
measurements).  ``--json`` emits the machine-readable report instead of
text.

Usage::

    python tools/perf_gate.py [--dir .] [--tolerance 0.25] [--json] [--strict]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")

# parsed[...] scalar keys that are environment probes, not workload results
_NON_METRIC = {
    "platform_rtt_ms",  # RTT probe of the accelerator link, not a query
}

# quantile-tail metrics (p90/p95/p99 keys): a p99 over a few hundred smoke
# queries is a one-or-two-sample statistic on shared hardware — compared
# and REPORTED, but advisory: they never flip the verdict on their own
_ADVISORY_RE = re.compile(r"_p9\d($|_)")

# absolute noise floor for wall-time metrics: a ratio-only gate misfires on
# millisecond-scale micro measurements (queue latencies, per-read transport
# deltas) where scheduler jitter dominates — an ms regression must also
# exceed this many ms of absolute delta to gate; below it, advisory
_DEFAULT_MIN_DELTA_MS = 10.0


def find_rounds(directory: str) -> List[Tuple[int, str]]:
    """All ``BENCH_r<NN>.json`` files in *directory*, sorted by round."""
    out = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


def extract_metrics(parsed: dict) -> Dict[str, Tuple[float, str]]:
    """Flatten a round's ``parsed`` dict into ``{name: (value, kind)}``.

    ``kind`` is ``"ms"`` (lower is better) or ``"rows_per_sec"`` (higher is
    better).  Nested suite dicts contribute dotted names (``engine.q1_ms``);
    non-timing sub-structures (stage breakdowns, AQE event lists) are skipped.
    """
    metrics: Dict[str, Tuple[float, str]] = {}

    def visit(prefix: str, obj) -> None:
        if not isinstance(obj, dict):
            return
        for key, val in obj.items():
            name = f"{prefix}{key}"
            if isinstance(val, dict):
                visit(f"{name}.", val)
            elif isinstance(val, (int, float)) and not isinstance(val, bool):
                if key in _NON_METRIC:
                    continue
                if key.endswith("_ms"):
                    metrics[name] = (float(val), "ms")
                elif key.endswith("rows_per_sec"):
                    metrics[name] = (float(val), "rows_per_sec")

    visit("", parsed)
    # Headline metric: named by parsed["metric"], throughput-valued.
    value = parsed.get("value")
    if isinstance(value, (int, float)) and parsed.get("unit") == "rows/s":
        metrics[parsed.get("metric", "headline")] = (float(value),
                                                     "rows_per_sec")
    return metrics


def _load_round(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def compare(old: Dict[str, Tuple[float, str]],
            new: Dict[str, Tuple[float, str]],
            tolerance: float,
            min_delta_ms: float = _DEFAULT_MIN_DELTA_MS) -> dict:
    """Per-metric comparison; only metrics present in both rounds gate."""
    regressions, advisory, improvements, stable = [], [], [], []
    for name in sorted(set(old) & set(new)):
        old_v, kind = old[name]
        new_v, _ = new[name]
        if old_v <= 0:
            continue
        ratio = new_v / old_v
        entry = {"metric": name, "kind": kind, "old": old_v, "new": new_v,
                 "ratio": round(ratio, 4)}
        if kind == "ms":
            regressed = new_v > old_v * (1.0 + tolerance)
            improved = new_v < old_v * (1.0 - tolerance)
        else:  # rows_per_sec: higher is better
            regressed = new_v < old_v * (1.0 - tolerance)
            improved = new_v > old_v * (1.0 + tolerance)
        below_floor = kind == "ms" and (new_v - old_v) < min_delta_ms
        if regressed and (_ADVISORY_RE.search(name) or below_floor):
            advisory.append(entry)
        else:
            (regressions if regressed else
             improvements if improved else stable).append(entry)
    return {"regressions": regressions, "advisory_regressions": advisory,
            "improvements": improvements, "stable": stable,
            "compared": (len(regressions) + len(advisory)
                         + len(improvements) + len(stable)),
            "only_old": sorted(set(old) - set(new)),
            "only_new": sorted(set(new) - set(old))}


def build_report(directory: str, tolerance: float,
                 min_delta_ms: float = _DEFAULT_MIN_DELTA_MS) -> dict:
    rounds = find_rounds(directory)
    report = {"tolerance": tolerance, "status": "ok", "rounds": len(rounds)}
    if len(rounds) < 2:
        report["status"] = "skipped"
        report["reason"] = (f"need >= 2 BENCH_r*.json rounds, "
                            f"found {len(rounds)}")
        return report
    new_n, new_path = rounds[-1]
    new_doc = _load_round(new_path)
    if new_doc is None:
        report["status"] = "skipped"
        report["reason"] = f"unreadable round file: {new_path}"
        return report
    if new_doc.get("rc") not in (0, None):
        report["status"] = "skipped"
        report["reason"] = (f"newest round r{new_n} exited "
                            f"rc={new_doc.get('rc')}; not comparable")
        return report
    # Baseline: the most recent *clean* prior round (timed-out or crashed
    # rounds produce partial/absent parsed metrics and would gate on noise).
    old_n = old_doc = None
    for cand_n, cand_path in reversed(rounds[:-1]):
        doc = _load_round(cand_path)
        if doc is not None and doc.get("rc") in (0, None):
            old_n, old_doc = cand_n, doc
            break
    if old_doc is None:
        report["status"] = "skipped"
        report["reason"] = "no clean (rc=0) prior round to compare against"
        return report
    report["old_round"], report["new_round"] = old_n, new_n
    cmp = compare(extract_metrics(old_doc.get("parsed") or {}),
                  extract_metrics(new_doc.get("parsed") or {}),
                  tolerance, min_delta_ms)
    report.update(cmp)
    if not cmp["compared"]:
        report["status"] = "skipped"
        report["reason"] = "no metric present in both rounds"
    elif cmp["regressions"]:
        report["status"] = "regressed"
    return report


def render(report: dict) -> str:
    lines = [f"perf gate: tolerance ±{report['tolerance'] * 100:.0f}%"]
    if report["status"] == "skipped":
        lines.append(f"  skipped: {report['reason']}")
        return "\n".join(lines)
    lines[0] += (f", r{report['old_round']:02d} -> r{report['new_round']:02d}"
                 f" ({report['compared']} comparable metrics)")

    def fmt(e):
        unit = "ms" if e["kind"] == "ms" else "rows/s"
        return (f"  {e['metric']}: {e['old']:.1f} -> {e['new']:.1f} {unit} "
                f"({e['ratio']:.2f}x)")

    if report["regressions"]:
        lines.append(f"REGRESSIONS ({len(report['regressions'])}):")
        lines.extend(fmt(e) for e in report["regressions"])
    if report.get("advisory_regressions"):
        lines.append(f"advisory (tail metric or below the absolute floor; "
                     f"not gated) ({len(report['advisory_regressions'])}):")
        lines.extend(fmt(e) for e in report["advisory_regressions"])
    if report["improvements"]:
        lines.append(f"improvements ({len(report['improvements'])}):")
        lines.extend(fmt(e) for e in report["improvements"])
    lines.append(f"stable: {len(report['stable'])}")
    if report["only_new"]:
        lines.append(f"new-only metrics (not gated): "
                     f"{', '.join(report['only_new'])}")
    if report["only_old"]:
        lines.append(f"dropped metrics: {', '.join(report['only_old'])}")
    lines.append(f"verdict: {report['status']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json round files")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="relative slack before a delta counts as a "
                         "regression (default 0.25)")
    ap.add_argument("--min-delta-ms", type=float,
                    default=_DEFAULT_MIN_DELTA_MS,
                    help="absolute floor for wall-time regressions: an *_ms "
                         "metric must also slow down by at least this many "
                         "ms to gate (default 10.0); smaller deltas are "
                         "reported as advisory")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON report instead of text")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on regressions (default: warn only)")
    args = ap.parse_args(argv)

    report = build_report(args.dir, args.tolerance, args.min_delta_ms)
    print(json.dumps(report, indent=2) if args.json else render(report))
    if args.strict and report["status"] == "regressed":
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
