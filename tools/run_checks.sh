#!/usr/bin/env bash
# CI entrypoint for the repository's consistency checks:
#   1. the static-analysis lint suite (AST rules + metrics-docs),
#   2. generated-docs freshness (docs/user-guide/configs.md),
#   3. the static-analysis + concurrency + wire-serde + speculation +
#      observability + adaptive-execution test files (rule fixtures,
#      plan-validator cases, seeded-interleaving stress + lock-order shim
#      units, exhaustive wire round-trips, speculation policy math and
#      attempt-dedup races, runtime-stats folding / EXPLAIN ANALYZE /
#      cluster history, device observatory: jit compile/retrace
#      accounting, transfer bytes, watermarks, fusion advisor,
#      AQE rewrites + rollback + serde),
#   4. the chaos recovery suite (deterministic fault injection: seeded
#      failpoint plans, kill/fetch-failure/drop/restart scenarios,
#      quarantine, straggler speculation, corrupt-shuffle checksums) plus
#      the scheduler-fleet HA suite (tests/test_fleet.py: shard killed
#      mid-job and adopted by a sibling, lease fencing under partition,
#      adoption/completion races, real-process SIGKILL failover) —
#      proves the fault-tolerance paths still recover.  Runs with the
#      runtime lock-order validator on (BALLISTA_LOCK_ORDER_RUNTIME=1):
#      every real lock acquisition is checked against the static
#      concurrency model, and any inversion or unpredicted nesting fails
#      the leg,
#   5. the serving smoke (benchmarks/serving.py --smoke): 8 concurrent
#      sessions of repeated q6 variants through the prepared-plan +
#      result caches — zero errors and a nonzero plan-cache hit rate,
#      also under the runtime lock-order validator,
#   6. the fleet serving smoke (--smoke --shards 2): the same workload
#      against a 2-shard scheduler fleet behind a shared KV, then a
#      failover leg that crash-kills shard 0 mid-run — both legs must
#      complete every query with zero errors,
#   7. the perf gate (tools/perf_gate.py): newest BENCH_r*.json round vs
#      the previous clean round, per-query wall time and throughput —
#      warn-only here because container bench numbers are noisy.
# tests/test_static_analysis.py also runs the lint suite inside tier-1, so
# pytest alone still gates new violations; this script is the fast
# standalone form for CI and pre-push hooks.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== static analysis (lint suite) =="
python -m arrow_ballista_tpu.analysis

echo "== generated docs up to date =="
python docs/gen_configs.py --check

echo "== analysis + concurrency + serde + speculation + observability + aqe test files =="
python -m pytest tests/test_static_analysis.py tests/test_concurrency.py \
    tests/test_serde_wire.py tests/test_speculation.py \
    tests/test_observatory.py tests/test_device_obs.py tests/test_aqe.py \
    -q -p no:cacheprovider

echo "== chaos recovery + fleet HA suites (-m chaos, runtime lock-order validation on) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 \
    python -m pytest tests/test_chaos.py tests/test_fleet.py \
    -q -m chaos -p no:cacheprovider

echo "== serving smoke (8 sessions x q6, caches on, runtime lock-order validation on) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 python -m benchmarks.serving --smoke

echo "== fleet serving smoke (2 shards + mid-run shard-kill failover) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 python -m benchmarks.serving --smoke --shards 2

echo "== perf gate (warn-only: bench rounds vs previous clean round) =="
# Container bench numbers are noisy; the gate reports per-query regressions
# but never fails CI here.  Use --strict on stable hardware.
python tools/perf_gate.py || echo "perf gate: reporting failed (non-fatal)"

echo "all checks passed"
