#!/usr/bin/env bash
# CI entrypoint for the repository's consistency checks:
#   1. the static-analysis lint suite (AST rules + metrics-docs),
#   2. generated-docs freshness (docs/user-guide/configs.md),
#   3. the static-analysis + concurrency + wire-serde + speculation +
#      observability + adaptive-execution + doctor test files (rule
#      fixtures, plan-validator cases, seeded-interleaving stress +
#      lock-order shim units, exhaustive wire round-trips, speculation
#      policy math and attempt-dedup races, runtime-stats folding /
#      EXPLAIN ANALYZE / cluster history, device observatory: jit
#      compile/retrace accounting, transfer bytes, watermarks, fusion
#      advisor, AQE rewrites + rollback + serde, flight-recorder journal
#      + forensics bundles + seeded-pathology diagnosis, whole-stage
#      compiler: chain detection, allowlist verdicts, fused-vs-interpreted
#      equality, fusion serde + rollback/speculation/chaos interplay,
#      live observability: watch-stream ordering/gap semantics, the
#      progress/ETA estimator, in-flight doctor alerts, SLO burn rates,
#      query-lifecycle guardrails: server-side deadlines, cooperative
#      cancel tokens + the public cancel surface, poison-query
#      containment with quarantine refund, retry anti-affinity,
#      zombie-task reconciliation, the janitor live-job guard),
#   4. the chaos recovery suite (deterministic fault injection: seeded
#      failpoint plans, kill/fetch-failure/drop/restart scenarios,
#      quarantine, straggler speculation, corrupt-shuffle checksums,
#      lifecycle guardrails under chaos: deadline expiry mid-stage,
#      lost cancel fanout reaped by heartbeat, poison containment) plus
#      the scheduler-fleet HA suite (tests/test_fleet.py: shard killed
#      mid-job and adopted by a sibling, lease fencing under partition,
#      adoption/completion races, real-process SIGKILL failover) —
#      proves the fault-tolerance paths still recover.  Runs with the
#      runtime lock-order validator on (BALLISTA_LOCK_ORDER_RUNTIME=1):
#      every real lock acquisition is checked against the static
#      concurrency model, and any inversion or unpredicted nesting fails
#      the leg,
#   5. the memory-governor oracle sweep (tools/memory_sweep.py): the
#      TPC-H suite twice — unlimited memory vs a budget tiny enough that
#      the governor denies every join-build and aggregation-state
#      reservation — every query bit-identical between the legs, spills
#      proven to have happened, zero reservation leaks,
#   5b. the query-lifecycle sweep (tools/lifecycle_sweep.py): the TPC-H
#      suite with a generous server-side deadline vs none — bit-identical
#      and the deadline reaper never fires — then 100 mixed
#      cancel/deadline-expiry/poison cycles against one standalone
#      context with a residual audit at the end: zero in-flight tasks,
#      cancel tokens, slot reservations, pending tasks, active graphs,
#      or admission permits, and no executor quarantined by poison,
#   6. the doctor smoke: one standalone query with the flight recorder
#      on — the forensics bundle must validate against the
#      ballista.forensics/v1 schema, carry a complete journal timeline,
#      and the query doctor must return zero findings on the healthy
#      run,
#   7. the live-obs smoke: one standalone query with the live plane on,
#      then watched via ctx.watch() — at least one progress frame with a
#      monotonically non-decreasing fraction, a terminal frame, and zero
#      journal drops,
#   8. the serving smoke (benchmarks/serving.py --smoke): 8 concurrent
#      sessions of repeated q6 variants through the prepared-plan +
#      result caches — zero errors and a nonzero plan-cache hit rate,
#      also under the runtime lock-order validator,
#   9. the fleet serving smoke (--smoke --shards 2): the same workload
#      against a 2-shard scheduler fleet behind a shared KV, then a
#      failover leg that crash-kills shard 0 mid-run — both legs must
#      complete every query with zero errors,
#  10. the perf gate (tools/perf_gate.py): newest BENCH_r*.json round vs
#      the previous clean round, per-query wall time and throughput —
#      STRICT since PR 17: regressions past the tolerance fail; override
#      with BALLISTA_PERF_TOLERANCE on noisy hardware.
# tests/test_static_analysis.py also runs the lint suite inside tier-1, so
# pytest alone still gates new violations; this script is the fast
# standalone form for CI and pre-push hooks.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== static analysis (lint suite) =="
python -m arrow_ballista_tpu.analysis
# SARIF artifact for CI inline annotation (same findings, machine form;
# the gating text run above already decided the exit status)
python -m arrow_ballista_tpu.analysis --sarif > analysis.sarif || true

echo "== generated docs up to date =="
python docs/gen_configs.py --check

echo "== analysis + concurrency + serde + speculation + observability + aqe + compile + live-obs + lifecycle test files =="
python -m pytest tests/test_static_analysis.py tests/test_concurrency.py \
    tests/test_serde_wire.py tests/test_speculation.py \
    tests/test_observatory.py tests/test_device_obs.py tests/test_aqe.py \
    tests/test_doctor.py tests/test_compile.py tests/test_live_obs.py \
    tests/test_lifecycle.py tests/test_cancellation.py \
    -q -p no:cacheprovider -m 'not chaos'

echo "== chaos recovery + fleet HA suites (-m chaos, runtime lock-order validation on) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 \
    python -m pytest tests/test_chaos.py tests/test_fleet.py \
    tests/test_doctor.py tests/test_compile.py tests/test_live_obs.py \
    -q -m chaos -p no:cacheprovider

echo "== memory-governor oracle sweep (tiny budget: every join/agg spills, bit-identical) =="
python -m tools.memory_sweep

echo "== query-lifecycle sweep (deadline oracle bit-identical + 100-cycle leak audit) =="
python -m tools.lifecycle_sweep

echo "== doctor smoke (flight recorder on: bundle validates, clean run diagnoses clean) =="
python - <<'EOF'
import json

import numpy as np
import pyarrow as pa

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.obs import journal
from arrow_ballista_tpu.obs.doctor import diagnose, validate_bundle
from arrow_ballista_tpu.utils.config import BallistaConfig

ctx = BallistaContext.standalone(
    BallistaConfig({"ballista.journal.enabled": "true",
                    "ballista.shuffle.partitions": "4"}),
    concurrent_tasks=2, num_executors=2)
try:
    rng = np.random.default_rng(7)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 7, 4000), type=pa.int64()),
        "v": pa.array(rng.integers(0, 100, 4000), type=pa.int64())}))
    ctx.sql("select g, sum(v) as s from t group by g order by g").collect()
    bundle = ctx.forensics()
    problems = validate_bundle(bundle)
    assert not problems, f"forensics bundle invalid: {problems}"
    kinds = [e["kind"] for e in bundle["journal"]]
    assert "job.submitted" in kinds and "job.successful" in kinds, kinds
    json.dumps(bundle)  # the bundle is a self-contained JSON artifact
    diag = diagnose(bundle)
    assert not diag["findings"], \
        f"doctor found pathologies on a clean run: {diag['text']}"
    emitted, dropped = journal.counters()
    assert emitted > 0 and dropped == 0, (emitted, dropped)
    print(f"doctor smoke ok: {len(bundle['journal'])} journal events, "
          f"{len(diag['rules_evaluated'])} rules evaluated clean")
finally:
    ctx.shutdown()
EOF

echo "== live-obs smoke (watch a real query: progress frames, terminal frame, zero drops) =="
python - <<'EOF'
import numpy as np
import pyarrow as pa

from arrow_ballista_tpu.client.context import BallistaContext
from arrow_ballista_tpu.obs import journal
from arrow_ballista_tpu.utils.config import BallistaConfig

ctx = BallistaContext.standalone(
    BallistaConfig({"ballista.journal.enabled": "true",
                    "ballista.live.enabled": "true",
                    "ballista.live.doctor.interval.seconds": "0.5",
                    "ballista.shuffle.partitions": "4"}),
    concurrent_tasks=2, num_executors=2)
try:
    rng = np.random.default_rng(17)
    ctx.register_table("t", pa.table({
        "g": pa.array(rng.integers(0, 7, 4000), type=pa.int64()),
        "v": pa.array(rng.integers(0, 100, 4000), type=pa.int64())}))
    ctx.sql("select g, sum(v) as s from t group by g order by g").collect()
    frames = list(ctx.watch())
    kinds = [f["t"] for f in frames]
    assert kinds.count("progress") >= 1, kinds
    assert kinds[-1] == "end" and frames[-1]["state"] == "successful", \
        frames[-1]
    fracs = [f["progress"]["fraction"] for f in frames
             if f["t"] == "progress"]
    assert all(a <= b for a, b in zip(fracs, fracs[1:])), fracs
    emitted, dropped = journal.counters()
    assert emitted > 0 and dropped == 0, (emitted, dropped)
    assert journal.watcher_count() == 0  # the stream detached cleanly
    print(f"live-obs smoke ok: {kinds.count('event')} event frames, "
          f"{kinds.count('progress')} progress frames, final fraction "
          f"{fracs[-1] if fracs else 'n/a'}, 0 journal drops")
finally:
    ctx.shutdown()
EOF

echo "== serving smoke (8 sessions x q6, caches on, runtime lock-order validation on) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 python -m benchmarks.serving --smoke

echo "== fleet serving smoke (2 shards + mid-run shard-kill failover) =="
BALLISTA_LOCK_ORDER_RUNTIME=1 python -m benchmarks.serving --smoke --shards 2

echo "== perf gate (strict: newest bench round vs previous clean round) =="
# Strict since PR 17: a regression past the tolerance fails CI.  Container
# bench numbers are noisy, so the tolerance is generous by default and
# overridable per-host (BALLISTA_PERF_TOLERANCE=0.60 tools/run_checks.sh);
# p9x tails and sub-10ms wall-time deltas are advisory-only (see the gate's
# module docstring).
python tools/perf_gate.py --strict \
    --tolerance "${BALLISTA_PERF_TOLERANCE:-0.40}"

echo "all checks passed"
