# Shared base image: python + jax (TPU wheels picked at build time) + the
# framework package.  Role parity: reference dev/docker/ballista-builder +
# per-binary Dockerfiles (dev/docker/*.Dockerfile).
FROM python:3.12-slim

ARG JAX_EXTRA=tpu
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make netcat-openbsd && rm -rf /var/lib/apt/lists/*
RUN pip install --no-cache-dir "jax[${JAX_EXTRA}]" pyarrow pandas fsspec

WORKDIR /opt/ballista-tpu
COPY arrow_ballista_tpu ./arrow_ballista_tpu
COPY benchmarks ./benchmarks
COPY native ./native
RUN make -C native 2>/dev/null || true  # native data plane is optional
ENV PYTHONPATH=/opt/ballista-tpu
