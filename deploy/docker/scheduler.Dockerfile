# Scheduler daemon (reference dev/docker/ballista-scheduler.Dockerfile).
# Build from the repo root:
#   docker build -f deploy/docker/base.Dockerfile -t ballista-tpu-base .
#   docker build -f deploy/docker/scheduler.Dockerfile -t ballista-tpu-scheduler .
FROM ballista-tpu-base

EXPOSE 50050 50051
ENTRYPOINT ["python", "-m", "arrow_ballista_tpu.scheduler_daemon"]
CMD ["--bind-host", "0.0.0.0", "--bind-port", "50050", "--rest-port", "50051"]
