# Executor daemon (reference dev/docker/ballista-executor.Dockerfile).
# Executors bind the TPU: run with the TPU runtime mounted / device plugin
# (e.g. GKE TPU node pools) or JAX_PLATFORMS=cpu for CPU-only pools.
FROM ballista-tpu-base

EXPOSE 50052
ENTRYPOINT ["python", "-m", "arrow_ballista_tpu.executor_daemon"]
CMD ["--bind-host", "0.0.0.0", "--bind-port", "50052", \
     "--scheduler-host", "ballista-scheduler"]
