"""ctypes bindings for the native runtime components under native/.

Builds on demand with g++ (no pybind11 in the image; plain C ABI).  The
native pieces are optional accelerations: every caller falls back to the
Python implementation when the toolchain or the .so is unavailable.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_lock = threading.Lock()
_dataplane_lib: Optional[ctypes.CDLL] = None
_dataplane_failed = False


def _build(so_name: str, source: str) -> Optional[str]:
    so_path = os.path.join(_BUILD_DIR, so_name)
    src_path = os.path.join(_NATIVE_DIR, source)
    if os.path.exists(so_path) and \
            os.path.getmtime(so_path) >= os.path.getmtime(src_path):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-o", so_path,
           src_path, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so_path
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        log.warning("native build failed (%s): %s", so_name,
                    stderr.decode(errors="replace")[-2000:])
        return None


def dataplane() -> Optional[ctypes.CDLL]:
    """The native shuffle data-plane server (native/dataplane.cpp).
    Returns None when unavailable."""
    global _dataplane_lib, _dataplane_failed
    with _lock:
        if _dataplane_lib is not None or _dataplane_failed:
            return _dataplane_lib
        so = _build("libdataplane.so", "dataplane.cpp")
        if so is None:
            _dataplane_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.dp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                 ctypes.c_char_p, ctypes.c_int]
        lib.dp_start.restype = ctypes.c_int
        lib.dp_stop.argtypes = []
        lib.dp_stop.restype = None
        lib.dp_bytes_served.argtypes = []
        lib.dp_bytes_served.restype = ctypes.c_uint64
        _dataplane_lib = lib
        return lib
