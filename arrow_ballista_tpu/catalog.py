"""Table providers: named datasets resolvable to schemas and scans.

Parity: the reference registers tables client-side and ships them inside the
logical plan (reference ballista/client/src/context.rs:214-352
``register_csv/parquet/avro`` + CREATE EXTERNAL TABLE handling); providers
here serve both the SQL planner (schemas) and the physical planner (scans,
row-count estimates for broadcast decisions).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from .models import expr as E
from .models.ipc import int64_decimal_storage_scale
from .models.schema import DataType, Field, Schema, decimal
from .sql.planner import Catalog
from .utils.errors import PlanningError


def arrow_schema_to_engine(pa_schema, nullable_by_col=None) -> Schema:
    """``nullable_by_col`` marks fields whose data actually contains NULLs
    (from null statistics) — engine nullability means "carries the in-band
    NULL sentinel", not arrow's everything-nullable default."""
    import pyarrow as pa

    nullable_by_col = nullable_by_col or {}
    fields = []
    for f in pa_schema:
        t = f.type
        meta = f.metadata or {}
        if pa.types.is_dictionary(t):
            t = t.value_type
        if pa.types.is_string(t) or pa.types.is_large_string(t):
            dt = DataType("string")
        elif pa.types.is_date32(t):
            dt = DataType("date32")
        elif pa.types.is_decimal(t):
            dt = decimal(t.scale)
        elif (pa.types.is_int64(t)
              and int64_decimal_storage_scale(f) is not None):
            # int64-stored decimals (unscaled values + metadata scale): the
            # physical-storage convention shared with the engine's shuffle
            # IPC files and the benchmark converter (benchmarks/tpch.py
            # decimal_to_int64_storage)
            dt = decimal(int64_decimal_storage_scale(f))
        elif pa.types.is_int64(t) or pa.types.is_uint64(t):
            dt = DataType("int64")
        elif pa.types.is_integer(t):
            dt = DataType("int32")
        elif pa.types.is_float64(t):
            dt = DataType("float64")
        elif pa.types.is_float32(t):
            dt = DataType("float32")
        elif pa.types.is_boolean(t):
            dt = DataType("bool")
        elif pa.types.is_timestamp(t) or pa.types.is_date64(t):
            dt = DataType("date32")
        else:
            raise PlanningError(f"unsupported arrow type {t} for column {f.name}")
        fields.append(Field(f.name, dt, bool(nullable_by_col.get(f.name, False))))
    return Schema(fields)


def _table_null_stats(table) -> dict:
    return {name: bool(col.null_count)
            for name, col in zip(table.column_names, table.columns)}


class TableProvider:
    name: str
    schema: Schema

    def scan(self, projection: Optional[List[str]], filters: Sequence[E.Expr],
             target_partitions: int):
        raise NotImplementedError

    def row_count(self) -> Optional[int]:
        return None


class MemoryTable(TableProvider):
    def __init__(self, name: str, table, schema: Optional[Schema] = None):
        import pyarrow as pa

        if not isinstance(table, pa.Table):
            table = pa.Table.from_pandas(table)
        self.name = name
        self.table = table
        self.schema = schema or arrow_schema_to_engine(
            table.schema, _table_null_stats(table))

    def scan(self, projection, filters, target_partitions):
        from .ops.physical import MemoryScanExec

        schema = self.schema if projection is None else self.schema.project(projection)
        return MemoryScanExec(schema, self.table, target_partitions, filters)

    def row_count(self):
        return self.table.num_rows


class ParquetTable(TableProvider):
    def __init__(self, name: str, paths, schema: Optional[Schema] = None):
        from .utils import object_store as obs

        self.name = name
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        if schema is None:
            files = []
            for p in self.paths:
                files.extend(obs.list_files(p, (".parquet",)))
            if not files:
                raise PlanningError(f"no parquet files in {self.paths}")
            pf = obs.parquet_file(files[0])
            # nullability from row-group statistics across EVERY file
            # (cheap, metadata-only); columns without stats are
            # conservatively nullable
            nullable: Dict[str, bool] = {}
            for fpath in files:
                meta = obs.parquet_file(fpath).metadata
                for ci in range(meta.num_columns):
                    col_name = meta.schema.column(ci).name
                    if nullable.get(col_name):
                        continue
                    has_nulls = False
                    for rg in range(meta.num_row_groups):
                        st = meta.row_group(rg).column(ci).statistics
                        if st is None or st.null_count is None or st.null_count > 0:
                            has_nulls = True
                            break
                    nullable[col_name] = has_nulls
            schema = arrow_schema_to_engine(pf.schema_arrow, nullable)
        self.schema = schema
        self._rows: Optional[int] = None

    def scan(self, projection, filters, target_partitions):
        from .ops.physical import ParquetScanExec

        schema = self.schema if projection is None else self.schema.project(projection)
        return ParquetScanExec(schema, self.paths, target_partitions, filters,
                               table_schema=self.schema)

    def row_count(self):
        if self._rows is None:
            from .ops.physical import ParquetScanExec

            self._rows = ParquetScanExec(self.schema, self.paths, 1,
                                         table_schema=self.schema).row_count_estimate()
        return self._rows


class CsvTable(TableProvider):
    def __init__(self, name: str, paths, schema: Optional[Schema] = None,
                 delimiter: str = ",", has_header: bool = True):
        self.name = name
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        self.delimiter = delimiter
        self.has_header = has_header
        if schema is None:
            import pyarrow.csv as pacsv

            from .utils import object_store as obs

            samples = obs.list_files(self.paths[0], (".csv", ".tbl"))
            if not samples:
                raise PlanningError(f"no csv files in {self.paths[0]}")
            sample = samples[0]
            with obs.open_input(sample) as fh:
                table = pacsv.read_csv(
                    fh, parse_options=pacsv.ParseOptions(delimiter=delimiter),
                )

            multi = len(self.paths) > 1 or obs.is_dir(self.paths[0])
            if multi:
                # only the first file was sampled; other files may hold
                # NULLs, so be conservative
                nulls = {name: True for name in table.column_names}
            else:
                nulls = _table_null_stats(table)
            schema = arrow_schema_to_engine(table.schema, nulls)
        self.schema = schema

    def scan(self, projection, filters, target_partitions):
        from .ops.physical import CsvScanExec

        schema = self.schema if projection is None else self.schema.project(projection)
        return CsvScanExec(schema, self.paths, target_partitions, filters,
                           table_schema=self.schema, delimiter=self.delimiter,
                           has_header=self.has_header)


class JsonTable(TableProvider):
    """Newline-delimited JSON (reference register_json, context.rs:358-530)."""

    def __init__(self, name: str, paths, schema: Optional[Schema] = None):
        from .utils import object_store as obs

        self.name = name
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        if schema is None:
            import pyarrow.json as pajson

            files = obs.list_files(self.paths[0], (".json", ".jsonl", ".ndjson"))
            if not files:
                raise PlanningError(f"no json files in {self.paths[0]}")
            with obs.open_input(files[0]) as fh:
                sample = pajson.read_json(fh)
            multi = len(files) > 1 or len(self.paths) > 1
            nulls = ({n: True for n in sample.column_names} if multi
                     else _table_null_stats(sample))
            schema = arrow_schema_to_engine(sample.schema, nulls)
        self.schema = schema

    def scan(self, projection, filters, target_partitions):
        from .ops.physical import JsonScanExec

        schema = self.schema if projection is None else self.schema.project(projection)
        return JsonScanExec(schema, self.paths, target_partitions, filters,
                            table_schema=self.schema)


class AvroTable(TableProvider):
    """Avro object container files (reference register_avro; codec in
    utils/avro.py since no avro library ships in this image)."""

    def __init__(self, name: str, paths, schema: Optional[Schema] = None):
        from .utils import object_store as obs

        self.name = name
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        if schema is None:
            from .utils.avro import avro_arrow_schema, read_avro_schema

            files = obs.list_files(self.paths[0], (".avro",))
            if not files:
                raise PlanningError(f"no avro files in {self.paths[0]}")
            # header-only: the writer schema (and union nullability) lives
            # in the container metadata — never decode the file to infer
            with obs.open_input(files[0]) as fh:
                avro_schema = read_avro_schema(fh)
            pa_schema, nulls = avro_arrow_schema(avro_schema)
            schema = arrow_schema_to_engine(pa_schema, nulls)
        self.schema = schema

    def scan(self, projection, filters, target_partitions):
        from .ops.physical import AvroScanExec

        schema = self.schema if projection is None else self.schema.project(projection)
        return AvroScanExec(schema, self.paths, target_partitions, filters,
                            table_schema=self.schema)


class SchemaCatalog(Catalog):
    """Mutable in-memory catalog of providers (per session)."""

    def __init__(self):
        self.tables: Dict[str, TableProvider] = {}

    def register(self, provider: TableProvider):
        self.tables[provider.name] = provider

    def deregister(self, name: str):
        self.tables.pop(name, None)

    def table_schema(self, name: str) -> Schema:
        p = self.tables.get(name)
        if p is None:
            raise PlanningError(f"table not found: {name}")
        return p.schema

    def table_names(self):
        return sorted(self.tables)

    def provider(self, name: str) -> TableProvider:
        p = self.tables.get(name)
        if p is None:
            raise PlanningError(f"table not found: {name}")
        return p
