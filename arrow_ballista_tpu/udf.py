"""Scalar UDF plugin system: user functions feeding the expression compiler.

Parity: the reference loads UDF plugins from shared objects at startup and
registers them into every session's function registry
(reference ballista/core/src/plugin/mod.rs + plugin/udf.rs + the
`plugin_dir` config key).  The Python-native analog:

- ``register_udf`` puts a :class:`ScalarUdf` in the process-global registry
  (the analog of ``GlobalPluginManager``);
- ``load_plugin_dir(path)`` imports every ``*.py`` file in a directory —
  plugin modules call ``register_udf`` at import time, exactly like the
  reference's ``dlopen`` + ``declare_plugin!`` handshake;
- entry-point discovery (``arrow_ballista_tpu.udfs`` group) covers
  pip-installed plugin packages.

UDFs evaluate on device: ``fn`` receives one jnp (or numpy, host mode)
array per argument and must return an array of the declared return dtype —
a pure elementwise/vectorized function, which is what XLA can fuse into the
surrounding stage program.  Both scheduler and executors resolve UDFs by
NAME from their local registry, so plugin code must be installed on every
node (true in the reference too — every node loads the same plugin dir).
"""
from __future__ import annotations

import dataclasses
import logging
import os
from typing import Callable, Dict, List, Optional, Sequence, Union

from .models.schema import DataType
from .utils.errors import PlanningError

log = logging.getLogger(__name__)

ReturnType = Union[DataType, Callable[[Sequence[DataType]], DataType]]


@dataclasses.dataclass(frozen=True)
class ScalarUdf:
    name: str
    fn: Callable  # (*arrays) -> array, vectorized & jit-traceable
    return_type: ReturnType
    arg_count: Optional[int] = None  # None = variadic
    doc: str = ""

    def result_dtype(self, arg_dtypes: Sequence[DataType]) -> DataType:
        if callable(self.return_type):
            return self.return_type(arg_dtypes)
        return self.return_type


class UdfRegistry:
    def __init__(self):
        self._udfs: Dict[str, ScalarUdf] = {}
        # bumped on every (de)registration: compiled closures bake udf.fn,
        # so the cross-job program cache keys on this generation — a
        # replaced UDF must never be served from a stale cached program
        self.generation = 0

    def register(self, udf: ScalarUdf) -> None:
        key = udf.name.lower()
        if key in self._udfs:
            log.info("replacing UDF %s", key)
        self._udfs[key] = udf
        self.generation += 1

    def get(self, name: str) -> Optional[ScalarUdf]:
        return self._udfs.get(name.lower())

    def names(self) -> List[str]:
        return sorted(self._udfs)

    def deregister(self, name: str) -> None:
        self._udfs.pop(name.lower(), None)
        self.generation += 1


# process-global registry (reference GlobalPluginManager singleton)
GLOBAL_UDFS = UdfRegistry()


def register_udf(name: str, fn: Callable, return_type: ReturnType,
                 arg_count: Optional[int] = None, doc: str = "") -> ScalarUdf:
    udf = ScalarUdf(name, fn, return_type, arg_count, doc)
    GLOBAL_UDFS.register(udf)
    return udf


def load_plugin_dir(path: str) -> List[str]:
    """Import every ``*.py`` in ``path``; modules register UDFs at import
    (reference plugin_manager walking plugin_dir for .so files).  Returns
    the module names loaded."""
    import importlib.util

    loaded = []
    if not os.path.isdir(path):
        raise PlanningError(f"plugin dir not found: {path}")
    for fname in sorted(os.listdir(path)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        mod_name = f"ballista_udf_plugin_{fname[:-3]}"
        spec = importlib.util.spec_from_file_location(
            mod_name, os.path.join(path, fname))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        loaded.append(mod_name)
        log.info("loaded UDF plugin %s", fname)
    return loaded


def load_entry_points() -> List[str]:
    """Discover pip-installed plugins via the ``arrow_ballista_tpu.udfs``
    entry-point group (each entry point is a callable invoked with the
    global registry)."""
    loaded = []
    try:
        from importlib.metadata import entry_points

        for ep in entry_points(group="arrow_ballista_tpu.udfs"):
            try:
                ep.load()(GLOBAL_UDFS)
                loaded.append(ep.name)
            except Exception:  # noqa: BLE001 — a bad plugin must not kill boot
                log.exception("UDF entry point %s failed", ep.name)
    except Exception:  # noqa: BLE001 — metadata API unavailable
        pass
    return loaded
