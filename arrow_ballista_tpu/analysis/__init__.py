"""Static analysis: AST lint suite + pre-launch plan sanity validation.

Run the lint suite with ``python -m arrow_ballista_tpu.analysis``; the plan
validator (``plan_checks.validate_graph``) runs automatically on every
``ExecutionGraph`` before task launch when ``ballista.analysis.plan_checks``
is on (the default).  See docs/developer-guide/static-analysis.md.
"""
from .framework import (
    Project,
    Rule,
    SourceFile,
    Violation,
    all_rules,
    json_report,
    register,
    run_lints,
    sarif_report,
    text_report,
)
from .plan_checks import (
    check_graph,
    check_rewritten_stage,
    validate_graph,
    validate_rewrite,
)

__all__ = [
    "Project",
    "Rule",
    "SourceFile",
    "Violation",
    "all_rules",
    "check_graph",
    "check_rewritten_stage",
    "json_report",
    "register",
    "run_lints",
    "sarif_report",
    "text_report",
    "validate_graph",
    "validate_rewrite",
]
