"""Pre-launch physical-plan sanity validation.

DataFusion runs ``SanityCheckPlan`` after physical optimization to reject
plans whose invariants the optimizer silently broke; this is the
distributed-stage analog, run on every ``ExecutionGraph`` before the first
task launches (gated by ``ballista.analysis.plan_checks``, default on).
Catching a writer/reader partition mismatch here costs microseconds; the
same bug at runtime surfaces as a fetch failure on some reducer minutes in,
after a full map-stage of wasted work.

Checks:

- stage DAG sanity: producers exist, no cycles, no orphan stages
  (unreachable from the final stage);
- shuffle boundaries: every ``UnresolvedShuffleExec`` agrees with its
  producer's ``ShuffleWriterExec`` on output partition count and schema;
- repartitioned joins: both build/probe shuffle inputs hash-partitioned
  with the same bucket count and key arity (a disagreement means rows with
  equal keys land in different buckets — wrong answers, not a crash);
- pass-through operators (filter/sort/limit/coalesce/shuffle-write) carry
  exactly their child's schema.

All failures are collected, then raised together as ``PlanValidationError``.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..ops.operators import (
    CoalescePartitionsExec,
    FilterExec,
    JoinExec,
    LimitExec,
    SortExec,
)
from ..ops.physical import ExecutionPlan, Partitioning
from ..ops.shuffle import (
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
)
from ..utils.errors import PlanValidationError

PASS_THROUGH = (FilterExec, SortExec, LimitExec, CoalescePartitionsExec,
                ShuffleWriterExec)


def _writer_output_count(writer: ShuffleWriterExec) -> int:
    part = writer.partitioning
    return part.count if part is not None else 1


def _writer_partitioning(writer: ShuffleWriterExec) -> Optional[Partitioning]:
    return writer.partitioning


def _walk(plan: ExecutionPlan):
    yield plan
    for child in plan.children():
        yield from _walk(child)


def _shuffle_leaves(plan: ExecutionPlan) -> List[UnresolvedShuffleExec]:
    return [n for n in _walk(plan) if isinstance(n, UnresolvedShuffleExec)]


def validate_graph(graph) -> None:
    """Raise ``PlanValidationError`` if ``graph`` breaks a launch invariant.

    ``graph`` is an ``ExecutionGraph`` (duck-typed: ``stages`` mapping,
    ``final_stage_id``, each stage with ``plan``/``producer_ids``)."""
    errors = check_graph(graph)
    if errors:
        raise PlanValidationError(graph.job_id, errors)


def check_graph(graph) -> List[str]:
    """Like ``validate_graph`` but returns the error list (for tooling)."""
    errors: List[str] = []
    stages = graph.stages

    # --- DAG shape: unknown producers, cycles, orphans -------------------
    for sid, stage in sorted(stages.items()):
        for pid in stage.producer_ids:
            if pid not in stages:
                errors.append(f"stage {sid} reads unknown producer stage {pid}")
        if sid in stage.producer_ids:
            errors.append(f"stage {sid} reads its own output")

    color: Dict[int, int] = {}  # 0 visiting, 1 done

    def has_cycle(sid: int, path: List[int]) -> bool:
        state = color.get(sid)
        if state == 0:
            errors.append("cyclic stage dependency: "
                          + " -> ".join(map(str, path + [sid])))
            return True
        if state == 1:
            return False
        color[sid] = 0
        for pid in stages[sid].producer_ids:
            if pid in stages and has_cycle(pid, path + [sid]):
                break  # report one cycle per root, not every unwind frame
        color[sid] = 1
        return False

    for sid in sorted(stages):
        has_cycle(sid, [])

    reachable = set()
    frontier = [graph.final_stage_id] if graph.final_stage_id in stages else []
    while frontier:
        sid = frontier.pop()
        if sid in reachable:
            continue
        reachable.add(sid)
        frontier.extend(p for p in stages[sid].producer_ids if p in stages)
    for sid in sorted(set(stages) - reachable):
        errors.append(f"orphan stage {sid}: unreachable from final stage "
                      f"{graph.final_stage_id}")

    # --- shuffle boundaries ----------------------------------------------
    for sid, stage in sorted(stages.items()):
        for leaf in _shuffle_leaves(stage.plan):
            producer = stages.get(leaf.stage_id)
            if producer is None:
                continue  # already reported as unknown producer
            writer = producer.plan
            if not isinstance(writer, ShuffleWriterExec):
                errors.append(f"stage {leaf.stage_id} feeds a shuffle read "
                              f"in stage {sid} but its root is not a "
                              f"ShuffleWriterExec")
                continue
            want = leaf.output_partition_count()
            got = _writer_output_count(writer)
            if want != got:
                errors.append(
                    f"shuffle partition mismatch across stages "
                    f"{leaf.stage_id} -> {sid}: writer produces {got} "
                    f"partitions, reader expects {want}")
            if leaf.schema != writer.schema:
                errors.append(
                    f"shuffle schema mismatch across stages "
                    f"{leaf.stage_id} -> {sid}: writer emits "
                    f"{writer.schema.names()} but reader expects "
                    f"{leaf.schema.names()}")

    # --- repartitioned-join hash agreement -------------------------------
    for sid, stage in sorted(stages.items()):
        for node in _walk(stage.plan):
            if not isinstance(node, JoinExec):
                continue
            if node.dist == "broadcast":
                # the build side is read in full by every probe partition;
                # co-partitioning is not required (an AQE broadcast switch
                # legitimately leaves the two inputs partitioned apart)
                continue
            kids = node.children()
            if len(kids) != 2:
                continue
            sides = [_shuffle_leaves(k) for k in kids]
            if not (len(sides[0]) == 1 and len(sides[1]) == 1):
                continue  # not a both-sides-repartitioned join
            parts: List[Optional[Partitioning]] = []
            for leaf in (sides[0][0], sides[1][0]):
                producer = stages.get(leaf.stage_id)
                writer = producer.plan if producer is not None else None
                parts.append(_writer_partitioning(writer)
                             if isinstance(writer, ShuffleWriterExec) else None)
            left, right = parts
            if left is None or right is None:
                continue
            if left.kind == "hash" and right.kind == "hash":
                if left.count != right.count:
                    errors.append(
                        f"join in stage {sid}: build/probe shuffle inputs "
                        f"use different hash partition counts "
                        f"({left.count} vs {right.count})")
                if len(left.exprs) != len(right.exprs):
                    errors.append(
                        f"join in stage {sid}: build/probe shuffle inputs "
                        f"hash on different key arity "
                        f"({len(left.exprs)} vs {len(right.exprs)})")

    # --- pass-through schema consistency ---------------------------------
    for sid, stage in sorted(stages.items()):
        for node in _walk(stage.plan):
            if not isinstance(node, PASS_THROUGH):
                continue
            kids = node.children()
            if len(kids) != 1:
                continue
            if node.schema != kids[0].schema:
                errors.append(
                    f"stage {sid}: {type(node).__name__} changes its "
                    f"child's schema ({kids[0].schema.names()} -> "
                    f"{node.schema.names()}) but is a pass-through operator")

    return errors


# --------------------------------------------------------------------------
# AQE rewrite re-validation (scheduler/aqe.py calls this after every
# runtime mutation of the graph; a failure here means the rewrite itself
# is buggy, so it raises instead of letting a corrupt plan launch tasks)
# --------------------------------------------------------------------------

def validate_rewrite(graph, stage, prior_schema) -> None:
    """Raise ``PlanValidationError`` if a runtime rewrite of ``stage``
    broke a graph invariant.  ``prior_schema`` is the stage root's schema
    before the rewrite (None skips the schema comparison, e.g. for a
    broadcast flip that by construction preserves it)."""
    errors = check_rewritten_stage(graph, stage, prior_schema)
    if errors:
        raise PlanValidationError(graph.job_id, errors)


def check_rewritten_stage(graph, stage, prior_schema) -> List[str]:
    """Like ``validate_rewrite`` but returns the error list.

    Stage-local checks run on the stage's live plan (resolved or not):
    the rewrite must not change the stage's output schema, its partition
    bookkeeping must agree with the plan, and every shuffle reader's
    location keys must fit its partition count.  Graph-wide checks catch
    dangling edges a bad exchange graft would leave behind: orphaned
    stages, missing producers, and producer/consumer link asymmetry."""
    errors: List[str] = []
    plan = stage.resolved_plan if stage.resolved_plan is not None else stage.plan

    if prior_schema is not None and plan.schema != prior_schema:
        errors.append(
            f"stage {stage.stage_id}: rewrite changed the output schema "
            f"({prior_schema.names()} -> {plan.schema.names()})")
    if plan.output_partition_count() != stage.partitions:
        errors.append(
            f"stage {stage.stage_id}: rewrite left the stage bookkeeping "
            f"at {stage.partitions} partitions but the plan produces "
            f"{plan.output_partition_count()}")
    if len(stage.task_infos) != stage.partitions:
        errors.append(
            f"stage {stage.stage_id}: task slots ({len(stage.task_infos)}) "
            f"disagree with the partition count ({stage.partitions})")
    if len(stage.task_failures) < stage.partitions \
            or len(stage.task_attempts) < stage.partitions:
        errors.append(
            f"stage {stage.stage_id}: attempt/failure budgets are shorter "
            f"than the partition count ({stage.partitions})")
    for node in _walk(plan):
        if isinstance(node, ShuffleReaderExec):
            bad = sorted(q for q in node.locations
                         if not 0 <= q < node.partition_count)
            if bad:
                errors.append(
                    f"stage {stage.stage_id}: shuffle reader of stage "
                    f"{node.stage_id} holds locations for partitions "
                    f"{bad} outside its partition count "
                    f"{node.partition_count}")

    # graph-wide link integrity (an exchange graft edits three stages)
    stages = graph.stages
    reachable = set()
    frontier = [graph.final_stage_id] if graph.final_stage_id in stages else []
    while frontier:
        sid = frontier.pop()
        if sid in reachable:
            continue
        reachable.add(sid)
        frontier.extend(p for p in stages[sid].producer_ids if p in stages)
    for sid in sorted(set(stages) - reachable):
        errors.append(f"orphan stage {sid} after rewrite: unreachable from "
                      f"final stage {graph.final_stage_id}")
    for sid, s in sorted(stages.items()):
        for pid in s.producer_ids:
            if pid not in stages:
                errors.append(f"stage {sid} reads producer stage {pid} "
                              f"which is no longer in the graph")
            elif sid not in stages[pid].output_links:
                errors.append(f"stage {sid} reads stage {pid} but is "
                              f"missing from its output links")
        for cid in s.output_links:
            if cid not in stages:
                errors.append(f"stage {sid} feeds stage {cid} which is no "
                              f"longer in the graph")
    return errors
