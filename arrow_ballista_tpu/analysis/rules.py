"""Built-in lint rules: the conventions this codebase actually relies on.

Each rule documents the invariant it guards and where breaking it was (or
would be) observed.  Add a rule by subclassing ``framework.Rule`` and
decorating with ``@register``; see docs/developer-guide/static-analysis.md.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import (
    Project,
    Rule,
    SourceFile,
    Violation,
    dotted_name,
    import_aliases,
    is_self_attr,
    register,
)

PKG = "arrow_ballista_tpu"


# --------------------------------------------------------------------------
# hot-path-purity
# --------------------------------------------------------------------------

@register
class HotPathPurityRule(Rule):
    """No host materialization primitives in operator hot-path modules.

    ``np.asarray``/``jax.device_get``/``jax.device_put``/
    ``.block_until_ready()``/``.tolist()`` inside ops/kernels.py,
    ops/operators.py, ops/expressions.py each force a device<->host
    sync (~75 ms fixed latency per transfer on remote-attached
    TPU backends) and silently turn a fused device pipeline into a host
    round-trip.  ``jax.device_put`` is additionally banned because direct
    uploads bypass the transfer accounting in models/batch.py (the device
    observatory would under-report h2d bytes).  Deliberate host-mode paths
    (host UDF projection, the single packed scalar fetch) carry
    ``# ballista: allow=hot-path-purity`` with a justification.
    """

    name = "hot-path-purity"
    description = ("no np.asarray / jax.device_get / jax.device_put / "
                   ".block_until_ready() / .tolist() in operator hot-path "
                   "modules")

    FILES = (f"{PKG}/ops/kernels.py", f"{PKG}/ops/operators.py",
             f"{PKG}/ops/expressions.py", f"{PKG}/compile/fused.py",
             f"{PKG}/compile/chains.py", f"{PKG}/compile/fuse.py")
    BANNED_MODULE_CALLS = {("numpy", "asarray"), ("jax", "device_get"),
                           ("jax", "device_put")}
    BANNED_METHODS = {"block_until_ready", "tolist"}

    def check(self, project: Project) -> Iterable[Violation]:
        for relpath in self.FILES:
            sf = project.file(relpath)
            if sf is None or sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, ast.Attribute):
                    continue
                if isinstance(f.value, ast.Name):
                    mod = aliases.get(f.value.id, f.value.id)
                    if (mod, f.attr) in self.BANNED_MODULE_CALLS:
                        yield Violation(
                            self.name, sf.path, node.lineno,
                            f"{f.value.id}.{f.attr}() forces a device->host "
                            f"materialization in a hot-path module")
                        continue
                if f.attr in self.BANNED_METHODS:
                    yield Violation(
                        self.name, sf.path, node.lineno,
                        f".{f.attr}() forces a device->host sync in a "
                        f"hot-path module")


# --------------------------------------------------------------------------
# span-coverage
# --------------------------------------------------------------------------

@register
class SpanCoverageRule(Rule):
    """Every physical-operator ``execute``/``execute_write`` override must
    run under ``ctx.op_span(self)`` so per-operator profiling (PR 2) covers
    the whole plan — one unwrapped operator leaves a hole in every profile
    and breaks the >=95%-coverage tracing test.

    Compliant shapes: a ``with ctx.op_span(self):`` anywhere in the body,
    a body that only raises (abstract / refuses-to-run operators), or a
    delegation to a sibling ``self.execute*`` method that spans.

    PR 6 extension: any OTHER operator-signature method in ops/ that
    emits operator stats (``self.metrics()...`` or ``deferred_rows``)
    is held to the same standard — metrics recorded outside a span are
    invisible to the profile's operator attribution and silently skew
    EXPLAIN ANALYZE.  Private helpers reached from a (checked) spanning
    entry point are exempt: being called as ``self.<name>`` elsewhere in
    the module (this covers overrides dispatched from a base class's
    spanning execute) means the span is already open on the stack.
    """

    name = "span-coverage"
    description = "operator execute() overrides wrapped via ctx.op_span"

    DIR = (f"{PKG}/ops/", f"{PKG}/compile/")
    METHODS = ("execute", "execute_write")
    # record_transfer feeds the device observatory's per-operator transfer
    # accounting; calling it outside ctx.op_span(self) silently drops the
    # bytes from the enclosing operator's stage summary.
    STATS_FNS = ("deferred_rows", "record_transfer")

    def check(self, project: Project) -> Iterable[Violation]:
        for sf in project.source_files():
            if not sf.path.startswith(self.DIR) or sf.tree is None:
                continue
            for cls in sf.tree.body:
                if not isinstance(cls, ast.ClassDef):
                    continue
                for fn in cls.body:
                    if (not isinstance(fn, ast.FunctionDef)
                            or not self._is_operator_sig(fn)):
                        continue
                    if fn.name in self.METHODS:
                        if not self._compliant(fn):
                            yield Violation(
                                self.name, sf.path, fn.lineno,
                                f"{cls.name}.{fn.name} is not wrapped in "
                                f"ctx.op_span(self) (and neither raises nor "
                                f"delegates to a spanning execute method)")
                    elif (self._emits_stats(fn)
                            and fn.name not in self._called_internally(
                                sf.tree, excluding=fn)
                            and not self._compliant(fn)):
                        yield Violation(
                            self.name, sf.path, fn.lineno,
                            f"{cls.name}.{fn.name} emits operator metrics "
                            f"but runs outside ctx.op_span(self) and is "
                            f"never reached from a spanning entry point")

    @staticmethod
    def _is_operator_sig(fn: ast.FunctionDef) -> bool:
        args = [a.arg for a in fn.args.args]
        return len(args) >= 3 and args[0] == "self" and "ctx" in args

    def _emits_stats(self, fn: ast.FunctionDef) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d == "self.metrics" or d in self.STATS_FNS:
                return True
        return False

    @staticmethod
    def _called_internally(tree: ast.Module,
                           excluding: ast.FunctionDef) -> Set[str]:
        """Method names invoked as ``self.<name>(...)`` anywhere in the
        module outside the method itself (recursion doesn't self-exempt;
        module scope so a base class dispatching to an override counts)."""
        skip = set(map(id, ast.walk(excluding)))
        called: Set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and id(node) not in skip
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                called.add(node.func.attr)
        return called

    def _compliant(self, fn: ast.FunctionDef) -> bool:
        body = [s for s in fn.body
                if not (isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Constant)
                        and isinstance(s.value.value, str))]  # skip docstring
        if body and all(isinstance(s, ast.Raise) for s in body):
            return True
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    c = item.context_expr
                    if (isinstance(c, ast.Call)
                            and dotted_name(c.func) == "ctx.op_span"):
                        return True
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d is not None and d.startswith("self.execute"):
                    return True
        return False


# --------------------------------------------------------------------------
# serde-completeness
# --------------------------------------------------------------------------

def _dataclass_names(tree: ast.Module) -> List[Tuple[str, int]]:
    out = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = dotted_name(target)
            if d in ("dataclass", "dataclasses.dataclass"):
                out.append((node.name, node.lineno))
                break
    return out


@register
class SerdeCompletenessRule(Rule):
    """Every wire dataclass must be registered (with a to/from pair) in
    ``serde.WIRE_TYPES``.  The control plane serializes exactly these
    shapes over the JSON framing; an unregistered dataclass means some
    call site is hand-rolling ``vars()`` without a deserializer contract,
    and the next added field silently drops on the wire.
    """

    name = "serde-completeness"
    description = "wire dataclasses registered for round-trip in serde.py"

    WIRE_FILES = (f"{PKG}/scheduler/types.py", f"{PKG}/net/wire.py")
    SERDE_FILE = f"{PKG}/serde.py"

    def check(self, project: Project) -> Iterable[Violation]:
        serde = project.file(self.SERDE_FILE)
        registered: Set[str] = set()
        registry_found = False
        if serde is not None and serde.tree is not None:
            for node in serde.tree.body:
                if (isinstance(node, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == "WIRE_TYPES"
                                for t in node.targets)
                        and isinstance(node.value, ast.Dict)):
                    registry_found = True
                    for k in node.value.keys:
                        if isinstance(k, ast.Name):
                            registered.add(k.id)
        if not registry_found:
            yield Violation(self.name, self.SERDE_FILE, 0,
                            "no WIRE_TYPES registry found (expected a "
                            "module-level dict literal keyed by wire "
                            "dataclass)")
            return
        for relpath in self.WIRE_FILES:
            sf = project.file(relpath)
            if sf is None or sf.tree is None:
                continue
            for name, line in _dataclass_names(sf.tree):
                if name not in registered:
                    yield Violation(
                        self.name, sf.path, line,
                        f"wire dataclass {name} is not registered in "
                        f"serde.WIRE_TYPES (add a to_obj/from_obj pair)")


# --------------------------------------------------------------------------
# config-registry
# --------------------------------------------------------------------------

@register
class ConfigRegistryRule(Rule):
    """Every ``ballista.*`` config key must be registered in the
    ``utils/config.py`` entry registry, carry a non-empty doc string, be
    rendered into docs/user-guide/configs.md, and every string-literal
    ``.get("ballista.*")``/``.set(...)`` call site must name a registered
    key.  An unregistered key raises at runtime only when that code path
    runs; this catches it at lint time.
    """

    name = "config-registry"
    description = "ballista.* keys registered, documented, and rendered"

    CONFIG_FILE = f"{PKG}/utils/config.py"
    DOC_FILE = "docs/user-guide/configs.md"

    def check(self, project: Project) -> Iterable[Violation]:
        sf = project.file(self.CONFIG_FILE)
        if sf is None or sf.tree is None:
            return
        consts: Dict[str, Tuple[str, int]] = {}  # NAME -> (key, line)
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                    and node.value.value.startswith("ballista.")):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts[t.id] = (node.value.value, node.lineno)
        entries: Dict[str, int] = {}  # key -> line of its ConfigEntry(...)
        undocumented: List[Tuple[str, int]] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "ConfigEntry" and node.args):
                continue
            arg0 = node.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
                key = arg0.value
            elif isinstance(arg0, ast.Name) and arg0.id in consts:
                key = consts[arg0.id][0]
            else:
                continue
            entries[key] = node.lineno
            doc = None
            if len(node.args) >= 4:
                doc = node.args[3]
            for kw in node.keywords:
                if kw.arg == "doc":
                    doc = kw.value
            if (doc is None or (isinstance(doc, ast.Constant)
                                and not str(doc.value).strip())):
                undocumented.append((key, node.lineno))

        for name, (key, line) in sorted(consts.items()):
            if key not in entries:
                yield Violation(self.name, sf.path, line,
                                f"config constant {name} = {key!r} has no "
                                f"ConfigEntry registration")
        for key, line in undocumented:
            yield Violation(self.name, sf.path, line,
                            f"config key {key!r} has an empty doc string")
        doc_text = project.read_text(self.DOC_FILE)
        if doc_text is None:
            yield Violation(self.name, self.DOC_FILE, 0,
                            "docs/user-guide/configs.md is missing (run "
                            "python docs/gen_configs.py)")
        else:
            for key in sorted(entries):
                if f"`{key}`" not in doc_text:
                    yield Violation(
                        self.name, self.DOC_FILE, 0,
                        f"registered key {key!r} is absent from "
                        f"{self.DOC_FILE} (run python docs/gen_configs.py)")
        # literal call sites anywhere in the package
        for src in project.source_files():
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("get", "set") and node.args):
                    continue
                arg0 = node.args[0]
                if (isinstance(arg0, ast.Constant)
                        and isinstance(arg0.value, str)
                        and arg0.value.startswith("ballista.")
                        and arg0.value not in entries):
                    yield Violation(
                        self.name, src.path, node.lineno,
                        f".{node.func.attr}({arg0.value!r}) names an "
                        f"unregistered config key")


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------

@register
class LockDisciplineRule(Rule):
    """Mutations of known shared scheduler state containers must happen
    inside ``with self._lock``/``self._cond`` (or in a ``*_locked`` helper,
    the repo convention for 'caller holds the lock').  These containers are
    hit concurrently by the event loop, the launch pool, the reaper, and
    RPC threads; one unlocked mutation is a rare-flake generator.
    """

    name = "lock-discipline"
    description = "shared scheduler state mutated only under self._lock"

    # (file, class) -> guarded attribute names
    GUARDED: Dict[Tuple[str, str], Set[str]] = {
        (f"{PKG}/scheduler/cluster.py", "ClusterState"):
            {"_executors", "_heartbeats", "_available", "_rr_cursor"},
        (f"{PKG}/scheduler/cluster.py", "JobState"):
            {"_status", "_graphs", "_done"},
        (f"{PKG}/scheduler/session.py", "SessionManager"):
            {"_sessions"},
        (f"{PKG}/scheduler/scheduler.py", "SchedulerServer"):
            {"_cleanup_timers", "_status_inbox"},
    }
    LOCK_ATTRS = {"_lock", "_cond", "_cleanup_lock", "_status_lock"}
    MUTATORS = {"append", "pop", "clear", "update", "setdefault", "add",
                "remove", "extend", "popitem", "insert", "discard"}

    def check(self, project: Project) -> Iterable[Violation]:
        by_file: Dict[str, List[Tuple[str, Set[str]]]] = {}
        for (path, cls), attrs in self.GUARDED.items():
            by_file.setdefault(path, []).append((cls, attrs))
        for path, classes in sorted(by_file.items()):
            sf = project.file(path)
            if sf is None or sf.tree is None:
                continue
            for node in sf.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for cls_name, attrs in classes:
                    if node.name != cls_name:
                        continue
                    yield from self._check_class(sf, node, attrs)

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef,
                     attrs: Set[str]) -> Iterable[Violation]:
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                continue
            yield from self._walk(sf, cls.name, fn.name, fn.body, attrs,
                                  locked=False)

    def _walk(self, sf: SourceFile, cls: str, fn: str, body, attrs: Set[str],
              locked: bool) -> Iterable[Violation]:
        for stmt in body:
            if isinstance(stmt, ast.With):
                inside = locked or any(
                    is_self_attr(item.context_expr, self.LOCK_ATTRS)
                    for item in stmt.items)
                yield from self._walk(sf, cls, fn, stmt.body, attrs, inside)
                continue
            if not locked:
                attr = self._mutated_attr(stmt, attrs)
                if attr is not None:
                    yield Violation(
                        self.name, sf.path, stmt.lineno,
                        f"{cls}.{fn} mutates shared attr self.{attr} "
                        f"outside 'with self._lock'")
            # nested bodies (if/for/try/...)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    yield from self._walk(sf, cls, fn, sub, attrs, locked)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._walk(sf, cls, fn, handler.body, attrs, locked)
            # inner defs inherit nothing: a nested closure may run later on
            # another thread, so treat its body as unlocked
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk(sf, cls, fn, stmt.body, attrs, False)

    def _mutated_attr(self, stmt: ast.stmt, attrs: Set[str]) -> Optional[str]:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            if is_self_attr(t, attrs):
                return t.attr
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            if (isinstance(f, ast.Attribute) and f.attr in self.MUTATORS
                    and is_self_attr(f.value, attrs)):
                return f.value.attr
        return None


# --------------------------------------------------------------------------
# no-blocking-in-event-loop
# --------------------------------------------------------------------------

@register
class NoBlockingInEventLoopRule(Rule):
    """No ``time.sleep`` or socket calls on the scheduler event loop.

    Every state transition funnels through the single-consumer loop
    (scheduler/event_loop.py); one blocking call there stalls all
    scheduling — exactly the slow-event class the loop's own watchdog
    warns about, but caught statically.  Checked in event_loop.py itself
    and in SchedulerServer's ``_on_*``/``_offer``/``_absorb*`` handlers.
    """

    name = "no-blocking-in-event-loop"
    description = "no time.sleep / socket calls in event-loop handlers"

    LOOP_FILE = f"{PKG}/scheduler/event_loop.py"
    SCHED_FILE = f"{PKG}/scheduler/scheduler.py"
    HANDLER_RE = re.compile(r"^(_on_|_offer$|_absorb)")

    def check(self, project: Project) -> Iterable[Violation]:
        sf = project.file(self.LOOP_FILE)
        if sf is not None and sf.tree is not None:
            yield from self._scan(sf, sf.tree)
        sf = project.file(self.SCHED_FILE)
        if sf is not None and sf.tree is not None:
            for cls in sf.tree.body:
                if not (isinstance(cls, ast.ClassDef)
                        and cls.name == "SchedulerServer"):
                    continue
                for fn in cls.body:
                    if (isinstance(fn, ast.FunctionDef)
                            and self.HANDLER_RE.match(fn.name)):
                        yield from self._scan(sf, fn)

    def _scan(self, sf: SourceFile, node: ast.AST) -> Iterable[Violation]:
        aliases = import_aliases(sf.tree)
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            d = dotted_name(n.func)
            if d is None:
                continue
            root = d.split(".")[0]
            resolved = aliases.get(root, root)
            full = d.replace(root, resolved, 1)
            if full == "time.sleep" or full.startswith("socket."):
                yield Violation(
                    self.name, sf.path, n.lineno,
                    f"{d}() blocks the scheduler event loop")


# --------------------------------------------------------------------------
# metrics-docs (folded in from tools/check_metrics_docs.py)
# --------------------------------------------------------------------------

@register
class MetricsDocsRule(Rule):
    """Every prometheus metric family the collectors emit must be
    documented in docs/user-guide/metrics.md.  Runtime-reflective (it
    instantiates the collectors and renders their exposition), so it only
    runs against the importable package — fixture projects select it
    explicitly when they want it.
    """

    name = "metrics-docs"
    description = "emitted prometheus metric families documented"

    DOC_FILE = "docs/user-guide/metrics.md"

    def emitted_metric_names(self) -> List[str]:
        from ..executor.metrics import ExecutorMetrics
        from ..scheduler.metrics import InMemoryMetricsCollector

        text = InMemoryMetricsCollector().gather() + ExecutorMetrics().gather()
        return sorted(set(re.findall(r"^# TYPE (\S+) \S+$", text, re.M)))

    def check(self, project: Project) -> Iterable[Violation]:
        doc = project.read_text(self.DOC_FILE)
        if doc is None:
            yield Violation(self.name, self.DOC_FILE, 0,
                            "docs/user-guide/metrics.md is missing")
            return
        for name in self.emitted_metric_names():
            if name not in doc:
                yield Violation(
                    self.name, self.DOC_FILE, 0,
                    f"metric family {name!r} is emitted by a collector but "
                    f"absent from {self.DOC_FILE}")


# --------------------------------------------------------------------------
# recovery-path-logging
# --------------------------------------------------------------------------

@register
class RecoveryPathLoggingRule(Rule):
    """Broad exception handlers on recovery paths must log or re-raise.

    The executor/scheduler retry loops lean on ``except Exception`` to
    survive transient failures — correct, but a silent ``pass`` there
    turns a dying scheduler into an executor that spins forever with no
    trace (the failure mode the PR-4 chaos suite reproduces).  Any bare /
    ``Exception`` / ``BaseException`` handler under ``executor/`` or
    ``scheduler/`` must contain a ``raise`` or a logging call; deliberate
    silences carry ``# ballista: allow=recovery-path-logging`` with a
    justification (e.g. best-effort cleanup where the peer is already
    gone and the outcome is reported elsewhere).
    """

    name = "recovery-path-logging"
    description = ("broad except handlers in executor/ and scheduler/ "
                   "log or re-raise")

    DIRS = (f"{PKG}/executor/", f"{PKG}/scheduler/")
    BROAD = {"Exception", "BaseException"}
    LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                   "critical", "log"}

    def check(self, project: Project) -> Iterable[Violation]:
        for sf in project.source_files():
            if sf.tree is None or not sf.path.startswith(self.DIRS):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not self._is_broad(node.type):
                    continue
                if not self._handles(node):
                    yield Violation(
                        self.name, sf.path, node.lineno,
                        "broad except swallows the error silently — log it, "
                        "re-raise, or justify with "
                        "'# ballista: allow=recovery-path-logging'")

    def _is_broad(self, t: Optional[ast.expr]) -> bool:
        if t is None:  # bare except:
            return True
        if isinstance(t, ast.Tuple):
            return any(self._is_broad(e) for e in t.elts)
        return dotted_name(t) in self.BROAD

    def _handles(self, handler: ast.ExceptHandler) -> bool:
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                d = dotted_name(n.func)
                if (d is not None and d.split(".")[-1] in self.LOG_METHODS
                        and "log" in d.lower()):
                    return True
        return False
