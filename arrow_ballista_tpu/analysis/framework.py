"""Custom lint framework: rule registry, suppressions, reporters.

The codebase's correctness rests on conventions no general-purpose linter
knows about (device-residency in operator hot paths, ``ctx.op_span``
coverage, the serde wire-type registry, the config-key registry, scheduler
lock discipline).  Zerrow (arxiv 2504.06151) and the zero-cost
Arrow<->Spark interface work (arxiv 2106.13020) both show that a single
accidental host<->device materialization silently erases zero-copy wins —
exactly the regression class a static pass catches before a benchmark
does.  This module is the harness; the rules live in ``rules.py``.

Usage:

    python -m arrow_ballista_tpu.analysis            # text report, exit 1 on hits
    python -m arrow_ballista_tpu.analysis --json     # machine-readable

Per-line suppression::

    x = np.asarray(v)  # ballista: allow=hot-path-purity — host-mode path

A suppression comment on its own line applies to the next line.  Every
suppression should carry a justification after the rule name.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Type

_SUPPRESS_RE = re.compile(r"#\s*ballista:\s*allow=([A-Za-z0-9_,*-]+)")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: ``rule`` fired at ``path:line``."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """A parsed python source plus its per-line suppression map."""

    def __init__(self, relpath: str, text: str):
        self.path = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:  # surfaced as a violation by the runner
            self.parse_error = str(e)
        # line (1-based) -> set of suppressed rule names ('*' = all)
        self.suppressions: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = {r.strip() for r in m.group(1).split(",")}

    def is_suppressed(self, rule: str, line: int) -> bool:
        """True when ``rule`` is allowed at ``line`` — by a trailing comment
        on the line itself, or by a comment-only line directly above it."""
        for cand in (line, line - 1):
            rules = self.suppressions.get(cand)
            if rules is None:
                continue
            if cand == line - 1 and not self._comment_only(cand):
                continue  # a trailing comment suppresses its OWN line only
            if rule in rules or "*" in rules:
                return True
        return False

    def _comment_only(self, line: int) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        return self.lines[line - 1].lstrip().startswith("#")


class Project:
    """The analyzed tree: repo root + the python package under it.

    Tests point this at fixture trees with the same relative layout, so
    rules never hard-code absolute paths.
    """

    def __init__(self, root: str, package: str = "arrow_ballista_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self._files: Dict[str, Optional[SourceFile]] = {}

    def abspath(self, relpath: str) -> str:
        return os.path.join(self.root, *relpath.split("/"))

    def exists(self, relpath: str) -> bool:
        return os.path.exists(self.abspath(relpath))

    def read_text(self, relpath: str) -> Optional[str]:
        try:
            with open(self.abspath(relpath), encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def file(self, relpath: str) -> Optional[SourceFile]:
        if relpath not in self._files:
            text = self.read_text(relpath)
            self._files[relpath] = (SourceFile(relpath, text)
                                    if text is not None else None)
        return self._files[relpath]

    def source_files(self) -> List[SourceFile]:
        """Every ``.py`` file under the package, sorted by path."""
        out = []
        pkg_dir = self.abspath(self.package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                sf = self.file(rel)
                if sf is not None:
                    out.append(sf)
        return out


class Rule:
    """Base lint rule.  ``check(project)`` yields raw violations; the
    runner applies suppressions afterward, so rules never special-case
    them."""

    name: str = ""
    description: str = ""

    def check(self, project: Project) -> Iterable[Violation]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a rule to the global registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    from . import concurrency, jit_discipline, rules  # noqa: F401 — importing registers

    return dict(_REGISTRY)


def run_lints(root: str, rule_names: Optional[Sequence[str]] = None,
              package: str = "arrow_ballista_tpu") -> List[Violation]:
    """Run the lint suite over ``root``; returns unsuppressed violations
    sorted by (path, line, rule)."""
    registry = all_rules()
    if rule_names is None:
        selected = list(registry.values())
    else:
        unknown = [n for n in rule_names if n not in registry]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                             f"known: {', '.join(sorted(registry))}")
        selected = [registry[n] for n in rule_names]
    project = Project(root, package=package)
    findings: List[Violation] = []
    for sf in project.source_files():
        if sf.parse_error:
            findings.append(Violation("syntax", sf.path, 0,
                                      f"cannot parse: {sf.parse_error}"))
    for cls in selected:
        for v in cls().check(project):
            sf = project.file(v.path) if v.path.endswith(".py") else None
            if sf is not None and sf.is_suppressed(v.rule, v.line):
                continue
            findings.append(v)
    return sorted(findings, key=lambda v: (v.path, v.line, v.rule))


def text_report(violations: Sequence[Violation]) -> str:
    if not violations:
        return "analysis: clean (0 violations)"
    lines = [v.format() for v in violations]
    lines.append(f"analysis: {len(violations)} violation(s)")
    return "\n".join(lines)


def json_report(violations: Sequence[Violation]) -> str:
    return json.dumps({"violations": [dataclasses.asdict(v) for v in violations],
                       "count": len(violations)}, indent=2)


def sarif_report(violations: Sequence[Violation]) -> str:
    """SARIF 2.1.0 log for CI inline annotation (one run, one driver).

    Rule metadata comes from the registry; findings synthesized by the
    runner itself (the ``syntax`` pseudo-rule) get a minimal stub so the
    log always validates."""
    registry = all_rules()
    rule_ids = sorted({v.rule for v in violations} | set(registry))
    rules = []
    for rid in rule_ids:
        cls = registry.get(rid)
        desc = (cls.description if cls is not None
                else "file could not be parsed")
        rules.append({"id": rid,
                      "shortDescription": {"text": desc}})
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = [{
        "ruleId": v.rule,
        "ruleIndex": index[v.rule],
        "level": "error",
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": v.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(v.line, 1)},
            },
        }],
    } for v in violations]
    log = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "ballista-analysis",
                "informationUri": ("https://github.com/apache/"
                                   "arrow-ballista"),
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
    return json.dumps(log, indent=2)


# --------------------------------------------------------------------------
# shared AST helpers (used by rules.py)
# --------------------------------------------------------------------------

def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local name -> imported module/object dotted path."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_self_attr(node: ast.AST, attrs: Set[str]) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in attrs)
