"""Runtime lock-order validator: instrument real lock acquisitions and
check them against the static concurrency model.

The static side (``analysis/concurrency.py``) predicts every lock
acquisition order the package can exhibit; this module observes what a
live run ACTUALLY does and asserts the two agree:

- no **contradicted** edge: the run never acquires B-then-A when the
  static graph only allows A-then-B (a would-be inversion the static
  pass missed or a fix regressed),
- no **unpredicted** edge: every observed A-then-B is reachable in the
  static graph — otherwise the model has a blind spot (a call path the
  interprocedural pass cannot see) and the lock-order rule's 'clean'
  verdict is weaker than it claims.

Mechanics: :func:`install` replaces ``threading.Lock`` / ``RLock`` /
``Condition`` with factories that wrap locks created *by package code*
(decided by the creator's stack frame) in a recording proxy.  Each proxy
remembers its creation site ``(file, line)``; because the package
convention is single-line ``self._x = threading.Lock()`` assignments,
that site equals the declaration line the static model indexes in
``ConcurrencyModel.decl_sites``, which is how runtime locks map back to
static identities.  A thread-local stack tracks held proxies; each
successful acquire records edges ``held -> acquired``.

The shim is debug-only and **zero-cost when off**: nothing is patched
unless :func:`install` runs, which the wiring (tests/conftest.py,
benchmarks/serving.py) only does when the
``ballista.analysis.lock_order.runtime`` config / the
``BALLISTA_LOCK_ORDER_RUNTIME`` env var enables it.

Condition notes: a ``Condition(wrapped_lock)`` routes its acquire /
release / wait through the proxy because the proxy deliberately refuses
to expose ``_release_save`` / ``_acquire_restore`` — ``threading.
Condition`` then falls back to its pure-Python paths, which call
``proxy.acquire()`` / ``proxy.release()``.  ``wait()`` therefore
correctly pops the lock from the held stack while blocked and re-records
it on wakeup.  ``_is_owned`` IS exposed (delegating to the raw lock)
because the Condition fallback mis-reports ownership for reentrant
locks.  A bare ``Condition()`` gets a wrapped RLock attributed to the
Condition's own creation site, matching the static model's
own-lock-token fallback for unwrapped conditions.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

#: runtime creation site: (abs file, line)
Site = Tuple[str, int]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))

# captured BEFORE patching, used for shim-internal state — these must
# never be proxies or acquire-recording would recurse
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock
_RAW_CONDITION = threading.Condition


class _Recorder:
    """Global edge log: (site held, site acquired) -> count."""

    def __init__(self) -> None:
        self._lock = _RAW_LOCK()
        self.edges: Dict[Tuple[Site, Site], int] = {}
        self.sites: Set[Site] = set()
        self._tls = threading.local()

    def _stack(self) -> List[Site]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def on_create(self, site: Site) -> None:
        with self._lock:
            self.sites.add(site)

    def on_acquire(self, site: Site) -> None:
        stack = self._stack()
        new_edges = [(h, site) for h in stack if h != site]
        stack.append(site)
        if new_edges:
            with self._lock:
                for e in new_edges:
                    self.edges[e] = self.edges.get(e, 0) + 1

    def on_release(self, site: Site) -> None:
        stack = self._stack()
        # remove the most recent occurrence (re-entrant RLocks may hold
        # the same site multiple times)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    def snapshot(self) -> Dict[Tuple[Site, Site], int]:
        with self._lock:
            return dict(self.edges)

    def reset(self) -> None:
        with self._lock:
            self.edges.clear()
            self.sites.clear()


_recorder = _Recorder()


class _LockProxy:
    """Recording wrapper around a real Lock/RLock.

    Exposes acquire/release/__enter__/__exit__/locked plus a delegating
    ``_is_owned`` — but NOT ``_release_save``/``_acquire_restore`` — so
    ``threading.Condition`` uses its pure-Python wait paths (see module
    docstring) and every transition goes through the recorder.
    """

    __slots__ = ("_raw", "_site")

    def __init__(self, raw, site: Site):
        self._raw = raw
        self._site = site
        _recorder.on_create(site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            _recorder.on_acquire(self._site)
        return ok

    def release(self) -> None:
        _recorder.on_release(self._site)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def _is_owned(self) -> bool:
        # Condition.notify/wait need ownership checks.  RLock tracks its
        # owner — delegate (no recording: this is a query, not a
        # transition).  A plain Lock has no owner concept; fall back to
        # Condition's own heuristic, also without recording.
        raw_owned = getattr(self._raw, "_is_owned", None)
        if raw_owned is not None:
            return bool(raw_owned())
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_LockProxy {self._site[0]}:{self._site[1]} {self._raw!r}>"


def _creation_site() -> Optional[Site]:
    """(file, line) of the immediate caller, or None when that caller is
    not package code (the lock stays raw).  Only the DIRECT caller counts:
    locks that stdlib helpers (queue.Queue, ThreadPoolExecutor, Event)
    create on the package's behalf belong to those helpers' own
    well-audited discipline and would only add unmappable noise.  The
    shim's own module is excluded so registry-internal locks never
    self-instrument."""
    f = sys._getframe(2)
    if f is None:
        return None
    fn = f.f_code.co_filename
    if fn.startswith(_PKG_DIR) and not fn.startswith(_ANALYSIS_DIR):
        return (fn, f.f_lineno)
    return None


def _make_lock_factory(raw_ctor):
    def factory(*args, **kwargs):
        raw = raw_ctor(*args, **kwargs)
        site = _creation_site()
        if site is None:
            return raw
        return _LockProxy(raw, site)

    return factory


def _condition_factory(lock=None):
    if lock is None:
        site = _creation_site()
        if site is None:
            return _RAW_CONDITION()
        # bare Condition(): the static model treats it as its own lock
        # token at the Condition's declaration line
        lock = _LockProxy(_RAW_RLOCK(), site)
    return _RAW_CONDITION(lock)


_installed = False


def enabled() -> bool:
    """True when the shim should run: BALLISTA_LOCK_ORDER_RUNTIME env var
    (shared truthiness rule) or the config default for
    ``ballista.analysis.lock_order.runtime``."""
    from ..utils.config import ANALYSIS_LOCK_ORDER_RUNTIME, BallistaConfig, env_flag

    flag = env_flag("BALLISTA_LOCK_ORDER_RUNTIME")
    if flag is not None:
        return flag
    return bool(BallistaConfig().get(ANALYSIS_LOCK_ORDER_RUNTIME))


def install() -> None:
    """Patch the threading lock constructors.  Idempotent.  Must run
    before the package modules under test create their locks (i.e. before
    importing them) for full coverage; later is safe but records less."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_lock_factory(_RAW_LOCK)
    threading.RLock = _make_lock_factory(_RAW_RLOCK)
    threading.Condition = _condition_factory
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _RAW_LOCK
    threading.RLock = _RAW_RLOCK
    threading.Condition = _RAW_CONDITION
    _installed = False


# --------------------------------------------------------------------------
# validation against the static model
# --------------------------------------------------------------------------


class ValidationReport:
    def __init__(self) -> None:
        self.checked = 0          # runtime edges with both ends mapped
        self.unknown = 0          # runtime edges with an unmapped end
        self.contradicted: List[str] = []
        self.unpredicted: List[str] = []

    @property
    def ok(self) -> bool:
        return not self.contradicted and not self.unpredicted

    def summary(self) -> str:
        return (f"lock-order runtime validation: {self.checked} edge(s) "
                f"checked, {self.unknown} unmapped, "
                f"{len(self.contradicted)} contradicted, "
                f"{len(self.unpredicted)} unpredicted")

    def details(self) -> str:
        lines = [self.summary()]
        for s in self.contradicted:
            lines.append(f"  CONTRADICTED {s}")
        for s in self.unpredicted:
            lines.append(f"  UNPREDICTED {s}")
        return "\n".join(lines)


def validate(root: Optional[str] = None) -> ValidationReport:
    """Check every recorded runtime edge against the static model built
    from ``root`` (default: the repo containing this package).

    - runtime edge (a, b) with static ``has_path(b, a)`` but not
      ``has_path(a, b)``: **contradicted** — the run proved an inversion
      of the static order.
    - runtime edge (a, b) with neither path: **unpredicted** — the static
      model missed a reachable nesting; its 'no cycles' verdict does not
      cover this pair.

    Edges whose creation sites don't map to a static declaration (locks
    made by tests, fixtures, or multi-line declarations) are counted as
    unmapped, not failed: the validator checks consistency where the two
    views overlap, and reports the overlap size so a silent mapping
    regression is visible.
    """
    from .concurrency import build_model, fmt_lock
    from .framework import Project

    if root is None:
        root = os.path.dirname(_PKG_DIR)
    model = build_model(Project(root))
    # (abs file, line) -> LockId via repo-relative path
    site_to_lock = {}
    for (rel, line), lid in model.decl_sites.items():
        site_to_lock[(os.path.join(root, *rel.split("/")), line)] = lid

    rep = ValidationReport()
    for (sa, sb), count in sorted(_recorder.snapshot().items()):
        a = site_to_lock.get(sa)
        b = site_to_lock.get(sb)
        if a is None or b is None:
            rep.unknown += 1
            continue
        if a == b:
            # same static lock nested at runtime: either a reentrant
            # RLock (fine) or a bug LockOrderRule reports statically
            continue
        rep.checked += 1
        desc = (f"{fmt_lock(a)} -> {fmt_lock(b)} (observed {count}x, "
                f"from {os.path.relpath(sa[0], root)}:{sa[1]} -> "
                f"{os.path.relpath(sb[0], root)}:{sb[1]})")
        if model.has_path(a, b):
            continue
        if model.has_path(b, a):
            rep.contradicted.append(desc)
        else:
            rep.unpredicted.append(desc)
    return rep


def assert_consistent(root: Optional[str] = None) -> ValidationReport:
    """validate() + raise AssertionError on any disagreement."""
    rep = validate(root)
    if not rep.ok:
        raise AssertionError(rep.details())
    return rep


def reset() -> None:
    """Drop all recorded edges/sites (between validation phases)."""
    _recorder.reset()
