"""JIT-discipline analyzer: static verification of every device kernel.

The device observatory (obs/device.py) made retraces, transfers, and
donation *observable at runtime*; this module proves jit discipline
*before merge*.  It builds a per-call-site **JitSiteModel** for every
``observed_jit`` construction under ``ops/``, ``compile/``, ``models/``,
and ``obs/device.py`` — the signature string the runtime observatory
reports under, the traced callable (lambda, named def, or decorated
function), resolved ``static_argnums``/``static_argnames`` positions,
``donate_argnums``, and every reachable call site (linked
interprocedurally through the repo's binding idioms: direct names,
``self._attr`` assignment, tuple returns from ``shared_program``
builders matched to same-shape unpacks in the same class, decorators,
and cross-module from-imports of module-level wrappers).

Four rules consume the model:

``trace-key-stability``
    Batch-varying VALUES (reads of ``.columns``/``.mask``/``.dicts``/
    ``.num_rows``, or results of other jit calls) flowing into a static
    argument position mint a new trace key per distinct value — a
    retrace storm the observatory would count as ``jit_retraces`` under
    the same signature this rule reports.  Values are considered clean
    again after passing a *sanitizer* (``round_capacity``,
    ``dense_domain``, ``.bit_length()``-based pow2 bucketing, or a
    ``.capacity`` read — capacities are pow2-padded by construction).
    Also flags wrappers constructed inside loops (each construction
    starts an empty trace cache) and traced bodies that close over a
    batch-varying local (the value is baked into the trace).

``donation-safety``
    For donated arguments, XLA deletes the input buffer: any later read
    of the same attribute, any escape of the base object, or any method
    call on it (which may read buffers internally) is a
    *use-after-donation* violation.  A call inside a loop counts reads
    anywhere in that loop unless the base is the loop's own target
    (rebound each iteration).  Conversely, an undonated argument that
    shares a donated argument's base and is provably dead after every
    call — or whose base is freshly produced by another jit call in the
    same function and dead after — is reported as a
    *provably-safe-but-undonated* advisory.

``host-device-boundary``
    Inside traced bodies: host ``numpy`` calls, ``.tolist()``/
    ``.item()``, ``float()``/``int()``/``bool()`` concretization, and
    float64 promotion are host round-trips or weak-type hazards that
    the shape-keyed trace cache cannot see.  Outside traced bodies:
    ``jax.device_get``/``jax.device_put`` in a function that never
    calls ``record_transfer`` is an unaccounted transfer — the
    observatory's byte counters silently lie about it.

``fusion-verdict-consistency``
    ``compile/fuse.py``'s ``DEFAULT_OPERATORS`` allowlist, the
    ``_op_verdict`` per-node doubts, ``compile/fused.py``'s kernel
    builders, and ``compile/chains.py``'s static reason tables must
    agree with the operator classes that actually exist: every
    allowlisted name is a real operator with a builder branch and a
    verdict branch, verdicts consult ``host_mode`` when the operator
    has one, and chain tables name no phantom classes.

A fifth, repo-wide rule:

``deprecated-jax-api``
    ``jax.shard_map`` does not exist in jax 0.4.x — every call raises
    ``AttributeError`` at dispatch time (the 47 standing tier-1
    failures).  Flags the stale convention with the remediation:
    ``jax.experimental.shard_map.shard_map(f, mesh=..., in_specs=...,
    out_specs=...)`` or pjit-with-shardings (ROADMAP #1).

Suppressions use the standard grammar
(``# ballista: allow=<rule> — justification``); findings on deliberate
trade-offs (the above-ceiling exact-size join compile, batched scalar
syncs) are suppressed at the tainting assignment, not the call, so the
justification sits next to the branch that makes the trade.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .framework import (
    Project,
    Rule,
    SourceFile,
    Violation,
    dotted_name,
    import_aliases,
    register,
)

# Model scope: every observed_jit construction in the execution engine.
_SCAN_DIRS = ("ops", "compile", "models")
_SCAN_FILES = ("obs/device.py",)

_WRAPPER = "observed_jit"

#: ColumnBatch attributes whose VALUES vary per batch — the taint seeds.
_VALUE_ATTRS = frozenset({"columns", "mask", "dicts",
                          "num_rows", "_num_rows"})

#: Attribute reads yielding shape-class metadata: ``capacity`` is
#: pow2-padded by ``round_capacity`` at construction, shapes key the
#: trace anyway.  Reading one of these is NOT a per-batch value.
_SANITIZED_ATTRS = frozenset({"capacity", "shape", "ndim", "size"})

#: Calls whose result is shape-class-stable even over tainted inputs:
#: pow2 bucketing and dict-domain bounds take a bounded set of values.
_SANITIZERS = frozenset({"round_capacity", "dense_domain", "bit_length"})

#: Host-only ColumnBatch attributes: reading one after donation is safe
#: (no device buffer involved).
_HOST_ATTRS = frozenset({"schema", "dicts", "capacity", "num_rows",
                         "_num_rows", "names", "fields", "dtype"})


# --------------------------------------------------------------------------
# model data structures
# --------------------------------------------------------------------------

@dataclasses.dataclass
class CallSite:
    """One resolved invocation of a jit wrapper."""

    path: str
    node: ast.Call
    func: Optional[ast.AST]  # enclosing FunctionDef (None = module level)


@dataclasses.dataclass
class JitSite:
    """One ``observed_jit(...)`` construction plus everything the rules
    need to reason about it."""

    path: str
    line: int
    sig: str                       # runtime signature ("<dynamic>" if not
                                   # a string literal)
    ctor: ast.Call
    scope_key: str                 # enclosing class name or "<module>"
    enclosing_fn: Optional[ast.AST]
    fn_node: Optional[ast.AST]     # traced Lambda/FunctionDef, if resolved
    fn_params: Optional[List[str]]
    has_varargs: bool = False
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    calls: List[CallSite] = dataclasses.field(default_factory=list)

    def static_positions(self) -> Set[int]:
        pos = set(self.static_argnums)
        if self.fn_params:
            for name in self.static_argnames:
                if name in self.fn_params:
                    pos.add(self.fn_params.index(name))
        return pos


class _ModuleModel:
    """Per-file AST indexes shared by the rules."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.path = sf.path
        self.tree = sf.tree
        self.parents: Dict[int, ast.AST] = {}
        self.aliases = import_aliases(self.tree) if self.tree else {}
        if self.tree is not None:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self.parents[id(child)] = parent

    def parent_chain(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def enclosing(self, node: ast.AST, kinds) -> Optional[ast.AST]:
        for anc in self.parent_chain(node):
            if isinstance(anc, kinds):
                return anc
        return None

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        return self.enclosing(
            node, (ast.FunctionDef, ast.AsyncFunctionDef))

    def enclosing_class_name(self, node: ast.AST) -> str:
        cls = self.enclosing(node, ast.ClassDef)
        return cls.name if cls is not None else "<module>"


# --------------------------------------------------------------------------
# scope-local statement walking (never descends into nested defs)
# --------------------------------------------------------------------------

_SCOPE_KINDS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _scope_nodes(root: ast.AST) -> List[ast.AST]:
    """All descendants of *root* in root's own scope — nested function /
    class bodies are opaque (they are their own scopes)."""
    out: List[ast.AST] = []

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            out.append(child)
            if not isinstance(child, _SCOPE_KINDS):
                rec(child)

    rec(root)
    return out


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    return []


def _literal_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(val, int):
        return (val,)
    if isinstance(val, (tuple, list)):
        return tuple(v for v in val if isinstance(v, int))
    return ()


def _literal_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return ()
    if isinstance(val, str):
        return (val,)
    if isinstance(val, (tuple, list)):
        return tuple(v for v in val if isinstance(v, str))
    return ()


# --------------------------------------------------------------------------
# taint analysis: which expressions carry per-batch VALUES
# --------------------------------------------------------------------------

TaintSources = Set[Tuple[int, str]]


def _expr_taint(node: Optional[ast.AST],
                env: Dict[str, TaintSources]) -> TaintSources:
    """Source set (line, why) if *node* carries a batch-varying value;
    empty set = shape-class-stable."""
    if node is None:
        return set()
    if isinstance(node, ast.Name):
        # Store/Del contexts (comprehension targets, assignment targets)
        # BIND the name — they do not read the enclosing scope's value.
        if not isinstance(node.ctx, ast.Load):
            return set()
        return env.get(node.id, set())
    if isinstance(node, ast.Attribute):
        if node.attr in _VALUE_ATTRS:
            return {(node.lineno,
                     f"reads batch content '.{node.attr}'")}
        if node.attr in _SANITIZED_ATTRS:
            return set()
        return _expr_taint(node.value, env)
    if isinstance(node, ast.Call):
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname in _SANITIZERS:
            return set()
        out: TaintSources = set()
        for arg in node.args:
            out |= _expr_taint(arg, env)
        for kw in node.keywords:
            out |= _expr_taint(kw.value, env)
        if isinstance(node.func, ast.Attribute):
            out |= _expr_taint(node.func.value, env)
        return out
    if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                         ast.DictComp)):
        # comprehension targets shadow enclosing names: evaluate the
        # element in an env where each target carries its iterable's
        # taint, not the (unrelated) function-local binding.
        inner = dict(env)
        out: TaintSources = set()
        for gen in node.generators:
            iter_taint = _expr_taint(gen.iter, inner)
            out |= iter_taint
            for name in _target_names(gen.target):
                inner[name] = set(iter_taint)
            for cond in gen.ifs:
                out |= _expr_taint(cond, inner)
        if isinstance(node, ast.DictComp):
            out |= _expr_taint(node.key, inner)
            out |= _expr_taint(node.value, inner)
        else:
            out |= _expr_taint(node.elt, inner)
        return out
    if isinstance(node, (ast.Constant, ast.Lambda, ast.JoinedStr)):
        return set()
    out = set()
    for child in ast.iter_child_nodes(node):
        out |= _expr_taint(child, env)
    return out


_MUTATORS = frozenset({"append", "add", "extend", "update", "insert"})


def _function_taint_env(fn: ast.AST) -> Dict[str, TaintSources]:
    """Flow-insensitive name -> taint-source map for one function scope.

    Sources collapse to the tainting ASSIGNMENT line, so a suppression
    sits next to the branch that introduces the hazard, not the call."""
    nodes = _scope_nodes(fn)
    env: Dict[str, TaintSources] = {}

    def mark(name: str, line: int, why: str) -> bool:
        prev = env.setdefault(name, set())
        entry = (line, why)
        if entry in prev:
            return False
        prev.add(entry)
        return True

    for _ in range(4):
        changed = False
        for node in nodes:
            if isinstance(node, ast.Assign):
                taint = _expr_taint(node.value, env)
                if taint:
                    for target in node.targets:
                        for name in _target_names(target):
                            changed |= mark(
                                name, node.lineno,
                                "assigned from a batch-varying "
                                "expression")
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if _expr_taint(node.value, env):
                    for name in _target_names(node.target):
                        changed |= mark(
                            name, node.lineno,
                            "assigned from a batch-varying expression")
            elif isinstance(node, ast.AugAssign):
                if _expr_taint(node.value, env):
                    for name in _target_names(node.target):
                        changed |= mark(
                            name, node.lineno,
                            "accumulates a batch-varying expression")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _expr_taint(node.iter, env):
                    for name in _target_names(node.target):
                        changed |= mark(
                            name, node.lineno,
                            "iterates a batch-varying sequence")
            elif isinstance(node, ast.Expr) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr in _MUTATORS
                        and isinstance(call.func.value, ast.Name)):
                    taint: TaintSources = set()
                    for arg in call.args:
                        taint |= _expr_taint(arg, env)
                    if taint:
                        changed |= mark(
                            call.func.value.id, call.lineno,
                            "mutated with a batch-varying element")
        if not changed:
            break
    return env


def _free_loads(fn: ast.AST) -> Dict[str, int]:
    """Names loaded in *fn* (including nested scopes) but never bound
    there: closure captures.  Maps name -> first-use line."""
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    loads: Dict[str, int] = {}
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    bound.add(node.id)
                elif node.id not in loads:
                    loads[node.id] = node.lineno
            elif isinstance(node, ast.arg):
                bound.add(node.arg)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                bound.add(node.name)
    return {n: ln for n, ln in loads.items() if n not in bound}


# --------------------------------------------------------------------------
# JitSiteModel construction
# --------------------------------------------------------------------------

class JitSiteModel:
    """All jit sites in scope, with call sites resolved."""

    def __init__(self) -> None:
        self.sites: List[JitSite] = []
        self.modules: Dict[str, _ModuleModel] = {}
        # wrapper alias names per (path, scope_key); used by the
        # donation freshness proof to recognize "result of a jit call".
        self.alias_names: Dict[Tuple[str, str], Set[str]] = {}
        self._env_cache: Dict[int, Dict[str, TaintSources]] = {}

    def taint_env(self, fn: Optional[ast.AST]) -> Dict[str, TaintSources]:
        if fn is None:
            return {}
        key = id(fn)
        if key not in self._env_cache:
            self._env_cache[key] = _function_taint_env(fn)
        return self._env_cache[key]

    def wrapper_names_in(self, path: str, scope_key: str) -> Set[str]:
        return (self.alias_names.get((path, scope_key), set())
                | self.alias_names.get((path, "<module>"), set()))


def _scan_files(project: Project) -> List[SourceFile]:
    out: List[SourceFile] = []
    pkg = project.package
    for sf in project.source_files():
        rel = sf.path
        if not rel.startswith(pkg + "/"):
            continue
        sub = rel[len(pkg) + 1:]
        if sub in _SCAN_FILES or any(
                sub.startswith(d + "/") for d in _SCAN_DIRS):
            out.append(sf)
    return out


def _is_wrapper_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and ((isinstance(node.func, ast.Name)
                  and node.func.id == _WRAPPER)
                 or (isinstance(node.func, ast.Attribute)
                     and node.func.attr == _WRAPPER)))


def _resolve_starred_dict(call: ast.Call, fn: Optional[ast.AST],
                          key: str) -> Optional[ast.AST]:
    """Resolve ``f(**kw)`` keyword *key* through ``kw[key] = <literal>``
    subscript assignments in the enclosing function."""
    names = {kw.value.id for kw in call.keywords
             if kw.arg is None and isinstance(kw.value, ast.Name)}
    if not names or fn is None:
        return None
    for node in _scope_nodes(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)):
            sub = node.targets[0]
            if (isinstance(sub.value, ast.Name) and sub.value.id in names
                    and isinstance(sub.slice, ast.Constant)
                    and sub.slice.value == key):
                return node.value
    return None


def _lookup_def(name: str, mod: _ModuleModel,
                around: ast.AST) -> Optional[ast.AST]:
    """Find ``def name`` in the enclosing function chain or at module
    level."""
    scopes: List[ast.AST] = []
    fn = mod.enclosing_function(around)
    while fn is not None:
        scopes.append(fn)
        fn = mod.enclosing_function(fn)
    scopes.append(mod.tree)
    for scope in scopes:
        for stmt in _scope_nodes(scope):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name == name:
                return stmt
    return None


def _extract_site(ctor: ast.Call, mod: _ModuleModel,
                  decorated: Optional[ast.AST]) -> JitSite:
    sig = "<dynamic>"
    if ctor.args and isinstance(ctor.args[0], ast.Constant) \
            and isinstance(ctor.args[0].value, str):
        sig = ctor.args[0].value
    fn_node: Optional[ast.AST] = decorated
    if fn_node is None and len(ctor.args) >= 2:
        cand = ctor.args[1]
        if isinstance(cand, ast.Lambda):
            fn_node = cand
        elif isinstance(cand, ast.Name):
            fn_node = _lookup_def(cand.id, mod, ctor)
    fn_params: Optional[List[str]] = None
    has_varargs = False
    if fn_node is not None:
        args = fn_node.args
        fn_params = [a.arg for a in (args.posonlyargs + args.args)]
        has_varargs = args.vararg is not None

    statics: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    donated: Tuple[int, ...] = ()
    enclosing = mod.enclosing_function(ctor)
    for kw in ctor.keywords:
        if kw.arg == "static_argnums":
            statics = _literal_int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            static_names = _literal_str_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            donated = _literal_int_tuple(kw.value)
    if not donated:
        resolved = _resolve_starred_dict(ctor, enclosing, "donate_argnums")
        if resolved is not None:
            donated = _literal_int_tuple(resolved)

    return JitSite(
        path=mod.path, line=ctor.lineno, sig=sig, ctor=ctor,
        scope_key=mod.enclosing_class_name(ctor),
        enclosing_fn=enclosing, fn_node=fn_node, fn_params=fn_params,
        has_varargs=has_varargs, static_argnums=statics,
        static_argnames=static_names, donate_argnums=donated)


def build_model(project: Project) -> JitSiteModel:
    cached = getattr(project, "_jit_discipline_model", None)
    if cached is not None:
        return cached
    model = JitSiteModel()
    # name aliases: (path, scope_key, name) -> [sites]
    name_aliases: Dict[Tuple[str, str, str], List[JitSite]] = {}
    attr_aliases: Dict[Tuple[str, str, str], List[JitSite]] = {}
    # tuple shapes: (path, scope_key, arity) -> [(index, site)]
    shapes: Dict[Tuple[str, str, int], List[Tuple[int, JitSite]]] = {}
    # module-level wrapper names visible cross-file
    exports: Dict[str, JitSite] = {}

    def add_alias(table, key, site):
        table.setdefault(key, []).append(site)
        model.alias_names.setdefault((key[0], key[1]), set()).add(key[2])

    for sf in _scan_files(project):
        if sf.tree is None:
            continue
        mod = _ModuleModel(sf)
        model.modules[sf.path] = mod
        decorated_ctors: Dict[int, ast.AST] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_wrapper_ctor(dec):
                        decorated_ctors[id(dec)] = node
        for node in ast.walk(mod.tree):
            if not _is_wrapper_ctor(node):
                continue
            decorated = decorated_ctors.get(id(node))
            site = _extract_site(node, mod, decorated)
            model.sites.append(site)
            scope = site.scope_key
            if decorated is not None:
                add_alias(name_aliases, (sf.path, scope, decorated.name),
                          site)
                if mod.enclosing_function(decorated) is None \
                        and scope == "<module>":
                    exports[decorated.name] = site
                continue
            parent = mod.parents.get(id(node))
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                target = parent.targets[0]
                if isinstance(target, ast.Name):
                    add_alias(name_aliases, (sf.path, scope, target.id),
                              site)
                    if mod.enclosing_function(parent) is None \
                            and scope == "<module>":
                        exports[target.id] = site
                elif isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self":
                    add_alias(attr_aliases, (sf.path, scope, target.attr),
                              site)
            elif isinstance(parent, (ast.Tuple, ast.List)):
                grand = mod.parents.get(id(parent))
                index = next(i for i, e in enumerate(parent.elts)
                             if e is node)
                arity = len(parent.elts)
                if isinstance(grand, (ast.Return, ast.Assign)):
                    shapes.setdefault((sf.path, scope, arity), []) \
                        .append((index, site))
                if isinstance(grand, ast.Assign) \
                        and len(grand.targets) == 1 \
                        and isinstance(grand.targets[0], ast.Attribute) \
                        and isinstance(grand.targets[0].value, ast.Name) \
                        and grand.targets[0].value.id == "self":
                    # self._x = (..., wrapper, ...): unpacks of self._x
                    # match through the same shape table.
                    pass

    # second pass: match same-shape tuple unpacks to register aliases
    for sf_path, mod in model.modules.items():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], (ast.Tuple, ast.List))):
                continue
            elts = node.targets[0].elts
            if any(isinstance(e, ast.Starred) for e in elts):
                continue
            scope = mod.enclosing_class_name(node)
            for index, site in shapes.get((sf_path, scope, len(elts)), ()):
                target = elts[index]
                if isinstance(target, ast.Name) and target.id != "_":
                    add_alias(name_aliases, (sf_path, scope, target.id),
                              site)
                elif isinstance(target, ast.Attribute) \
                        and isinstance(target.value, ast.Name) \
                        and target.value.id == "self":
                    add_alias(attr_aliases, (sf_path, scope, target.attr),
                              site)

    # third pass: resolve call sites against the alias tables
    def plausible(site: JitSite, call: ast.Call) -> bool:
        if site.fn_params is None or site.has_varargs:
            return True
        return len(call.args) + len(call.keywords) <= len(site.fn_params)

    for sf_path, mod in model.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or _is_wrapper_ctor(node):
                continue
            scope = mod.enclosing_class_name(node)
            targets: List[JitSite] = []
            if isinstance(node.func, ast.Name):
                name = node.func.id
                targets += name_aliases.get((sf_path, scope, name), [])
                if scope != "<module>":
                    targets += name_aliases.get(
                        (sf_path, "<module>", name), [])
                if not targets and name in exports \
                        and name in mod.aliases:
                    targets.append(exports[name])
            elif isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                targets += attr_aliases.get(
                    (sf_path, scope, node.func.attr), [])
            fn = mod.enclosing_function(node)
            for site in targets:
                if plausible(site, node):
                    site.calls.append(CallSite(sf_path, node, fn))

    project._jit_discipline_model = model  # type: ignore[attr-defined]
    return model


# --------------------------------------------------------------------------
# shared read-after analysis (donation)
# --------------------------------------------------------------------------

def _pos_after(node: ast.AST, call: ast.Call) -> bool:
    end_line = getattr(call, "end_lineno", call.lineno)
    end_col = getattr(call, "end_col_offset", 0)
    return (node.lineno, node.col_offset) > (end_line, end_col)


def _reads_after(mod: _ModuleModel, fn: Optional[ast.AST], call: ast.Call,
                 base: str, attr: Optional[str]) -> List[Tuple[int, str]]:
    """Reads of *base* (restricted to *attr* when given) that can observe
    state after *call* ran: later in source, or anywhere inside a shared
    loop that does not rebind *base* per iteration."""
    if fn is None:
        return []
    in_call = {id(n) for n in ast.walk(call)}
    shared_loops = []
    for anc in mod.parent_chain(call):
        if anc is fn:
            break
        if isinstance(anc, ast.While):
            shared_loops.append(anc)
        elif isinstance(anc, (ast.For, ast.AsyncFor)):
            if base not in _target_names(anc.target):
                shared_loops.append(anc)
    loop_members = set()
    for loop in shared_loops:
        loop_members |= {id(n) for n in ast.walk(loop)}

    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name) and node.id == base
                and isinstance(node.ctx, ast.Load)):
            continue
        if id(node) in in_call:
            continue
        if not (_pos_after(node, call) or id(node) in loop_members):
            continue
        parent = mod.parents.get(id(node))
        if isinstance(parent, ast.Attribute) and parent.value is node:
            a = parent.attr
            grand = mod.parents.get(id(parent))
            is_method = isinstance(grand, ast.Call) and grand.func is parent
            if attr is not None:
                if a == attr:
                    out.append((node.lineno, f"re-reads '.{a}'"))
                elif a in _HOST_ATTRS or a in _VALUE_ATTRS \
                        or a in _SANITIZED_ATTRS:
                    continue  # a different, undonated buffer / host data
                elif is_method:
                    out.append((node.lineno,
                                f"calls '.{a}()' which may read the "
                                f"donated buffer"))
                else:
                    out.append((node.lineno, f"reads '.{a}'"))
            else:
                if a in _HOST_ATTRS:
                    continue
                out.append((node.lineno, f"reads '.{a}'"))
        else:
            out.append((node.lineno, "the object escapes"))
    return out


def _arg_base(expr: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """(base_name, attr) for ``b.columns`` / plain ``b`` arguments."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return expr.value.id, expr.attr
    if isinstance(expr, ast.Name):
        return expr.id, None
    return None


# --------------------------------------------------------------------------
# rule 1: trace-key-stability
# --------------------------------------------------------------------------

@register
class TraceKeyStabilityRule(Rule):
    name = "trace-key-stability"
    description = ("batch-varying values must not reach static argument "
                   "positions, be baked into traced closures, or rebuild "
                   "wrappers per loop iteration — each mints a new trace "
                   "(seen as jit_retraces under the same signature in "
                   "the device observatory)")

    def check(self, project: Project) -> Iterable[Violation]:
        model = build_model(project)
        seen: Set[Tuple[str, int, str]] = set()

        def emit(path: str, line: int, msg: str):
            key = (path, line, msg)
            if key not in seen:
                seen.add(key)
                yield Violation(self.name, path, line, msg)

        for site in model.sites:
            mod = model.modules[site.path]
            # (a) construction inside a loop: empty trace cache per pass
            for anc in mod.parent_chain(site.ctor):
                if site.enclosing_fn is not None and anc is site.enclosing_fn:
                    break
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                    yield from emit(
                        site.path, site.line,
                        f"jit site '{site.sig}' is constructed inside a "
                        f"loop — every construction starts an empty "
                        f"trace cache, so each iteration recompiles")
                    break
            # (b) batch-varying closure captures baked into the trace
            if site.fn_node is not None and site.enclosing_fn is not None:
                env = model.taint_env(site.enclosing_fn)
                for name in sorted(_free_loads(site.fn_node)):
                    for src_line, why in sorted(env.get(name, ())):
                        yield from emit(
                            site.path, site.line,
                            f"traced body of '{site.sig}' closes over "
                            f"'{name}' ({why} at line {src_line}) — the "
                            f"value is baked into the trace and every "
                            f"new value retraces")
            # (c) batch-varying values flowing into static positions
            static_pos = site.static_positions()
            static_kw = set(site.static_argnames)
            if not static_pos and not static_kw:
                continue
            for cs in site.calls:
                env = model.taint_env(cs.func)
                exprs: List[Tuple[str, ast.AST]] = []
                for p in sorted(static_pos):
                    if p < len(cs.node.args):
                        exprs.append((f"position {p}", cs.node.args[p]))
                for kw in cs.node.keywords:
                    if kw.arg in static_kw:
                        exprs.append((f"'{kw.arg}'", kw.value))
                for desc, expr in exprs:
                    for src_line, why in sorted(_expr_taint(expr, env)):
                        yield from emit(
                            cs.path, src_line,
                            f"static argument {desc} of jit site "
                            f"'{site.sig}' (called at line "
                            f"{cs.node.lineno}) takes a batch-varying "
                            f"value ({why}) — every distinct value "
                            f"mints a new trace; sanitize through "
                            f"round_capacity/pow2 bucketing or demote "
                            f"from the static set")


# --------------------------------------------------------------------------
# rule 2: donation-safety
# --------------------------------------------------------------------------

@register
class DonationSafetyRule(Rule):
    name = "donation-safety"
    description = ("donated buffers are deleted by XLA: flags reads after "
                   "the donating call (use-after-donation) and advises on "
                   "arguments provably dead after every call "
                   "(provably-safe-but-undonated)")

    def check(self, project: Project) -> Iterable[Violation]:
        model = build_model(project)
        for site in model.sites:
            mod = model.modules[site.path]
            static_pos = site.static_positions()
            if site.donate_argnums:
                yield from self._check_donated(site, mod)
                yield from self._advise_shared_base(site, mod, static_pos)
            else:
                yield from self._advise_fresh(site, mod, model, static_pos)

    def _check_donated(self, site: JitSite,
                       mod: _ModuleModel) -> Iterable[Violation]:
        for cs in site.calls:
            for p in site.donate_argnums:
                if p >= len(cs.node.args):
                    continue
                based = _arg_base(cs.node.args[p])
                if based is None:
                    continue
                base, attr = based
                for line, why in _reads_after(mod, cs.func, cs.node,
                                              base, attr):
                    arg = base if attr is None else f"{base}.{attr}"
                    yield Violation(
                        self.name, cs.path, line,
                        f"use-after-donation: argument {p} ('{arg}') of "
                        f"jit site '{site.sig}' is donated at line "
                        f"{cs.node.lineno}, but this {why} — the buffer "
                        f"is deleted by XLA after the call")

    def _advise_shared_base(self, site: JitSite, mod: _ModuleModel,
                            static_pos: Set[int]) -> Iterable[Violation]:
        """Undonated args sharing a donated arg's base and dead after
        every call can ride the same freshness proof."""
        if site.fn_params is None or not site.calls:
            return
        arity = len(site.fn_params)
        for p in range(arity):
            if p in site.donate_argnums or p in static_pos:
                continue
            proof = []
            for cs in site.calls:
                if p >= len(cs.node.args):
                    proof = None
                    break
                based = _arg_base(cs.node.args[p])
                if based is None or based[1] is None:
                    proof = None
                    break
                base, attr = based
                donated_bases = {
                    _arg_base(cs.node.args[d])[0]
                    for d in site.donate_argnums
                    if d < len(cs.node.args)
                    and _arg_base(cs.node.args[d]) is not None}
                if base not in donated_bases:
                    proof = None
                    break
                if _reads_after(mod, cs.func, cs.node, base, attr):
                    proof = None
                    break
                proof.append(f"'{base}.{attr}'")
            if proof is None:
                continue
            yield Violation(
                self.name, site.path, site.line,
                f"provably-safe-but-undonated: argument {p} "
                f"({', '.join(sorted(set(proof)))}) of jit site "
                f"'{site.sig}' shares the donated arguments' provenance "
                f"and is dead after every call site — extend "
                f"donate_argnums to include {p}")

    def _advise_fresh(self, site: JitSite, mod: _ModuleModel,
                      model: JitSiteModel,
                      static_pos: Set[int]) -> Iterable[Violation]:
        """Undonated sites whose inputs are freshly produced by another
        jit call in the same function and dead after every call."""
        if site.fn_params is None or not site.calls:
            return
        arity = len(site.fn_params)
        for p in range(arity):
            if p in static_pos:
                continue
            ok = bool(site.calls)
            names = set()
            for cs in site.calls:
                if cs.func is None or p >= len(cs.node.args):
                    ok = False
                    break
                based = _arg_base(cs.node.args[p])
                if based is None:
                    ok = False
                    break
                base, attr = based
                if not self._always_fresh(base, cs, mod, model):
                    ok = False
                    break
                if _reads_after(mod, cs.func, cs.node, base, attr):
                    ok = False
                    break
                names.add(base if attr is None else f"{base}.{attr}")
            if ok:
                yield Violation(
                    self.name, site.path, site.line,
                    f"provably-safe-but-undonated: argument {p} "
                    f"({', '.join(sorted(names))}) of jit site "
                    f"'{site.sig}' is freshly produced by another jit "
                    f"call and dead after every call site — donate it "
                    f"(donate_argnums=({p},)) to let XLA reuse the "
                    f"buffer")

    @staticmethod
    def _always_fresh(base: str, cs: CallSite, mod: _ModuleModel,
                      model: JitSiteModel) -> bool:
        """True when *base* is bound ONLY from jit-wrapper call results
        in the call's enclosing function (a fresh device buffer this
        function owns)."""
        wrappers = model.wrapper_names_in(
            cs.path, mod.enclosing_class_name(cs.node))
        found = False
        for node in _scope_nodes(cs.func):
            if isinstance(node, ast.Assign):
                bound = []
                for t in node.targets:
                    bound.extend(_target_names(t))
                if base not in bound:
                    continue
                value = node.value
                is_wrapper_call = (
                    isinstance(value, ast.Call)
                    and ((isinstance(value.func, ast.Name)
                          and value.func.id in wrappers)
                         or (isinstance(value.func, ast.Attribute)
                             and value.func.attr in wrappers)))
                if not is_wrapper_call:
                    return False
                found = True
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if base in _target_names(node.target):
                    return False
            elif isinstance(node, ast.AugAssign):
                if base in _target_names(node.target):
                    return False
        return found


# --------------------------------------------------------------------------
# rule 3: host-device-boundary
# --------------------------------------------------------------------------

@register
class HostDeviceBoundaryRule(Rule):
    name = "host-device-boundary"
    description = ("traced bodies must stay on-device (no host numpy, "
                   ".tolist/.item, float()/int()/bool() concretization, "
                   "or float64 promotion); device_get/device_put outside "
                   "the accounted materialization sites must call "
                   "record_transfer")

    def check(self, project: Project) -> Iterable[Violation]:
        model = build_model(project)
        seen_bodies: Set[int] = set()
        for site in model.sites:
            if site.fn_node is None or id(site.fn_node) in seen_bodies:
                continue
            seen_bodies.add(id(site.fn_node))
            mod = model.modules[site.path]
            yield from self._check_body(site, mod)
        for path, mod in model.modules.items():
            yield from self._check_transfers(mod)

    def _check_body(self, site: JitSite,
                    mod: _ModuleModel) -> Iterable[Violation]:
        numpy_names = {local for local, target in mod.aliases.items()
                       if target == "numpy"}
        body = site.fn_node.body
        stmts = body if isinstance(body, list) else [body]
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    func = node.func
                    if isinstance(func, ast.Attribute):
                        root = func.value
                        while isinstance(root, ast.Attribute):
                            root = root.value
                        if isinstance(root, ast.Name) \
                                and root.id in numpy_names:
                            yield Violation(
                                self.name, site.path, node.lineno,
                                f"host numpy call "
                                f"'{dotted_name(func)}' inside the "
                                f"traced body of '{site.sig}' — "
                                f"materializes on host under jit")
                        if func.attr in ("tolist", "item"):
                            yield Violation(
                                self.name, site.path, node.lineno,
                                f"'.{func.attr}()' inside the traced "
                                f"body of '{site.sig}' forces a "
                                f"device->host sync per trace")
                        if func.attr == "astype" and node.args \
                                and isinstance(node.args[0], ast.Name) \
                                and node.args[0].id == "float":
                            yield Violation(
                                self.name, site.path, node.lineno,
                                f"astype(float) inside the traced body "
                                f"of '{site.sig}' promotes to float64 "
                                f"(weak-typed python float)")
                    elif isinstance(func, ast.Name) \
                            and func.id in ("float", "int", "bool"):
                        yield Violation(
                            self.name, site.path, node.lineno,
                            f"'{func.id}()' inside the traced body of "
                            f"'{site.sig}' concretizes a tracer — "
                            f"aborts tracing or forces a host sync")
                elif isinstance(node, ast.Attribute) \
                        and node.attr == "float64":
                    yield Violation(
                        self.name, site.path, node.lineno,
                        f"float64 inside the traced body of "
                        f"'{site.sig}' — x64 promotion doubles "
                        f"transfer bytes and splits the trace-key "
                        f"space")

    def _check_transfers(self, mod: _ModuleModel) -> Iterable[Violation]:
        jax_names = {local for local, target in mod.aliases.items()
                     if target == "jax"}
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if mod.enclosing_function(node) is not None:
                continue  # nested defs are covered by their outer walk
            transfers: List[Tuple[int, str]] = []
            accounted = False
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dn = dotted_name(sub.func) or ""
                leaf = dn.rsplit(".", 1)[-1]
                if leaf == "record_transfer":
                    accounted = True
                elif leaf in ("device_get", "device_put") and (
                        "." not in dn or dn.split(".", 1)[0] in jax_names):
                    transfers.append((sub.lineno, leaf))
            if transfers and not accounted:
                for line, leaf in transfers:
                    yield Violation(
                        self.name, mod.path, line,
                        f"'{leaf}' in '{node.name}' without a "
                        f"record_transfer call — the transfer is "
                        f"invisible to the device observatory's byte "
                        f"accounting (models/batch.py shows the "
                        f"sanctioned pattern)")


# --------------------------------------------------------------------------
# rule 4: fusion-verdict-consistency
# --------------------------------------------------------------------------

@register
class FusionVerdictConsistencyRule(Rule):
    name = "fusion-verdict-consistency"
    description = ("compile/fuse.py's operator allowlist, _op_verdict "
                   "branches, fused.py kernel builders, and chains.py "
                   "reason tables must agree with the operator classes "
                   "that exist (and consult host_mode where the class "
                   "has one)")

    def check(self, project: Project) -> Iterable[Violation]:
        pkg = project.package
        fuse = project.file(f"{pkg}/compile/fuse.py")
        if fuse is None or fuse.tree is None:
            return
        fused = project.file(f"{pkg}/compile/fused.py")
        chains = project.file(f"{pkg}/compile/chains.py")
        classes = self._class_index(project)
        model = build_model(project)

        allow, allow_line = self._allowlist(fuse)
        verdicts = self._verdict_branches(fuse)
        builder_names = self._referenced_names(fused)

        impure: Dict[str, List[Violation]] = {}
        body_rule = HostDeviceBoundaryRule()
        for site in model.sites:
            if site.scope_key == "<module>" or site.fn_node is None:
                continue
            mod = model.modules[site.path]
            hits = list(body_rule._check_body(site, mod))
            if hits:
                impure.setdefault(site.scope_key, []).extend(hits)

        for name in sorted(allow):
            if name not in classes:
                yield Violation(
                    self.name, fuse.path, allow_line,
                    f"allowlisted operator '{name}' is not a class "
                    f"under ops/ or compile/ — stale allowlist entry")
                continue
            if name not in builder_names:
                yield Violation(
                    self.name, fuse.path, allow_line,
                    f"allowlisted operator '{name}' has no kernel "
                    f"builder in compile/fused.py — fusion would fail "
                    f"at stage resolution")
            if name not in verdicts:
                yield Violation(
                    self.name, fuse.path, allow_line,
                    f"allowlisted operator '{name}' has no per-node "
                    f"branch in _op_verdict — nodes fuse without a "
                    f"doubt check")
            elif classes[name][1] and not verdicts[name]:
                yield Violation(
                    self.name, fuse.path, allow_line,
                    f"'{name}' has a host_mode escape hatch but its "
                    f"_op_verdict branch never consults it — host-mode "
                    f"nodes would fuse onto the device path")
            for v in impure.get(name, ()):
                yield Violation(
                    self.name, v.path, v.line,
                    f"allowlisted operator '{name}' builds an impure "
                    f"device closure: {v.message}")

        if chains is not None and chains.tree is not None:
            for table in ("UNFUSABLE", "STATIC_REASONS"):
                for name, line in self._table_names(chains, table):
                    if name not in classes:
                        yield Violation(
                            self.name, chains.path, line,
                            f"{table} names '{name}', which is not a "
                            f"class under ops/ or compile/ — stale "
                            f"chain-table entry")

    @staticmethod
    def _class_index(project: Project) -> Dict[str, Tuple[str, bool]]:
        """class name -> (path, has host_mode) over ops/ + compile/."""
        out: Dict[str, Tuple[str, bool]] = {}
        pkg = project.package
        for sf in project.source_files():
            sub = sf.path[len(pkg) + 1:] if sf.path.startswith(pkg + "/") \
                else sf.path
            if not (sub.startswith("ops/") or sub.startswith("compile/")):
                continue
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    has_hm = any(
                        isinstance(n, (ast.Attribute, ast.arg, ast.Name))
                        and (getattr(n, "attr", None) == "host_mode"
                             or getattr(n, "arg", None) == "host_mode"
                             or getattr(n, "id", None) == "host_mode")
                        for n in ast.walk(node))
                    out[node.name] = (sf.path, has_hm)
        return out

    @staticmethod
    def _allowlist(fuse: SourceFile) -> Tuple[Set[str], int]:
        for node in ast.walk(fuse.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "DEFAULT_OPERATORS"
                            for t in node.targets):
                try:
                    names = ast.literal_eval(
                        node.value.args[0]
                        if isinstance(node.value, ast.Call)
                        and node.value.args else node.value)
                except (ValueError, SyntaxError, AttributeError):
                    return set(), node.lineno
                return {n for n in names if isinstance(n, str)}, \
                    node.lineno
        return set(), 0

    @staticmethod
    def _verdict_branches(fuse: SourceFile) -> Dict[str, bool]:
        """class name -> its _op_verdict branch mentions host_mode."""
        out: Dict[str, bool] = {}
        for node in ast.walk(fuse.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "_op_verdict":
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.If):
                        continue
                    names = []
                    for c in ast.walk(sub.test):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Name) \
                                and c.func.id == "isinstance" \
                                and len(c.args) == 2:
                            cls = c.args[1]
                            if isinstance(cls, ast.Name):
                                names.append(cls.id)
                            elif isinstance(cls, ast.Tuple):
                                names += [e.id for e in cls.elts
                                          if isinstance(e, ast.Name)]
                    if not names:
                        continue
                    branch_hm = any(
                        getattr(n, "attr", None) == "host_mode"
                        for b in sub.body for n in ast.walk(b)) or any(
                        getattr(n, "attr", None) == "host_mode"
                        for n in ast.walk(sub.test))
                    for n in names:
                        out[n] = out.get(n, False) or branch_hm
        return out

    @staticmethod
    def _referenced_names(fused: Optional[SourceFile]) -> Set[str]:
        if fused is None or fused.tree is None:
            return set()
        return {n.id for n in ast.walk(fused.tree)
                if isinstance(n, ast.Name)}

    @staticmethod
    def _table_names(chains: SourceFile,
                     table: str) -> List[Tuple[str, int]]:
        for node in ast.walk(chains.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == table
                            for t in node.targets):
                try:
                    val = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return []
                names = list(val.keys()) if isinstance(val, dict) \
                    else list(val)
                return [(n, node.lineno) for n in names
                        if isinstance(n, str)]
        return []


# --------------------------------------------------------------------------
# rule 5: deprecated-jax-api
# --------------------------------------------------------------------------

@register
class DeprecatedJaxApiRule(Rule):
    name = "deprecated-jax-api"
    description = ("jax.shard_map does not exist in jax 0.4.x — flags "
                   "the stale calling convention with its remediation "
                   "(the root cause of the standing multi-device test "
                   "failures)")

    _REMEDIATION = (
        "'jax.shard_map' is not an attribute in jax 0.4.x — this raises "
        "AttributeError at dispatch time (the 47 standing tier-1 "
        "failures in tests/test_parallel.py and test_udf.py).  Port to "
        "jax.experimental.shard_map.shard_map(f, mesh=..., in_specs=..., "
        "out_specs=...) — same kwargs, verified against the pinned jax — "
        "or pjit with shardings (ROADMAP #1)")

    def check(self, project: Project) -> Iterable[Violation]:
        for sf in project.source_files():
            if sf.tree is None:
                continue
            jax_names = {local for local, target
                         in import_aliases(sf.tree).items()
                         if target == "jax"}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Attribute) \
                        and node.attr == "shard_map" \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in jax_names:
                    yield Violation(self.name, sf.path, node.lineno,
                                    self._REMEDIATION)
                elif isinstance(node, ast.ImportFrom) \
                        and node.module == "jax" \
                        and any(a.name == "shard_map"
                                for a in node.names):
                    yield Violation(self.name, sf.path, node.lineno,
                                    self._REMEDIATION)
