"""CLI runner: ``python -m arrow_ballista_tpu.analysis``.

Exit status 0 = clean, 1 = violations found, 2 = usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from .framework import (all_rules, json_report, run_lints, sarif_report,
                        text_report)


def default_root() -> str:
    # .../repo/arrow_ballista_tpu/analysis/__main__.py -> repo
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m arrow_ballista_tpu.analysis",
        description="Run the project's static-analysis lint suite.")
    parser.add_argument("--root", default=default_root(),
                        help="repo root to analyze (default: this checkout)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report instead of text")
    parser.add_argument("--sarif", action="store_true",
                        help="emit a SARIF 2.1.0 log (for CI inline "
                             "annotation); takes precedence over --json")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0

    rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                  if args.rules else None)
    try:
        violations = run_lints(args.root, rule_names=rule_names)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.sarif:
        print(sarif_report(violations))
    else:
        print(json_report(violations) if args.json
              else text_report(violations))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
