"""Whole-program concurrency analysis: guarded-by, lock order, handoffs.

The Rust reference gets data-race freedom from the borrow checker; this
module rebuilds the useful fraction of that guarantee as an
interprocedural AST pass.  It builds one package-wide model —

  * a **lock inventory**: every ``threading.Lock``/``RLock``/``Condition``
    attribute per class (a ``Condition(self._lock)`` aliases the lock it
    wraps) plus module-level locks,
  * a **held-set map**: for every read/write of a ``self.*`` attribute,
    the set of locks statically held at that point (``with self._lock:``
    scopes, propagated one level through ``*_locked`` helper calls — the
    repo's 'caller holds the lock' convention),
  * a **thread-entry classification**: methods that run on a thread other
    than their caller's — ``Thread(target=self.m)`` targets, ``self.m``
    escaping as a callback argument or container element (the EventLoop
    handler, RPC/REST route tables), ``do_*`` HTTP handlers, and nested
    ``def`` closures (launch-pool / timer bodies),
  * a **lock-acquisition graph**: edges ``A -> B`` when ``B`` can be
    acquired while ``A`` is held, including interprocedural acquisitions
    reached through ``self.m()`` and typed-attribute calls
    (``self.cluster.register(...)`` resolving to ``ClusterState``).

Four rules read the model (rule names in brackets):

``guarded-by``        an attribute of a lock-holding class written outside
                      ``__init__``, touched from a thread entry point and
                      from at least one other method, with no single lock
                      common to all access sites.  Exemptions: a
                      ``# ballista: guarded-by=<lock>`` annotation on any
                      assignment to the attribute (documents the guard the
                      analyzer cannot prove; the named lock must exist),
                      ``guarded-by=none`` (documented single-writer or
                      benign-race field), and the ``ATOMIC_SWAP`` allowlist
                      (fields replaced wholesale with immutable snapshots,
                      e.g. ``ExecutionGraph.stats`` — readers see either
                      the old or the new object, never a torn one).
``lock-order``        any cycle in the acquisition graph (potential
                      deadlock), including one-lock self-cycles for
                      non-reentrant ``Lock``s.
``event-loop-handoff``a mutable object posted into an EventLoop and then
                      mutated by the posting thread after the post — the
                      consumer may observe the mutation mid-read.
``thread-lifecycle``  every ``threading.Thread(...)`` carries an explicit
                      ``daemon=`` decision, and a thread stored on
                      ``self`` has a bounded ``join(timeout=...)``
                      somewhere in its class (shutdown must not hang).

The same model feeds the runtime validator (``analysis/lock_order.py``):
``build_model()`` exposes lock declaration sites keyed by (path, line), so
locks observed at runtime (keyed by their creation frame) map back to
static identities and the observed acquisition order can be checked
against the static graph.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .framework import (
    Project,
    Rule,
    SourceFile,
    Violation,
    dotted_name,
    import_aliases,
    register,
)

PKG = "arrow_ballista_tpu"

#: fields replaced wholesale with a freshly built (effectively immutable)
#: object — the atomic-swap pattern.  Readers racing the swap see either
#: the old or the new snapshot; no lock is needed.  Keyed "Class.attr".
ATOMIC_SWAP: Set[str] = {
    # RuntimeStatsStore: fold_stage() builds a new per-stage summary and
    # binds it in one dict.__setitem__; readers only traverse snapshots.
    "ExecutionGraph.stats",
}

_GUARD_RE = re.compile(r"#\s*ballista:\s*guarded-by=([A-Za-z0-9_]+)")

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_COND_CTOR = "threading.Condition"
_MUTATORS = {"append", "pop", "clear", "update", "setdefault", "add",
             "remove", "extend", "popitem", "insert", "discard",
             "appendleft", "popleft"}

#: (path, class, attr) — '' class means module scope
LockId = Tuple[str, str, str]
#: (path, class, method) — '' class means module-level function
MethodKey = Tuple[str, str, str]


class _Access:
    __slots__ = ("attr", "write", "held", "line")

    def __init__(self, attr: str, write: bool, held: FrozenSet[str], line: int):
        self.attr, self.write, self.held, self.line = attr, write, held, line


class _Method:
    """Per-method facts: accesses, calls, and lock acquisitions, each with
    the set of class-local lock tokens held at that point."""

    def __init__(self, name: str, line: int, closure: bool = False):
        self.name = name
        self.line = line
        self.closure = closure  # nested def: runs later, often on another thread
        self.accesses: List[_Access] = []
        # (callee method name, held, line) for self.m(...)
        self.self_calls: List[Tuple[str, FrozenSet[str], int]] = []
        # (self attr, callee method, held, line) for self.attr.m(...)
        self.attr_calls: List[Tuple[str, str, FrozenSet[str], int]] = []
        # (module-level function name, held, line)
        self.fn_calls: List[Tuple[str, FrozenSet[str], int]] = []
        # (lock token, held-before, line)
        self.acquisitions: List[Tuple[str, FrozenSet[str], int]] = []
        # extra locks callers provably hold (``*_locked`` convention)
        self.assumed_held: FrozenSet[str] = frozenset()


class _ClassModel:
    def __init__(self, path: str, name: str):
        self.path = path
        self.name = name
        self.locks: Dict[str, int] = {}        # lock attr -> decl line
        self.rlocks: Set[str] = set()          # subset of locks: reentrant
        self.cond_alias: Dict[str, str] = {}   # condition attr -> wrapped lock
        self.guards: Dict[str, Tuple[str, int]] = {}  # attr -> (decl, line)
        self.attr_types: Dict[str, str] = {}   # self.attr -> class simple name
        self.containers: Set[str] = set()      # attrs holding dict/list/set/deque
        self.methods: Dict[str, _Method] = {}
        self.entries: Set[str] = set()         # thread-entry method names

    def lock_token(self, attr: str) -> Optional[str]:
        """Normalize an attribute to its lock token (conditions alias the
        lock they wrap)."""
        if attr in self.locks:
            return attr
        if attr in self.cond_alias:
            return self.cond_alias[attr]
        return None

    def all_lock_names(self) -> Set[str]:
        return set(self.locks) | set(self.cond_alias)


class _ModuleModel:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.locks: Dict[str, int] = {}        # module-level NAME -> line
        self.rlocks: Set[str] = set()
        self.classes: Dict[str, _ClassModel] = {}
        self.functions: Dict[str, _Method] = {}


class ConcurrencyModel:
    """The package-wide model all concurrency rules (and the runtime
    lock-order validator) read."""

    def __init__(self) -> None:
        self.modules: Dict[str, _ModuleModel] = {}
        # class simple name -> (path, class name); ambiguous names dropped
        self.class_index: Dict[str, Tuple[str, str]] = {}
        # (path, line of the lock-creating assignment) -> LockId
        self.decl_sites: Dict[Tuple[str, int], LockId] = {}
        # acquisition-order edges with first-seen provenance
        self.edges: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
        # lock ids that are reentrant (RLock) — self-cycles are fine
        self.reentrant: Set[LockId] = set()

    # --- graph helpers ---------------------------------------------------
    def add_edge(self, a: LockId, b: LockId, path: str, line: int) -> None:
        if (a, b) not in self.edges:
            self.edges[(a, b)] = (path, line)

    def successors(self, a: LockId) -> List[LockId]:
        return [b for (x, b) in self.edges if x == a]

    def has_path(self, a: LockId, b: LockId) -> bool:
        """True when ``b`` is reachable from ``a`` (including a == b via a
        cycle edge; trivially True when a == b and a self-edge exists)."""
        seen = {a}
        stack = [a]
        while stack:
            for nxt in self.successors(stack.pop()):
                if nxt == b:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False


def fmt_lock(lock: LockId) -> str:
    path, cls, attr = lock
    return f"{path}:{cls + '.' if cls else ''}{attr}"


# --------------------------------------------------------------------------
# model construction
# --------------------------------------------------------------------------

def build_model(project: Project) -> ConcurrencyModel:
    model = ConcurrencyModel()
    ambiguous: Set[str] = set()
    for sf in project.source_files():
        if sf.tree is None:
            continue
        mm = _build_module(sf)
        model.modules[sf.path] = mm
        for cname in mm.classes:
            if cname in model.class_index or cname in ambiguous:
                model.class_index.pop(cname, None)
                ambiguous.add(cname)
            else:
                model.class_index[cname] = (sf.path, cname)
    _collect_locks(model)
    _apply_locked_convention(model)
    _propagate_entries(model)
    _build_edges(model)
    return model


def _infer_ctor_class(value: ast.expr) -> Optional[str]:
    """Class simple name when ``value`` constructs one: ``Foo()``,
    ``mod.Foo()``, ``arg or Foo()``, ``Foo() if c else Bar()`` (first
    constructed operand wins)."""
    if isinstance(value, ast.Call):
        d = dotted_name(value.func)
        if d is None:
            return None
        if "." not in d and d[:1].isupper():
            return d
        last = d.split(".")[-1]
        if d[:1].islower() and last[:1].isupper():
            return last
        return None
    if isinstance(value, ast.BoolOp):
        for operand in value.values:
            typ = _infer_ctor_class(operand)
            if typ is not None:
                return typ
        return None
    if isinstance(value, ast.IfExp):
        return (_infer_ctor_class(value.body)
                or _infer_ctor_class(value.orelse))
    return None


def _resolve_ctor(aliases: Dict[str, str], call: ast.Call) -> Optional[str]:
    d = dotted_name(call.func)
    if d is None:
        return None
    root = d.split(".")[0]
    return d.replace(root, aliases.get(root, root), 1)


def _build_module(sf: SourceFile) -> _ModuleModel:
    mm = _ModuleModel(sf)
    aliases = import_aliases(sf.tree)
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            full = _resolve_ctor(aliases, node.value)
            if full in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        mm.locks[t.id] = node.lineno
                        if full.endswith("RLock"):
                            mm.rlocks.add(t.id)
        if isinstance(node, ast.ClassDef):
            mm.classes[node.name] = _build_class(sf, node, aliases)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            meth = _Method(node.name, node.lineno)
            _Walker(sf, None, mm, aliases, meth).walk(node.body, frozenset())
            mm.functions[node.name] = meth
    # module-level singleton (``STATS = DataPlaneStats()``): the instance
    # is importable from any thread, so every public method is an entry
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            d = dotted_name(node.value.func)
            if d is not None and d in mm.classes:
                cm = mm.classes[d]
                cm.entries |= {m for m in cm.methods
                               if not m.startswith("_")}
    return mm


def _build_class(sf: SourceFile, cls: ast.ClassDef,
                 aliases: Dict[str, str]) -> _ClassModel:
    cm = _ClassModel(sf.path, cls.name)
    # pass 1: lock inventory, guard annotations, attribute types, entries
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                or isinstance(node, ast.AnnAssign):
            t = node.targets[0] if isinstance(node, ast.Assign) \
                else node.target
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and node.value is not None):
                m = _GUARD_RE.search(sf.lines[node.lineno - 1]) \
                    if node.lineno - 1 < len(sf.lines) else None
                if m:
                    cm.guards[t.attr] = (m.group(1), node.lineno)
                if isinstance(node.value, (ast.Dict, ast.List, ast.Set,
                                           ast.ListComp, ast.DictComp,
                                           ast.SetComp)):
                    cm.containers.add(t.attr)
                if isinstance(node.value, ast.Call):
                    ctor = dotted_name(node.value.func)
                    if ctor is not None and ctor.split(".")[-1] in (
                            "dict", "list", "set", "deque", "defaultdict",
                            "OrderedDict", "Counter"):
                        cm.containers.add(t.attr)
                    full = _resolve_ctor(aliases, node.value)
                    if full in _LOCK_CTORS:
                        cm.locks[t.attr] = node.lineno
                        if full.endswith("RLock"):
                            cm.rlocks.add(t.attr)
                    elif full == _COND_CTOR:
                        arg = node.value.args[0] if node.value.args else None
                        if (isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"):
                            cm.cond_alias[t.attr] = arg.attr
                        else:
                            cm.locks[t.attr] = node.lineno
                    else:
                        typ = _infer_ctor_class(node.value)
                        if typ is not None:
                            cm.attr_types[t.attr] = typ
                elif isinstance(node.value, (ast.BoolOp, ast.IfExp)):
                    # `self.store = store or MemoryKv()` and conditional
                    # defaults: any constructed operand names the type
                    typ = _infer_ctor_class(node.value)
                    if typ is not None:
                        cm.attr_types[t.attr] = typ
    # conditions wrapping an attr created later (or never) fall back to
    # being their own lock token
    for cond, wrapped in list(cm.cond_alias.items()):
        if wrapped not in cm.locks:
            del cm.cond_alias[cond]
            cm.locks[cond] = cm.locks.get(cond, 0)
    method_names = {n.name for n in cls.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    _classify_entries(cls, method_names, aliases, cm)
    # pass 2: per-method walk
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            meth = _Method(node.name, node.lineno)
            cm.methods[node.name] = meth
            mm_stub = _ModuleModel(sf)  # module locks resolved later via name
            _Walker(sf, cm, mm_stub, aliases, meth).walk(node.body, frozenset())
            cm.methods.update(mm_stub.functions)  # closures registered here
    return cm


def _classify_entries(cls: ast.ClassDef, method_names: Set[str],
                      aliases: Dict[str, str], cm: _ClassModel) -> None:
    """Thread-entry methods: Thread targets, escaped ``self.m`` references
    (callbacks / route tables), ``do_*`` HTTP handlers."""
    for name in method_names:
        if name.startswith("do_"):
            cm.entries.add(name)
    call_funcs = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            call_funcs.add(id(node.func))
            full = _resolve_ctor(aliases, node)
            if full == "threading.Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        d = dotted_name(kw.value)
                        if d is not None and d.startswith("self."):
                            cm.entries.add(d.split(".", 1)[1])
    for node in ast.walk(cls):
        if (isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr in method_names
                and id(node) not in call_funcs):
            # a bare ``self.m`` escaping the class: some other component
            # will call it, usually from its own thread
            cm.entries.add(node.attr)


class _Walker:
    """Statement walker tracking the held-lock set through ``with`` scopes.

    Records attribute accesses, lock acquisitions, and call sites into the
    given ``_Method``.  Nested ``def``s become pseudo-methods named
    ``outer.inner`` marked as closures (potentially another thread)."""

    def __init__(self, sf: SourceFile, cm: Optional[_ClassModel],
                 mm: _ModuleModel, aliases: Dict[str, str], meth: _Method):
        self.sf = sf
        self.cm = cm
        self.mm = mm
        self.aliases = aliases
        self.meth = meth

    def _token(self, expr: ast.expr) -> Optional[str]:
        """Lock token for a with-item / call receiver, or None."""
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cm is not None):
            return self.cm.lock_token(expr.attr)
        if isinstance(expr, ast.Name) and expr.id in self.mm.locks:
            return f"::{expr.id}"  # module-lock marker
        return None

    def walk(self, stmts: List[ast.stmt], held: FrozenSet[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = _Method(f"{self.meth.name}.{stmt.name}", stmt.lineno,
                                closure=True)
                self.mm.functions[inner.name] = inner
                _Walker(self.sf, self.cm, self.mm, self.aliases, inner) \
                    .walk(stmt.body, frozenset())
                continue
            if isinstance(stmt, ast.With):
                new_held = held
                for item in stmt.items:
                    tok = self._token(item.context_expr)
                    if tok is not None:
                        self.meth.acquisitions.append(
                            (tok, new_held, stmt.lineno))
                        new_held = new_held | {tok}
                    else:
                        self._scan_expr(item.context_expr, held)
                self.walk(stmt.body, new_held)
                continue
            self._writes(stmt, held)
            for field in ("test", "iter", "value", "exc", "msg"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, ast.expr):
                    self._scan_expr(sub, held)
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.Expr, ast.Return, ast.Delete)):
                self._scan_expr(stmt, held, skip_value=True)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    self.walk(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                self.walk(handler.body, held)

    # --- writes -----------------------------------------------------------
    def _writes(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in self._flatten(targets):
            if isinstance(t, ast.Subscript):
                t = t.value
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                self.meth.accesses.append(
                    _Access(t.attr, True, held, stmt.lineno))

    @staticmethod
    def _flatten(targets: List[ast.AST]) -> List[ast.AST]:
        out: List[ast.AST] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                out.extend(t.elts)
            else:
                out.append(t)
        return out

    # --- reads and calls --------------------------------------------------
    def _scan_expr(self, root: ast.AST, held: FrozenSet[str],
                   skip_value: bool = False) -> None:
        if skip_value:
            nodes: List[ast.AST] = []
            for field, value in ast.iter_fields(root):
                if field in ("targets", "target"):
                    # write targets already recorded; but their Subscript
                    # slices are reads
                    for t in (value if isinstance(value, list) else [value]):
                        if isinstance(t, ast.Subscript):
                            nodes.append(t.slice)
                elif isinstance(value, ast.AST):
                    nodes.append(value)
                elif isinstance(value, list):
                    nodes.extend(v for v in value if isinstance(v, ast.AST))
        else:
            nodes = [root]
        for n in nodes:
            for node in ast.walk(n):
                if isinstance(node, ast.Call):
                    self._record_call(node, held)
                elif (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    self.meth.accesses.append(
                        _Access(node.attr, False, held, node.lineno))

    def _record_call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        f = node.func
        d = dotted_name(f)
        if d is None:
            return
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            self.meth.self_calls.append((parts[1], held, node.lineno))
        elif parts[0] == "self" and len(parts) == 3:
            attr, m = parts[1], parts[2]
            # a mutator-method call writes the attr only when the attr is a
            # known container — ``self.quarantine.remove(id)`` on a helper
            # object is that object's business, not a dict mutation here
            if m in _MUTATORS and self.cm is not None \
                    and attr in self.cm.containers:
                self.meth.accesses.append(
                    _Access(attr, True, held, node.lineno))
            self.meth.attr_calls.append((attr, m, held, node.lineno))
        elif len(parts) == 1:
            self.meth.fn_calls.append((parts[0], held, node.lineno))
        elif len(parts) == 2 and parts[1] in _MUTATORS:
            pass  # local-variable mutation: out of scope for self-attrs


# --------------------------------------------------------------------------
# post-passes
# --------------------------------------------------------------------------

def _collect_locks(model: ConcurrencyModel) -> None:
    for path, mm in model.modules.items():
        for name, line in mm.locks.items():
            lid: LockId = (path, "", name)
            model.decl_sites[(path, line)] = lid
            if name in mm.rlocks:
                model.reentrant.add(lid)
        for cname, cm in mm.classes.items():
            for attr, line in cm.locks.items():
                lid = (path, cname, attr)
                if line:
                    model.decl_sites[(path, line)] = lid
                if attr in cm.rlocks:
                    model.reentrant.add(lid)


def _apply_locked_convention(model: ConcurrencyModel) -> None:
    """``*_locked`` helpers run with whatever locks every intra-class call
    site holds (intersection); with no visible call site, assume all class
    locks — the convention says the caller is responsible."""
    for mm in model.modules.values():
        for cm in mm.classes.values():
            for name, meth in cm.methods.items():
                base = name.rsplit(".", 1)[-1]
                if not base.endswith("_locked"):
                    continue
                sites = [held for other in cm.methods.values()
                         for (callee, held, _ln) in other.self_calls
                         if callee == base and other is not meth]
                if sites:
                    common = frozenset.intersection(*map(frozenset, sites))
                else:
                    common = frozenset(cm.locks)
                meth.assumed_held = common


def _propagate_entries(model: ConcurrencyModel) -> None:
    """One level: a method called via ``self.m()`` from a thread-entry
    method also runs on that thread."""
    for mm in model.modules.values():
        for cm in mm.classes.values():
            extra: Set[str] = set()
            for name in cm.entries:
                meth = cm.methods.get(name)
                if meth is None:
                    continue
                for callee, _held, _ln in meth.self_calls:
                    if callee in cm.methods:
                        extra.add(callee)
            cm.entries |= extra


def _method_key_iter(model: ConcurrencyModel):
    for path, mm in model.modules.items():
        for fname, meth in mm.functions.items():
            yield (path, "", fname), meth, None, mm
        for cname, cm in mm.classes.items():
            for mname, meth in cm.methods.items():
                yield (path, cname, mname), meth, cm, mm


def _build_edges(model: ConcurrencyModel) -> None:
    """Acquisition-order edges: direct nesting plus interprocedural
    acquisitions (fixpoint over the self/typed-attr/module call graph), so
    the static graph predicts every order the runtime shim can observe."""
    methods: Dict[MethodKey, _Method] = {}
    owner: Dict[MethodKey, Tuple[Optional[_ClassModel], _ModuleModel]] = {}
    for key, meth, cm, mm in _method_key_iter(model):
        methods[key] = meth
        owner[key] = (cm, mm)

    def norm(tok: str, path: str, cm: Optional[_ClassModel]) -> LockId:
        if tok.startswith("::"):
            return (path, "", tok[2:])
        return (path, cm.name if cm else "", tok)

    def callees(key: MethodKey) -> List[Tuple[MethodKey, FrozenSet[str], int]]:
        path, cname, _ = key
        cm, mm = owner[key]
        meth = methods[key]
        out = []
        for callee, held, ln in meth.self_calls:
            k = (path, cname, callee)
            if k in methods:
                out.append((k, held, ln))
        for attr, m, held, ln in meth.attr_calls:
            if cm is None or attr not in cm.attr_types:
                continue
            target = model.class_index.get(cm.attr_types[attr])
            if target is None:
                continue
            k = (target[0], target[1], m)
            if k in methods:
                out.append((k, held, ln))
        for fname, held, ln in meth.fn_calls:
            k = (path, "", fname)
            if k in methods:
                out.append((k, held, ln))
        return out

    # fixpoint: full set of locks a call into `key` may acquire
    acq: Dict[MethodKey, Set[LockId]] = {}
    for key, meth in methods.items():
        cm, _mm = owner[key]
        acq[key] = {norm(t, key[0], cm) for (t, _h, _ln) in meth.acquisitions}
    changed = True
    while changed:
        changed = False
        for key in methods:
            for k, _held, _ln in callees(key):
                before = len(acq[key])
                acq[key] |= acq[k]
                if len(acq[key]) != before:
                    changed = True

    for key, meth in methods.items():
        cm, _mm = owner[key]
        path = key[0]
        base_held = meth.assumed_held
        for tok, held, ln in meth.acquisitions:
            t = norm(tok, path, cm)
            for h in held | base_held:
                model.add_edge(norm(h, path, cm), t, path, ln)
        for k, held, ln in callees(key):
            eff = held | base_held
            if not eff:
                continue
            for t in acq[k]:
                for h in eff:
                    model.add_edge(norm(h, path, cm), t, path, ln)


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------

# One (project, model) pair at a time.  Keyed by the live Project object
# itself, not id(): a collected project's id() can be reused by a new
# Project, which would serve a model built from another tree's sources.
_MODEL_CACHE: List[Tuple[Project, ConcurrencyModel]] = []


def _model_for(project: Project) -> ConcurrencyModel:
    if not (_MODEL_CACHE and _MODEL_CACHE[0][0] is project):
        _MODEL_CACHE[:] = [(project, build_model(project))]
    return _MODEL_CACHE[0][1]


@register
class GuardedByRule(Rule):
    """Attributes of lock-holding classes reached from thread entry points
    must have one lock common to every access site, a ``guarded-by=``
    annotation naming the external guard, a ``guarded-by=none``
    single-writer justification, or an ATOMIC_SWAP listing."""

    name = "guarded-by"
    description = ("shared attributes of lock-holding classes accessed "
                   "from thread entries under a consistent lock")

    def check(self, project: Project) -> Iterable[Violation]:
        model = _model_for(project)
        for path, mm in sorted(model.modules.items()):
            for cname, cm in sorted(mm.classes.items()):
                if not cm.locks and not cm.cond_alias:
                    continue
                yield from self._check_class(cm)

    def _check_class(self, cm: _ClassModel) -> Iterable[Violation]:
        lock_names = cm.all_lock_names()
        # attr -> list of (method, access)
        sites: Dict[str, List[Tuple[_Method, _Access]]] = {}
        for mname, meth in cm.methods.items():
            if mname == "__init__" or mname.startswith("__init__."):
                continue
            for acc in meth.accesses:
                if acc.attr in lock_names or acc.attr.startswith("__"):
                    continue
                sites.setdefault(acc.attr, []).append((meth, acc))
        for attr in sorted(sites):
            guard = cm.guards.get(attr)
            if guard is not None:
                decl, line = guard
                if decl != "none" and cm.lock_token(decl) is None:
                    yield Violation(
                        self.name, cm.path, line,
                        f"{cm.name}.{attr} is annotated guarded-by={decl} "
                        f"but {cm.name} has no lock attribute {decl!r}")
                continue
            if f"{cm.name}.{attr}" in ATOMIC_SWAP:
                continue
            accs = sites[attr]
            writes = [(m, a) for (m, a) in accs if a.write]
            if not writes:
                continue
            methods_touching = {m.name for (m, a) in accs}
            if len(methods_touching) < 2:
                continue
            if not any(self._on_other_thread(cm, m) for (m, _a) in accs):
                continue
            held_sets = [a.held | m.assumed_held for (m, a) in accs]
            common = frozenset.intersection(*held_sets)
            if common:
                continue
            first = min(writes, key=lambda p: p[1].line)
            entry_names = sorted({m.name for (m, _a) in accs
                                  if self._on_other_thread(cm, m)})
            yield Violation(
                self.name, cm.path, first[1].line,
                f"{cm.name}.{attr} is accessed from thread entry point(s) "
                f"{', '.join(entry_names)} and from "
                f"{len(methods_touching)} methods with no lock common to "
                f"all sites — guard it with a class lock, or annotate the "
                f"assignment with '# ballista: guarded-by=<lock>' (or "
                f"'guarded-by=none' for a documented single-writer field)")

    @staticmethod
    def _on_other_thread(cm: _ClassModel, meth: _Method) -> bool:
        if meth.closure:
            return True
        base = meth.name.split(".", 1)[0]
        return base in cm.entries


@register
class LockOrderRule(Rule):
    """Cycles in the static lock-acquisition graph are potential
    deadlocks; one-lock self-cycles on non-reentrant Locks are certain
    ones."""

    name = "lock-order"
    description = "no cycles in the static lock-acquisition graph"

    def check(self, project: Project) -> Iterable[Violation]:
        model = _model_for(project)
        reported: Set[FrozenSet[LockId]] = set()
        for (a, b), (path, line) in sorted(model.edges.items(),
                                           key=lambda kv: kv[1]):
            if a == b:
                if a in model.reentrant:
                    continue
                yield Violation(
                    self.name, path, line,
                    f"non-reentrant lock {fmt_lock(a)} can be re-acquired "
                    f"while already held (self-deadlock)")
                continue
            if not model.has_path(b, a):
                continue
            cyc = frozenset((a, b))
            if cyc in reported:
                continue
            reported.add(cyc)
            yield Violation(
                self.name, path, line,
                f"lock-order inversion: {fmt_lock(b)} can be held while "
                f"acquiring {fmt_lock(a)}, but this site acquires "
                f"{fmt_lock(b)} while holding {fmt_lock(a)} — a concurrent "
                f"pair deadlocks")


@register
class EventLoopHandoffRule(Rule):
    """An object posted into an EventLoop belongs to the consumer; the
    poster mutating it afterwards races the handler."""

    name = "event-loop-handoff"
    description = "no mutation of objects after posting them to an EventLoop"

    def check(self, project: Project) -> Iterable[Violation]:
        model = _model_for(project)
        for path, mm in sorted(model.modules.items()):
            if mm.sf.tree is None:
                continue
            for fn in self._functions(mm.sf.tree):
                yield from self._check_fn(model, mm, path, fn)

    def _functions(self, tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def _is_loop_recv(self, model: ConcurrencyModel, mm: _ModuleModel,
                      recv: ast.expr) -> bool:
        d = dotted_name(recv)
        if d is None:
            return False
        last = d.split(".")[-1]
        if "loop" in last.lower():
            return True
        if d.startswith("self.") and d.count(".") == 1:
            for cm in mm.classes.values():
                t = cm.attr_types.get(last)
                if t == "EventLoop":
                    return True
        return False

    def _check_fn(self, model: ConcurrencyModel, mm: _ModuleModel, path: str,
                  fn: ast.FunctionDef) -> Iterable[Violation]:
        stmts = self._linear(fn)
        posted: Dict[str, int] = {}  # name -> post line
        for stmt in stmts:
            # rebinding forgets the old object
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        posted.pop(t.id, None)
            for name, line in list(posted.items()):
                mline = self._mutates(stmt, name)
                if mline is not None:
                    yield Violation(
                        self.name, path, mline,
                        f"{name!r} was posted to an event loop at line "
                        f"{line} but is mutated afterwards — the consumer "
                        f"thread may observe a half-updated object; build "
                        f"the object fully before posting")
                    posted.pop(name, None)
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "post" and node.args
                        and isinstance(node.args[0], ast.Name)
                        and self._is_loop_recv(model, mm, node.func.value)):
                    posted[node.args[0].id] = node.lineno

    @staticmethod
    def _linear(fn: ast.FunctionDef) -> List[ast.stmt]:
        out: List[ast.stmt] = []

        def rec(body: List[ast.stmt]) -> None:
            for s in body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                out.append(s)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(s, field, None)
                    if isinstance(sub, list):
                        rec(sub)
                for h in getattr(s, "handlers", []) or []:
                    rec(h.body)

        rec(fn.body)
        return out

    @staticmethod
    def _mutates(stmt: ast.stmt, name: str) -> Optional[int]:
        def hits(t: ast.AST) -> bool:
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute):
                t = t.value
            return isinstance(t, ast.Name) and t.id == name

        if isinstance(stmt, ast.Assign) and any(map(hits, stmt.targets)):
            return stmt.lineno
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) \
                and hits(stmt.target):
            return stmt.lineno
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                    and isinstance(f.value, ast.Name) and f.value.id == name):
                return stmt.lineno
        return None


@register
class ThreadLifecycleRule(Rule):
    """Every ``threading.Thread(...)`` carries an explicit ``daemon=``
    decision; a thread stored on ``self`` must have a bounded
    ``join(timeout=...)`` somewhere in its class so shutdown neither
    leaks the thread nor hangs on it."""

    name = "thread-lifecycle"
    description = ("explicit daemon= on every Thread; bounded join for "
                   "self-stored threads")

    def check(self, project: Project) -> Iterable[Violation]:
        for sf in project.source_files():
            if sf.tree is None:
                continue
            aliases = import_aliases(sf.tree)
            for cls in ast.walk(sf.tree):
                if isinstance(cls, ast.ClassDef):
                    yield from self._check_scope(sf, aliases, cls,
                                                 cls.name)
            yield from self._check_scope(sf, aliases, sf.tree, None,
                                         toplevel_only=True)

    def _check_scope(self, sf: SourceFile, aliases: Dict[str, str],
                     scope: ast.AST, cls_name: Optional[str],
                     toplevel_only: bool = False) -> Iterable[Violation]:
        joined: Dict[str, bool] = {}  # self attr -> has bounded join
        thread_attrs: List[Tuple[str, int]] = []
        skip: Set[int] = set()
        if toplevel_only:
            # module scope: ignore statements inside classes (handled above)
            for node in ast.iter_child_nodes(scope):
                if isinstance(node, ast.ClassDef):
                    skip |= set(map(id, ast.walk(node)))
        for node in ast.walk(scope):
            if id(node) in skip or not isinstance(node, ast.Call):
                continue
            full = _resolve_ctor(aliases, node)
            if full == "threading.Thread":
                if not any(kw.arg == "daemon" for kw in node.keywords):
                    yield Violation(
                        self.name, sf.path, node.lineno,
                        "threading.Thread(...) without an explicit daemon= "
                        "decision — state whether this thread may outlive "
                        "shutdown")
            d = dotted_name(node.func)
            if (d is not None and d.startswith("self.")
                    and d.endswith(".join") and d.count(".") == 2):
                attr = d.split(".")[1]
                bounded = bool(node.args) or any(kw.arg == "timeout"
                                                 for kw in node.keywords)
                joined[attr] = joined.get(attr, False) or bounded
        if cls_name is None:
            return
        for node in ast.walk(scope):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and _resolve_ctor(aliases, node.value)
                    == "threading.Thread"):
                t = node.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    thread_attrs.append((t.attr, node.lineno))
        for attr, line in thread_attrs:
            if not joined.get(attr, False):
                yield Violation(
                    self.name, sf.path, line,
                    f"{cls_name}.{attr} stores a Thread but the class never "
                    f"calls self.{attr}.join(timeout=...) — shutdown leaks "
                    f"the thread (or an unbounded join could hang)")
