"""Error taxonomy.

Mirrors the reference's ``BallistaError`` retry semantics
(reference ballista/core/src/error.rs:36-58, 228-277): the *kind* of a task
failure decides whether the scheduler retries the task, re-runs the producer
stage, or fails the job:

- ``FetchFailedError``  -> not task-retryable, but triggers producer-stage
  re-run (shuffle lineage recovery).
- ``IOError`` / transient -> task retryable (counts against task attempts).
- ``ExecutionError``    -> fatal for the job (deterministic query error).
- ``CancelledError``    -> job/task cancellation, never retried.
"""
from __future__ import annotations


class BallistaError(Exception):
    """Base class; ``retryable`` drives scheduler retry policy."""

    retryable = False
    fail_stage = False


class ExecutionError(BallistaError):
    """Deterministic failure while executing a plan: fails the job."""


class PlanningError(BallistaError):
    """SQL/logical/physical planning failure."""


class InternalError(BallistaError):
    pass


class PlanValidationError(PlanningError):
    """Pre-launch plan sanity validation rejected an ExecutionGraph.

    Raised by ``analysis.plan_checks.validate_graph`` before any task of the
    job launches; carries every violated invariant, not just the first."""

    def __init__(self, job_id: str, errors):
        self.job_id = job_id
        self.errors = list(errors)
        detail = "; ".join(self.errors)
        super().__init__(f"plan validation failed for job {job_id}: {detail}")


class ConfigurationError(BallistaError):
    pass


class IOError_(BallistaError):
    """Transient I/O failure: the task is retried (≤ task max attempts)."""

    retryable = True


class CancelledError(BallistaError):
    pass


class ResourceExhausted(BallistaError):
    """Admission control shed the job (tenant queue full, or the queue
    timeout expired before capacity freed up).  Transient back-pressure,
    not a query error: back off and resubmit — the message carries a
    ``retry after N s`` hint."""

    retryable = True


class MemoryExhausted(BallistaError):
    """The memory governor (arrow_ballista_tpu/memory/) denied a
    reservation and the operator could not degrade to spill (spill
    disabled, or the denial hit a non-spillable allocation).

    Retryable back-pressure, **never** an executor fault: the scheduler
    retries the task (ideally on a less-loaded executor) and the
    quarantine tracker is explicitly exempted — an executor that protects
    itself by denying memory must not be blamed into quarantine for it.
    Pickle-safe (crosses the executor -> scheduler boundary)."""

    retryable = True

    def __init__(self, pool: str, requested: int, available: int,
                 message: str = ""):
        super().__init__(pool, requested, available, message)
        self.pool = pool
        self.requested = requested
        self.available = available
        self.message = message

    def __str__(self):
        return (
            f"memory exhausted on pool {self.pool!r}: requested "
            f"{self.requested} bytes, {self.available} available"
            + (f" ({self.message})" if self.message else ""))


class FetchFailedError(BallistaError):
    """A shuffle fetch from ``executor_id`` failed.

    Not retryable at task level: the scheduler rolls back the consuming
    stage and re-runs the producing map stage (reference
    ballista/scheduler/src/state/execution_graph.rs:270-657).

    This error crosses process boundaries (executor -> scheduler), so it
    must round-trip pickling: ``args`` carries the constructor fields.
    """

    fail_stage = True

    def __init__(self, executor_id: str, map_stage_id: int, map_partition_id: int, message: str = ""):
        super().__init__(executor_id, map_stage_id, map_partition_id, message)
        self.executor_id = executor_id
        self.map_stage_id = map_stage_id
        self.map_partition_id = map_partition_id
        self.message = message

    def __str__(self):
        return (
            f"fetch failed from executor {self.executor_id} "
            f"(map stage {self.map_stage_id} partition {self.map_partition_id}): {self.message}"
        )


class IntegrityError(BallistaError):
    """Payload failed an integrity check (checksum mismatch or an
    undecodable frame) at a named site — corruption detected *before* bad
    bytes turn into wrong results or an opaque decode traceback.

    Retryable: a re-fetch usually heals transient wire corruption; when it
    doesn't, the caller escalates to ``FetchFailedError`` so shuffle
    lineage recovery re-runs the producer.  Pickle-safe (crosses the
    executor -> scheduler boundary inside failure messages).
    """

    retryable = True

    def __init__(self, site: str, detail: str = "", **context):
        super().__init__(site, detail, context)
        self.site = site
        self.detail = detail
        self.context = context

    def __str__(self):
        ctx = " ".join(f"{k}={v}" for k, v in sorted(self.context.items()))
        return f"integrity check failed at {self.site}: {self.detail}" + (
            f" [{ctx}]" if ctx else "")


class ExecutorKilled(BallistaError):
    """The ``faults`` kill action is abruptly stopping this executor.

    Raised in the task thread so the in-flight task unwinds as ``killed``
    (never reported as a job failure — the executor is simulating SIGKILL;
    the scheduler learns of the death via heartbeat timeout / launch
    failure, exactly as it would for a real crash)."""


class CapacityError(ExecutionError):
    """Static output capacity exceeded (join fan-out / agg groups).

    The fix is a config bump (e.g. ``ballista.join.output_factor``); the
    message says which knob.
    """
