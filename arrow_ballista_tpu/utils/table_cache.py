"""Device-resident scan cache: HBM is the buffer pool.

The reference leans on ParquetExec + the OS page cache to make repeated
scans cheap (reference ballista/core/src/utils.rs object-store registry +
DataFusion ParquetExec; the README's benchmark methodology assumes warm
file caches).  On a TPU the analogous resource is **HBM**: the expensive
step is not the disk read but the host->device transfer (the axon tunnel
streams ~1.85 GB/s with a ~75 ms fixed cost per dispatch), so the
TPU-native buffer pool keeps the *converted device batches* resident
across queries.

Granularity: one entry per (scan partition, projection, capacity) — the
exact list of ColumnBatches a ``ScanExec.execute`` call produces BEFORE
filter masks are applied (filters only derive new masks on top, so cached
batches are shared safely).  Keys embed file mtime+size, so a rewritten
file can never serve stale rows; stale entries age out by LRU.

Budget: bytes of device buffers (columns + mask), LRU-evicted.  Host-side
string dictionaries ride along uncounted (they are small next to the
column data and live in host RAM).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

DEFAULT_BUDGET = 6 << 30  # fits SF10 lineitem device form in 16 GB HBM
# CPU backends: "device" arrays ARE host RAM, and every CPU-only daemon
# process would pin its own duplicate copy — keep the pool small there
DEFAULT_BUDGET_CPU = 1 << 30


def _batch_bytes(b) -> int:
    n = int(b.mask.nbytes)
    for v in b.columns.values():
        n += int(v.nbytes)
    return n


class DeviceTableCache:
    def __init__(self, budget_bytes: int = DEFAULT_BUDGET):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[list, int]]" = OrderedDict()
        self._bytes = 0
        self._budget = budget_bytes
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def set_budget(self, budget_bytes: int) -> None:
        with self._lock:
            if budget_bytes == self._budget:
                return
            self._budget = budget_bytes
            self._evict_locked()

    def get(self, key: Tuple) -> Optional[List]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return list(entry[0])

    def put(self, key: Tuple, batches: List) -> None:
        size = sum(_batch_bytes(b) for b in batches)
        with self._lock:
            if size > self._budget:
                return  # larger than the whole pool: never cache
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (list(batches), size)
            self._bytes += size
            self._evict_locked()

    def _evict_locked(self) -> None:
        while self._bytes > self._budget and self._entries:
            _, (_, size) = self._entries.popitem(last=False)
            self._bytes -= size
            self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget": self._budget,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


# process-wide singleton: same-process executors (standalone mode, daemon
# task slots) share one HBM pool the way they share the one device
CACHE = DeviceTableCache()


def resolve_budget(value) -> int:
    """Config value -> bytes.  '0'/0 -> disabled.  'auto' is keyed on the
    backend platform like ``resolve_task_budget`` (utils/config.py):
    accelerators get the HBM-sized default, CPU backends the small one."""
    if isinstance(value, str):
        if value.strip().lower() == "auto":
            from ..models.batch import _platform_remote

            return DEFAULT_BUDGET if _platform_remote() else DEFAULT_BUDGET_CPU
        value = int(value)
    return int(value)
