"""Session configuration: typed, validated key-value settings.

Parity with the reference's ``BallistaConfig``
(reference ballista/core/src/config.rs:30-192): same shape (string KV with
typed validation + defaults, propagated client -> scheduler -> tasks), with
TPU-specific knobs added (batch capacity, static agg/join capacities, mesh
axis sizes) since static shapes are the engine's core discipline.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from .errors import ConfigurationError

# canonical keys (reference core/config.rs:30-39 defines the first five)
SHUFFLE_PARTITIONS = "ballista.shuffle.partitions"
BATCH_SIZE = "ballista.batch.size"
JOB_NAME = "ballista.job.name"
REPARTITION_JOINS = "ballista.repartition.joins"
REPARTITION_AGGREGATIONS = "ballista.repartition.aggregations"
PARQUET_PRUNING = "ballista.parquet.pruning"
# TPU-native knobs
AGG_CAPACITY = "ballista.agg.capacity"  # static max distinct groups per batch agg
JOIN_OUTPUT_FACTOR = "ballista.join.output_factor"  # mesh joins: out_cap = factor * per-device probe share
JOIN_MAX_CAPACITY = "ballista.join.max_capacity"  # ceiling for adaptive retry
COLLECT_STATISTICS = "ballista.collect_statistics"
MESH_SHUFFLE = "ballista.shuffle.mesh"  # use ICI all-to-all when executors co-located on a mesh
MESH_HYBRID = "ballista.shuffle.mesh.hybrid"  # mesh WITHIN a host, file shuffle ACROSS hosts
MESH_BROADCAST_ROWS = "ballista.shuffle.mesh.broadcast_rows"  # build side <= this -> all_gather broadcast join
MESH_MIN_ROWS = "ballista.shuffle.mesh.min_rows"  # adaptive: fuse on mesh only when exchange >= this
TASK_SLOTS = "ballista.executor.task_slots"
BROADCAST_THRESHOLD = "ballista.join.broadcast_threshold"  # rows; build sides smaller skip the shuffle
JOB_TIMEOUT_S = "ballista.job.timeout.seconds"  # client-side wait_for_job deadline
SCAN_CACHE_BYTES = "ballista.scan.cache.bytes"  # HBM-resident scan cache budget ('auto' | bytes | 0=off)
MEM_TASK_BUDGET = "ballista.memory.task.budget.bytes"  # per-task device working-set bound ('auto' | bytes | 0=unlimited)
# memory governor (arrow_ballista_tpu/memory/): reserve->grant->release
# accounting over a host-RSS pool and a device-HBM pool; operators that
# hold unbounded state reserve before materializing and spill on denial
MEM_HOST_BUDGET = "ballista.memory.host.budget.bytes"
MEM_DEVICE_BUDGET = "ballista.memory.device.budget.bytes"
MEM_SPILL_ENABLED = "ballista.memory.spill.enabled"
MEM_PRESSURE_SHED = "ballista.memory.pressure.shed.threshold"
# admission control / multi-tenancy (arrow_ballista_tpu/admission/) — all
# default to 0/"" = pass-through, the subsystem activates only when set
ADMISSION_TENANT = "ballista.admission.tenant"
ADMISSION_PRIORITY = "ballista.admission.priority"
ADMISSION_MAX_CONCURRENT_JOBS = "ballista.admission.max_concurrent_jobs"
ADMISSION_MAX_QUEUED_JOBS = "ballista.admission.max_queued_jobs"
ADMISSION_QUEUE_TIMEOUT_S = "ballista.admission.queue.timeout.seconds"
ADMISSION_MAX_PENDING_TASKS = "ballista.admission.max_pending_tasks"
ADMISSION_SLOT_SHARE = "ballista.admission.tenant.slot_share"
ADMISSION_RETRY_AFTER_S = "ballista.admission.retry_after.seconds"
# observability / tracing (arrow_ballista_tpu/obs/)
OBS_TRACING = "ballista.observability.tracing"
OBS_PROFILE_RETENTION = "ballista.observability.profile.retention"
OBS_COLLECTOR = "ballista.observability.collector"
OBS_OTLP_ENDPOINT = "ballista.observability.otlp.endpoint"
# device-level observatory (arrow_ballista_tpu/obs/device.py)
OBS_DEVICE_ENABLED = "ballista.observability.device.enabled"
OBS_DEVICE_WATERMARKS = "ballista.observability.device.watermarks"
OBS_DEVICE_ADVISOR_MIN_SAVINGS_MS = \
    "ballista.observability.device.advisor.min_savings_ms"
# flight recorder (arrow_ballista_tpu/obs/journal.py): causal event journal
JOURNAL_ENABLED = "ballista.journal.enabled"
JOURNAL_CAPACITY = "ballista.journal.capacity"
JOURNAL_SPILL_PATH = "ballista.journal.spill_path"
# structured logging (utils/logsetup.py): 'text' (default) or 'json'
LOG_FORMAT = "ballista.log.format"
# static analysis (arrow_ballista_tpu/analysis/)
ANALYSIS_PLAN_CHECKS = "ballista.analysis.plan_checks"
ANALYSIS_LOCK_ORDER_RUNTIME = "ballista.analysis.lock_order.runtime"
# RPC hardening (net/retry.py): client-side deadlines + bounded backoff
RPC_CONNECT_TIMEOUT_S = "ballista.rpc.connect.timeout.seconds"
RPC_READ_TIMEOUT_S = "ballista.rpc.read.timeout.seconds"
RPC_RETRY_BASE_S = "ballista.rpc.retry.base.seconds"
RPC_RETRY_CAP_S = "ballista.rpc.retry.cap.seconds"
RPC_RETRY_DEADLINE_S = "ballista.rpc.retry.deadline.seconds"
# cluster membership (scheduler/cluster.py): one timeout, documented grace
CLUSTER_EXECUTOR_TIMEOUT_S = "ballista.cluster.executor_timeout_s"
# executor quarantine (scheduler/quarantine.py)
QUARANTINE_FAILURES = "ballista.scheduler.quarantine.failures"
QUARANTINE_PROBATION_S = "ballista.scheduler.quarantine.probation.seconds"
# deterministic fault injection (arrow_ballista_tpu/faults/)
FAULTS_PLAN = "ballista.faults.plan"
# speculative execution (scheduler/speculation.py + execution_graph.py)
SPECULATION_ENABLED = "ballista.speculation.enabled"
SPECULATION_QUANTILE = "ballista.speculation.quantile"
SPECULATION_MULTIPLIER = "ballista.speculation.multiplier"
SPECULATION_MIN_RUNTIME_S = "ballista.speculation.min_runtime.seconds"
SPECULATION_MAX_CONCURRENT = "ballista.speculation.max_concurrent"
SPECULATION_INTERVAL_S = "ballista.speculation.interval.seconds"
# adaptive query execution (scheduler/aqe.py + execution_graph.py)
AQE_ENABLED = "ballista.aqe.enabled"
AQE_COALESCE_ENABLED = "ballista.aqe.coalesce.enabled"
AQE_COALESCE_TARGET_ROWS = "ballista.aqe.coalesce.target.rows"
AQE_COALESCE_TARGET_BYTES = "ballista.aqe.coalesce.target.bytes"
AQE_BROADCAST_ENABLED = "ballista.aqe.broadcast.enabled"
AQE_BROADCAST_THRESHOLD_ROWS = "ballista.aqe.broadcast.threshold.rows"
AQE_SKEW_ENABLED = "ballista.aqe.skew.enabled"
AQE_SKEW_FACTOR = "ballista.aqe.skew.factor"
AQE_SKEW_MIN_ROWS = "ballista.aqe.skew.min.rows"
# shuffle partition integrity (ops/shuffle.py + net/dataplane.py)
SHUFFLE_INTEGRITY = "ballista.shuffle.integrity.verify"
# shuffle transport (ops/shuffle.py + net/dataplane.py): local mmap fast
# path, streaming chunked remote fetch, and wire compression
SHUFFLE_LOCAL_HOST_MATCH = "ballista.shuffle.local.host_match"
SHUFFLE_MAX_CONCURRENT_FETCHES = "ballista.shuffle.max_concurrent_fetches"
SHUFFLE_WIRE_STREAMING = "ballista.shuffle.wire.streaming"
SHUFFLE_WIRE_CHUNK_ROWS = "ballista.shuffle.wire.chunk_rows"
SHUFFLE_WIRE_COMPRESSION = "ballista.shuffle.wire.compression"
# runtime statistics observatory (obs/stats.py + scheduler sampler)
STATS_HISTORY_CAPACITY = "ballista.stats.history.capacity"
STATS_HISTORY_INTERVAL_S = "ballista.stats.history.interval.seconds"
# serving caches (scheduler/serving_cache.py): prepared-plan templates and
# completed results/subplans keyed on catalog + config versions
PLAN_CACHE_ENABLED = "ballista.plan.cache.enabled"
PLAN_CACHE_MAX_ENTRIES = "ballista.plan.cache.max.entries"
PLAN_CACHE_MAX_BYTES = "ballista.plan.cache.max.bytes"
RESULT_CACHE_ENABLED = "ballista.result.cache.enabled"
RESULT_CACHE_MAX_ENTRIES = "ballista.result.cache.max.entries"
RESULT_CACHE_MAX_BYTES = "ballista.result.cache.max.bytes"
RESULT_CACHE_MAX_ENTRY_BYTES = "ballista.result.cache.max.entry.bytes"
RESULT_CACHE_SUBPLAN = "ballista.result.cache.subplan.enabled"
# scheduler fleet HA (scheduler/kv.py + scheduler/scheduler.py): lease-based
# job ownership in the shared KV, adoption of dead shards' jobs, and the
# cross-shard registry behind client failover + /api/autoscale
FLEET_LEASE_TTL_S = "ballista.fleet.lease.ttl.seconds"
FLEET_LEASE_RENEW_S = "ballista.fleet.lease.renew.seconds"
FLEET_ADOPT_INTERVAL_S = "ballista.fleet.adopt.interval.seconds"
FLEET_REGISTRY_STALE_S = "ballista.fleet.registry.stale.seconds"
# whole-stage compiler (compile/): fuse allowlisted operator chains into
# one jitted program at stage-plan resolution time
COMPILE_ENABLED = "ballista.compile.enabled"
COMPILE_MIN_OPS = "ballista.compile.min.ops"
COMPILE_OPERATORS = "ballista.compile.operators"
COMPILE_DONATE = "ballista.compile.donate"
# live observability plane (obs/live.py + journal watch streams): in-flight
# doctor alerts on a scheduler cadence, watch-stream subscriber bounds
LIVE_ENABLED = "ballista.live.enabled"
LIVE_DOCTOR_INTERVAL_S = "ballista.live.doctor.interval.seconds"
LIVE_WATCH_QUEUE_EVENTS = "ballista.live.watch.queue.events"
LIVE_WATCH_POLL_S = "ballista.live.watch.poll.seconds"
# SLO tracker (obs/slo.py): declarative latency objective over completed
# jobs, multi-window burn rates behind /api/slo and the autoscale signal
SLO_P99_TARGET_MS = "ballista.slo.latency.p99.target.ms"
SLO_WINDOW_S = "ballista.slo.window.seconds"
# query lifecycle guardrails: server-side deadline enforcement and
# poison-query containment (scheduler/scheduler.py)
QUERY_DEADLINE_S = "ballista.query.deadline.seconds"
POISON_DISTINCT_EXECUTORS = "ballista.poison.distinct_executors"


@dataclasses.dataclass
class ConfigEntry:
    key: str
    default: Any
    parse: Callable[[str], Any]
    doc: str = ""


def env_flag(name: str) -> bool:
    """Shared truthiness rule for boolean env overrides
    (BALLISTA_REMOTE_DEVICE, BALLISTA_FORCE_HASH_COLLISIONS, ...):
    unset/''/'0'/'false'/'no' are False, anything else True.
    Returns None when the variable is unset/blank so callers can
    distinguish 'explicitly 0' from 'not set'."""
    import os

    v = os.environ.get(name)
    if v is None or v.strip() == "":
        return None
    return v.strip().lower() not in ("0", "false", "no")


def _parse_bool(s: str) -> bool:
    if str(s).lower() in ("true", "1", "yes"):
        return True
    if str(s).lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"not a bool: {s!r}")


def _parse_partitions(s) -> int:
    """Shuffle partition count; 0 means 'auto' (derived at plan time from
    input row counts — the memory-control heuristic the reference leaves as
    a TODO grid, SURVEY §7 hard-parts)."""
    if str(s).lower() == "auto":
        return 0
    n = int(s)
    if n < 0:
        raise ValueError(f"partition count must be >= 0: {s!r}")
    return n


_ENTRIES: Dict[str, ConfigEntry] = {
    e.key: e
    for e in [
        ConfigEntry(SHUFFLE_PARTITIONS, 16, _parse_partitions,
                    "number of output partitions for shuffles, or 'auto' to "
                    "derive from input row counts at plan time"),
        ConfigEntry(BATCH_SIZE, 1 << 17, int, "static row capacity of a device ColumnBatch"),
        ConfigEntry(JOB_NAME, "", str, "human-readable job name"),
        ConfigEntry(REPARTITION_JOINS, True, _parse_bool,
                    "reference-parity placeholder (config.rs:34): the "
                    "distributed planner always repartitions joins; "
                    "accepted and propagated but not yet consulted"),
        ConfigEntry(REPARTITION_AGGREGATIONS, True, _parse_bool,
                    "reference-parity placeholder (config.rs:35): the "
                    "distributed planner always repartitions aggregations; "
                    "accepted and propagated but not yet consulted"),
        ConfigEntry(PARQUET_PRUNING, True, _parse_bool, "row-group pruning on parquet scans"),
        ConfigEntry(AGG_CAPACITY, 1 << 16, int, "static max distinct groups per aggregation"),
        ConfigEntry(JOIN_OUTPUT_FACTOR, 2, int,
                    "mesh-join output capacity = factor * per-device probe "
                    "share (plain joins size outputs by a count pass)"),
        ConfigEntry(JOIN_MAX_CAPACITY, 1 << 26, int,
                    "hard ceiling for adaptive join-capacity growth (rows)"),
        ConfigEntry(COLLECT_STATISTICS, True, _parse_bool,
                    "reference-parity placeholder (config.rs:38): scans "
                    "always collect the statistics pruning needs; accepted "
                    "and propagated but not yet consulted"),
        ConfigEntry(MESH_SHUFFLE, False, _parse_bool, "use ICI mesh all-to-all shuffle"),
        ConfigEntry(MESH_HYBRID, False, _parse_bool,
                    "hybrid exchange: mesh-fused partials per host, file shuffle across hosts"),
        ConfigEntry(MESH_BROADCAST_ROWS, 1 << 18, int,
                    "mesh joins all_gather the build side instead of "
                    "all_to_all-ing both sides when its live rows fit here "
                    "(CollectLeft analog)"),
        ConfigEntry(MESH_MIN_ROWS, 8_000_000, int,
                    "adaptive transport: mesh-fuse an exchange only when "
                    "its estimated input rows reach this (small exchanges "
                    "measured faster on the materialized file path; the "
                    "mesh's no-materialization advantage grows with size); "
                    "0 forces mesh for every eligible exchange"),
        ConfigEntry(TASK_SLOTS, 4, int, "concurrent task slots per executor"),
        ConfigEntry(BROADCAST_THRESHOLD, 4_000_000, int,
                    "broadcast join build sides with fewer estimated rows "
                    "(4M measured best at SF10: q3 -14%, q18 -9%, SF1 "
                    "neutral — a partitioned exchange of a 60M-row probe "
                    "costs far more than probing a few-M-row build)"),
        ConfigEntry(JOB_TIMEOUT_S, 3600, int,
                    "seconds a client waits for a submitted job before giving up"),
        ConfigEntry(SCAN_CACHE_BYTES, "auto", str,
                    "device-resident scan cache budget: 'auto' (6 GiB on "
                    "accelerator backends, 1 GiB on CPU), a byte count, or "
                    "0 to disable; see utils/table_cache.py"),
        ConfigEntry(MEM_TASK_BUDGET, "auto", str,
                    "memory control: per-task device working-set budget in "
                    "bytes; joins chunk their probe side and 'auto' shuffle "
                    "partition counts scale to keep task state under it.  "
                    "'auto' = 4 GiB on accelerator backends, unlimited on "
                    "CPU; 0 = unlimited"),
        ConfigEntry(MEM_HOST_BUDGET, "0", str,
                    "memory governor: host-RSS pool budget in bytes for "
                    "operator state (join build sides, aggregation "
                    "groups).  Reservations beyond the budget are denied "
                    "and the operator spills its state to disk as Arrow "
                    "IPC runs (bit-identical results).  'auto' = 16 GiB; "
                    "0 = unlimited (governor grants everything, spill "
                    "never triggers)"),
        ConfigEntry(MEM_DEVICE_BUDGET, "0", str,
                    "memory governor: device-HBM pool budget in bytes, "
                    "checked against the live-buffer watermark sampler "
                    "(obs/device.py).  'auto' = 12 GiB on accelerator "
                    "backends, unlimited on CPU; 0 = unlimited"),
        ConfigEntry(MEM_SPILL_ENABLED, True, _parse_bool,
                    "degrade to disk spill when the governor denies a "
                    "reservation (aggs: partial runs + sort-merge "
                    "finalize; joins: partitioned build rehydrate).  "
                    "False = a denial raises retryable MemoryExhausted "
                    "instead of spilling"),
        ConfigEntry(MEM_PRESSURE_SHED, 0.95, float,
                    "executor memory pressure (reserved/budget, max over "
                    "pools, reported via heartbeat) at or above which the "
                    "scheduler stops offering that executor tasks and "
                    "admission sheds new jobs with retriable "
                    "ResourceExhausted; >= 1.0 still degrades offer "
                    "ordering but never sheds"),
        ConfigEntry(ADMISSION_TENANT, "", str,
                    "tenant identity for admission control; empty = the "
                    "session id (each session is its own tenant)"),
        ConfigEntry(ADMISSION_PRIORITY, 0, int,
                    "admission queue priority (higher runs first; FIFO "
                    "within a priority)"),
        ConfigEntry(ADMISSION_MAX_CONCURRENT_JOBS, 0, int,
                    "max jobs a tenant may have running at once; excess "
                    "submissions wait in the admission queue (0 = "
                    "unlimited)"),
        ConfigEntry(ADMISSION_MAX_QUEUED_JOBS, 0, int,
                    "max jobs a tenant may have waiting for admission; "
                    "beyond this, submissions fail immediately with a "
                    "retriable 'queue full' status (0 = unlimited)"),
        ConfigEntry(ADMISSION_QUEUE_TIMEOUT_S, 0.0, float,
                    "seconds a job may wait for admission before failing "
                    "with a retriable 'queue timeout' status (0 = wait "
                    "forever)"),
        ConfigEntry(ADMISSION_MAX_PENDING_TASKS, 0, int,
                    "load shedding: hold new jobs in the admission queue "
                    "while the scheduler's pending task count is at or "
                    "above this (0 = never shed)"),
        ConfigEntry(ADMISSION_SLOT_SHARE, 0.0, float,
                    "fraction (0..1] of the cluster's registered task "
                    "slots this tenant's running jobs may occupy at once "
                    "(0 = unlimited)"),
        ConfigEntry(OBS_TRACING, True, _parse_bool,
                    "distributed tracing: span propagation client -> "
                    "scheduler -> executor -> operator, the per-job profile "
                    "ring buffer, and the /api/job/<id>/profile|trace "
                    "endpoints (False = spans off, endpoints return 404)"),
        ConfigEntry(OBS_PROFILE_RETENTION, 64, int,
                    "finished job profiles (and their span sets) the "
                    "scheduler retains in a ring buffer for "
                    "/api/job/<id>/profile and /trace"),
        ConfigEntry(OBS_COLLECTOR, "noop", str,
                    "span export collector: 'noop' (default), 'memory' "
                    "(bounded in-process buffer), or 'otlp' (best-effort "
                    "OTLP/HTTP JSON POST to "
                    "ballista.observability.otlp.endpoint)"),
        ConfigEntry(OBS_OTLP_ENDPOINT, "", str,
                    "OTLP/HTTP endpoint (e.g. "
                    "http://localhost:4318/v1/traces) used when the 'otlp' "
                    "collector is selected"),
        ConfigEntry(OBS_DEVICE_ENABLED, True, _parse_bool,
                    "device-level observatory (obs/device.py): JIT "
                    "compile/retrace/cache-hit accounting, host<->device "
                    "transfer bytes, and memory watermarks, attributed per "
                    "operator and shipped as TaskStatus.device_stats "
                    "(False = every probe is a single predicate check)"),
        ConfigEntry(OBS_DEVICE_WATERMARKS, True, _parse_bool,
                    "sample device live-buffer bytes and host RSS peaks at "
                    "task/operator boundaries (requires "
                    "ballista.observability.device.enabled; False drops "
                    "only the watermark sampling, keeping compile/transfer "
                    "accounting)"),
        ConfigEntry(OBS_DEVICE_ADVISOR_MIN_SAVINGS_MS, 1.0, float,
                    "fusion advisor (obs/advisor.py): drop stage operator "
                    "chains whose estimated fusion savings fall below this "
                    "many milliseconds"),
        ConfigEntry(JOURNAL_ENABLED, False, _parse_bool,
                    "flight recorder (obs/journal.py): causally-ordered "
                    "journal of every consequential scheduler/executor "
                    "decision (job lifecycle, task attempts, AQE, "
                    "speculation, cache hits, lease/quarantine "
                    "transitions, failpoint firings), feeding "
                    "GET /api/job/<id>/forensics and the query doctor "
                    "(False = every probe is a single predicate check and "
                    "the wire format is byte-identical to journal-off)"),
        ConfigEntry(JOURNAL_CAPACITY, 4096, int,
                    "events retained in the process-global journal ring "
                    "and in each per-job timeline; older events are "
                    "evicted and counted in journal_events_dropped_total"),
        ConfigEntry(JOURNAL_SPILL_PATH, "", str,
                    "append every journal event as one JSON line to this "
                    "file (durable postmortems beyond the in-memory "
                    "ring); empty = no spill"),
        ConfigEntry(LOG_FORMAT, "text", str,
                    "log record format: 'text' (classic one-line) or "
                    "'json' (structured, one JSON object per line with "
                    "job_id/trace_id/span_id correlation fields stamped "
                    "from the ambient observability scope)"),
        ConfigEntry(ADMISSION_RETRY_AFTER_S, 5, int,
                    "retry-after hint (seconds) embedded in retriable "
                    "admission failures (queue full / queue timeout)"),
        ConfigEntry(ANALYSIS_PLAN_CHECKS, True, _parse_bool,
                    "pre-launch plan sanity validation: reject an "
                    "ExecutionGraph with shuffle partition/schema "
                    "mismatches or orphan/cyclic stage dependencies before "
                    "any task launches (see "
                    "docs/developer-guide/static-analysis.md)"),
        ConfigEntry(ANALYSIS_LOCK_ORDER_RUNTIME, False, _parse_bool,
                    "debug lock-instrumentation shim: record the runtime "
                    "lock-acquisition order of every package lock and "
                    "validate it against the static concurrency model "
                    "(analysis/concurrency.py). Zero-cost when off; also "
                    "enabled by BALLISTA_LOCK_ORDER_RUNTIME=1. Intended "
                    "for the chaos/serving CI legs, not production"),
        ConfigEntry(RPC_CONNECT_TIMEOUT_S, 5.0, float,
                    "TCP connect deadline for client-side control-plane "
                    "RPCs (net/retry.py)"),
        ConfigEntry(RPC_READ_TIMEOUT_S, 60.0, float,
                    "read deadline for client-side control-plane RPCs "
                    "(net/retry.py)"),
        ConfigEntry(RPC_RETRY_BASE_S, 0.2, float,
                    "base backoff between RPC retries; doubles per attempt "
                    "(jittered, capped at ballista.rpc.retry.cap.seconds)"),
        ConfigEntry(RPC_RETRY_CAP_S, 5.0, float,
                    "upper bound on a single RPC retry backoff"),
        ConfigEntry(RPC_RETRY_DEADLINE_S, 30.0, float,
                    "give-up deadline across all retries of one RPC; on "
                    "expiry a retryable failure surfaces (executor marks "
                    "the scheduler unreachable; a failed launch becomes "
                    "ExecutorLost)"),
        ConfigEntry(CLUSTER_EXECUTOR_TIMEOUT_S, 180.0, float,
                    "seconds without a heartbeat before an executor is "
                    "declared lost (reaper -> ExecutorLost).  Work offers "
                    "stop earlier, at timeout minus a drain grace of "
                    "min(60s, timeout/2), so a slow-heartbeat executor "
                    "drains instead of receiving doomed tasks"),
        ConfigEntry(QUARANTINE_FAILURES, 5, int,
                    "consecutive retryable task failures on one executor "
                    "before it is quarantined (no new offers); 0 disables "
                    "quarantine"),
        ConfigEntry(QUARANTINE_PROBATION_S, 60.0, float,
                    "seconds a quarantined executor sits out before "
                    "probation re-admits it; one failure on probation "
                    "re-quarantines, one success clears it"),
        ConfigEntry(FAULTS_PLAN, "", str,
                    "deterministic fault-injection plan: inline JSON or "
                    "'@/path/to/plan.json' (see arrow_ballista_tpu/faults/ "
                    "and docs/user-guide/fault-tolerance.md); empty = "
                    "disabled, all failpoint sites are no-ops"),
        ConfigEntry(SPECULATION_ENABLED, False, _parse_bool,
                    "speculative execution: launch a duplicate attempt of a "
                    "straggling task on a different executor; first "
                    "successful attempt wins, the loser is cancelled and "
                    "its outputs ignored (results are identical either "
                    "way).  False = one attempt at a time, today's "
                    "behavior"),
        ConfigEntry(SPECULATION_QUANTILE, 0.75, float,
                    "duration quantile (0..1] of a stage's *completed* "
                    "attempts used as the straggler baseline"),
        ConfigEntry(SPECULATION_MULTIPLIER, 1.5, float,
                    "a running task is speculatable once its age exceeds "
                    "multiplier x the baseline quantile duration"),
        ConfigEntry(SPECULATION_MIN_RUNTIME_S, 5.0, float,
                    "never speculate a task younger than this, regardless "
                    "of the quantile math (protects short stages from "
                    "duplicate launches)"),
        ConfigEntry(SPECULATION_MAX_CONCURRENT, 2, int,
                    "max concurrent speculative attempts per stage"),
        ConfigEntry(SPECULATION_INTERVAL_S, 1.0, float,
                    "seconds between speculation-monitor scans of running "
                    "tasks"),
        ConfigEntry(AQE_ENABLED, True, _parse_bool,
                    "adaptive query execution: re-optimize not-yet-resolved "
                    "downstream stages from the observed shuffle statistics "
                    "of completed producers (dynamic partition coalescing, "
                    "shuffle-join -> broadcast switch, skew splitting).  "
                    "False freezes the plan at submit time, today's "
                    "behavior; results are identical either way (see "
                    "docs/user-guide/aqe.md)"),
        ConfigEntry(AQE_COALESCE_ENABLED, True, _parse_bool,
                    "AQE rewrite 1: merge tiny reduce partitions of an "
                    "unresolved stage up to the coalesce targets so a "
                    "many-task stage over a few thousand rows launches a "
                    "handful of tasks instead"),
        ConfigEntry(AQE_COALESCE_TARGET_ROWS, 8192, int,
                    "coalesced-partition target size in observed rows; "
                    "adjacent partitions merge while the merged group stays "
                    "at or under this (0 disables the row target)"),
        ConfigEntry(AQE_COALESCE_TARGET_BYTES, 1 << 20, int,
                    "coalesced-partition target size in observed shuffle "
                    "bytes; a merged group must also stay at or under this "
                    "(0 disables the byte target)"),
        ConfigEntry(AQE_BROADCAST_ENABLED, True, _parse_bool,
                    "AQE rewrite 2: when a completed stage's actual shuffle "
                    "output is under the broadcast threshold, flip the "
                    "downstream partitioned join that consumes it to a "
                    "broadcast join and graft away the probe side's "
                    "now-unnecessary exchange where the plan allows"),
        ConfigEntry(AQE_BROADCAST_THRESHOLD_ROWS, 4_000_000, int,
                    "observed build-side rows at or under which the "
                    "broadcast switch fires (mirrors the planner's "
                    "estimate-based ballista.join.broadcast_threshold)"),
        ConfigEntry(AQE_SKEW_ENABLED, True, _parse_bool,
                    "AQE rewrite 3: split a hot reduce partition into "
                    "several tasks, each reading a sub-range of the "
                    "producer's map outputs"),
        ConfigEntry(AQE_SKEW_FACTOR, 4.0, float,
                    "a partition is 'hot' when its observed rows exceed "
                    "factor x the mean partition rows of the stage"),
        ConfigEntry(AQE_SKEW_MIN_ROWS, 1_000_000, int,
                    "never skew-split a partition smaller than this many "
                    "observed rows (protects small stages from pointless "
                    "task fan-out)"),
        ConfigEntry(SHUFFLE_INTEGRITY, True, _parse_bool,
                    "verify the producer-recorded CRC-32 checksum of every "
                    "remotely fetched shuffle partition before "
                    "deserialization; a mismatch raises a retryable "
                    "IntegrityError (re-fetch, then lineage rollback) "
                    "instead of decoding corrupt bytes"),
        ConfigEntry(SHUFFLE_LOCAL_HOST_MATCH, True, _parse_bool,
                    "zero-copy local handoff: a reader whose executor "
                    "advertises the same host as a shuffle producer reads "
                    "the producer's IPC file directly via mmap instead of "
                    "fetching it over the data plane.  The mapped bytes are "
                    "lazily CRC-verified (when "
                    "ballista.shuffle.integrity.verify is on) and any "
                    "mismatch or missing file silently falls back to the "
                    "remote fetch path, so a stale same-named file can "
                    "never corrupt results"),
        ConfigEntry(SHUFFLE_MAX_CONCURRENT_FETCHES, 50, int,
                    "per reduce-task cap on concurrent remote shuffle "
                    "fetches (the reference's 50-permit semaphore, "
                    "shuffle_reader.rs:123); fetches run on a shared "
                    "process-level pool rather than a per-task one"),
        ConfigEntry(SHUFFLE_WIRE_STREAMING, True, _parse_bool,
                    "chunked streaming remote fetch: shuffle partitions "
                    "stream as framed Arrow IPC chunks (per-chunk CRC-32) "
                    "so the reader decodes batches while later chunks are "
                    "in flight, and a retry resumes from the last good "
                    "chunk instead of re-pulling the whole file.  False = "
                    "legacy whole-file fetch_partition blobs"),
        ConfigEntry(SHUFFLE_WIRE_CHUNK_ROWS, 1 << 16, int,
                    "rows per streamed shuffle chunk; chunk boundaries are "
                    "deterministic multiples of this so resume-from-chunk "
                    "is exact"),
        ConfigEntry(SHUFFLE_WIRE_COMPRESSION, "lz4", str,
                    "Arrow IPC buffer compression on the streaming remote "
                    "path: 'lz4' (default), 'zstd', or 'none'.  Applied "
                    "per-fetch on the network path only — local files and "
                    "mmap readers always see uncompressed bytes; an "
                    "unavailable codec silently degrades to 'none'"),
        ConfigEntry(STATS_HISTORY_CAPACITY, 512, int,
                    "ring-buffer capacity of the cluster time series behind "
                    "GET /api/cluster/history (oldest samples are evicted)"),
        ConfigEntry(STATS_HISTORY_INTERVAL_S, 5.0, float,
                    "seconds between cluster-history samples (executor "
                    "utilization, admission queue depth, event-loop lag)"),
        ConfigEntry(PLAN_CACHE_ENABLED, True, _parse_bool,
                    "prepared-plan cache: normalized SQL text (literals "
                    "extracted as bound parameters) -> validated "
                    "ExecutionGraph template.  A hit skips parse, logical "
                    "and physical planning, scalar-subquery execution and "
                    "plan validation; entries are keyed on the referenced "
                    "tables' versions (resolved file list + mtimes, or "
                    "registration generation for in-memory tables) and the "
                    "session-config fingerprint, so DDL, data changes or "
                    "config changes invalidate correctly (see "
                    "docs/user-guide/serving.md)"),
        ConfigEntry(PLAN_CACHE_MAX_ENTRIES, 256, int,
                    "max bound plan templates resident in the prepared-plan "
                    "cache (LRU beyond this)"),
        ConfigEntry(PLAN_CACHE_MAX_BYTES, 64 << 20, int,
                    "estimated-byte budget of the prepared-plan cache; "
                    "shared table data is not counted (LRU beyond this)"),
        ConfigEntry(RESULT_CACHE_ENABLED, False, _parse_bool,
                    "result/subplan cache: completed-query result bytes "
                    "(and completed shuffle-stage outputs as subplan "
                    "entries) keyed on (plan fingerprint, table versions), "
                    "served straight from the scheduler for repeat "
                    "queries.  Off by default because a hit skips "
                    "execution entirely — turn it on for serving "
                    "workloads.  Capture only happens when the result "
                    "files are readable on the scheduler host (always "
                    "true in-process); see docs/user-guide/serving.md"),
        ConfigEntry(RESULT_CACHE_MAX_ENTRIES, 512, int,
                    "max entries (results + subplans) resident in the "
                    "result cache (LRU beyond this)"),
        ConfigEntry(RESULT_CACHE_MAX_BYTES, 256 << 20, int,
                    "byte budget of the result/subplan cache (LRU beyond "
                    "this)"),
        ConfigEntry(RESULT_CACHE_MAX_ENTRY_BYTES, 32 << 20, int,
                    "results or stage outputs larger than this are never "
                    "cached (one giant answer must not wipe the working "
                    "set)"),
        ConfigEntry(RESULT_CACHE_SUBPLAN, True, _parse_bool,
                    "also cache completed shuffle-stage outputs keyed on "
                    "the stage's structural fingerprint, and pre-complete "
                    "matching stages of later submissions from the cached "
                    "bytes (in-process/shared-filesystem deployments only; "
                    "budget shared with the result cache)"),
        ConfigEntry(FLEET_LEASE_TTL_S, 15.0, float,
                    "TTL of a scheduler shard's job-ownership lease in the "
                    "shared KV; a shard that stops renewing for longer than "
                    "this has its jobs adopted by a surviving shard"),
        ConfigEntry(FLEET_LEASE_RENEW_S, 0.0, float,
                    "interval between lease renewals from the shard's lease "
                    "heartbeat thread; 0 = ttl/3"),
        ConfigEntry(FLEET_ADOPT_INTERVAL_S, 2.0, float,
                    "how often a shard scans the shared KV for expired "
                    "leases to adopt (only shards with a KV-backed job "
                    "state run the scanner)"),
        ConfigEntry(FLEET_REGISTRY_STALE_S, 30.0, float,
                    "shard-registry entries older than this are ignored "
                    "when aggregating the /api/autoscale signal and when "
                    "re-resolving a job's owner for client failover"),
        ConfigEntry(COMPILE_ENABLED, True, _parse_bool,
                    "whole-stage compiler: fuse maximal single-child "
                    "chains of allowlisted operators into one jitted "
                    "program per chain at stage-plan resolution time "
                    "(compile/; a pure performance rewrite — any doubt "
                    "leaves the stage interpreted; see "
                    "docs/user-guide/compilation.md)"),
        ConfigEntry(COMPILE_MIN_OPS, 2, int,
                    "minimum operators in an allowlisted run before the "
                    "compiler fuses it (shorter runs stay interpreted: "
                    "one operator fused alone saves nothing)"),
        ConfigEntry(COMPILE_OPERATORS, "FilterExec,ProjectionExec,"
                    "RenameExec,HashAggregateExec", str,
                    "comma-separated operator allowlist for whole-stage "
                    "fusion; operators outside the list (and host-mode / "
                    "scalar-subquery / clustered instances of listed "
                    "ones) always run interpreted"),
        ConfigEntry(COMPILE_DONATE, True, _parse_bool,
                    "donate the input column buffers of a fused row-only "
                    "program to XLA when the chain reads a shuffle (fresh "
                    "per-task buffers); a no-op on the CPU backend and "
                    "for agg-headed chains (the capacity-retry ladder "
                    "re-reads the input)"),
        ConfigEntry(LIVE_ENABLED, False, _parse_bool,
                    "live observability plane: run the in-flight doctor "
                    "scan thread against running jobs (obs/live.py) and "
                    "let the watch endpoints tail the journal; off = the "
                    "scan thread never starts and nothing changes on the "
                    "wire (docs/user-guide/live.md)"),
        ConfigEntry(LIVE_DOCTOR_INTERVAL_S, 5.0, float,
                    "cadence of the in-flight doctor scan over running "
                    "jobs (straggler / partition-skew / shuffle-hotspot / "
                    "control-plane-churn / journal-drops rules -> "
                    "alert.raised / alert.cleared journal events); <= 0 "
                    "disables the scan thread even when live is on"),
        ConfigEntry(LIVE_WATCH_QUEUE_EVENTS, 1024, int,
                    "bound of each watch subscriber's event queue; a "
                    "consumer that falls behind sheds oldest events and "
                    "receives one watch.gap event with the drop count "
                    "(emit() never blocks on a slow watcher)"),
        ConfigEntry(LIVE_WATCH_POLL_S, 0.25, float,
                    "long-poll tick of the REST watch streams and "
                    "ctx.watch(): how often a quiet stream re-checks job "
                    "state and emits progress frames"),
        ConfigEntry(SLO_P99_TARGET_MS, 0.0, float,
                    "latency SLO: 99% of completed jobs must finish "
                    "under this wall time (a failed job always counts as "
                    "a violation); 0 disables SLO tracking entirely "
                    "(null tracker, no samples kept)"),
        ConfigEntry(SLO_WINDOW_S, 300.0, float,
                    "slow burn-rate window of the SLO tracker in "
                    "seconds; the fast window is 1/12 of it (the 1h/5m "
                    "SRE ratio); served at /api/slo and summed into "
                    "/api/autoscale"),
        ConfigEntry(QUERY_DEADLINE_S, 0.0, float,
                    "server-side query deadline in seconds, measured from "
                    "submission: the scheduler fails a job that runs past "
                    "it with a DeadlineExceeded terminal status and "
                    "cancels its tasks fleet-wide.  Session-level or "
                    "per-submit (the per-request config override wins); "
                    "the absolute expiry rides the job checkpoint, so an "
                    "adopting shard keeps enforcing the original clock.  "
                    "0 disables"),
        ConfigEntry(POISON_DISTINCT_EXECUTORS, 2, int,
                    "poison-query containment: when the SAME partition "
                    "fails with equivalent errors on this many distinct "
                    "non-quarantined executors, the job is classified "
                    "poison and failed immediately — zero quarantine "
                    "strikes are charged and the remaining retry budget "
                    "is skipped, so one bad query can never blacklist "
                    "the fleet.  0 disables classification"),
    ]
}


def resolve_task_budget(cfg: "BallistaConfig") -> int:
    """MEM_TASK_BUDGET -> bytes (0 = unlimited).

    Memory-control role of the reference's spill machinery
    (reference ballista/core/src/utils.rs:176-212 write_stream_to_disk):
    a static-shape engine cannot react to pressure by spilling mid-kernel,
    so the budget is enforced *before* allocation — joins chunk their probe
    loop and 'auto' partition counts scale so no task's working set is
    planned above the budget.  Disk-tier state remains the shuffle's IPC
    files, exactly as reference shuffle files serve as its data
    checkpoints."""
    v = cfg.get(MEM_TASK_BUDGET)
    if isinstance(v, str):
        if v.strip().lower() == "auto":
            # keyed on the backend PLATFORM, not remote_device(): that
            # helper is a D2H-latency proxy with a user override
            # (BALLISTA_REMOTE_DEVICE=0 restores eager safety nets), and
            # the override must not silently lift the memory budget on
            # small-HBM accelerators
            from ..models.batch import _platform_remote

            return (4 << 30) if _platform_remote() else 0
        v = int(v)
    return int(v)


def resolve_pool_budget(cfg: "BallistaConfig", key: str) -> int:
    """MEM_HOST_BUDGET / MEM_DEVICE_BUDGET -> bytes (0 = unlimited).

    'auto' picks a conservative default: 16 GiB for the host pool, and
    for the device pool 12 GiB on accelerator backends (under every
    shipping HBM size) / unlimited on CPU, mirroring the
    resolve_task_budget platform keying."""
    v = cfg.get(key)
    if isinstance(v, str):
        if v.strip().lower() == "auto":
            if key == MEM_DEVICE_BUDGET:
                from ..models.batch import _platform_remote

                return (12 << 30) if _platform_remote() else 0
            return 16 << 30
        v = int(v)
    return int(v)


class BallistaConfig:
    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings: Dict[str, Any] = {}
        for k, v in (settings or {}).items():
            self.set(k, v)

    @staticmethod
    def builder() -> "BallistaConfigBuilder":
        return BallistaConfigBuilder()

    def set(self, key: str, value: Any) -> None:
        entry = _ENTRIES.get(key)
        if entry is None:
            raise ConfigurationError(f"unknown configuration key {key!r}")
        if isinstance(value, str) and not isinstance(entry.default, str):
            try:
                value = entry.parse(value)
            except Exception as e:
                raise ConfigurationError(f"invalid value for {key}: {e}") from e
        expected = type(entry.default)
        if expected is float and isinstance(value, int) and not isinstance(value, bool):
            value = float(value)
        if not isinstance(value, expected) or (expected is int and isinstance(value, bool)):
            raise ConfigurationError(
                f"invalid value for {key}: expected {expected.__name__}, got {type(value).__name__} ({value!r})"
            )
        self._settings[key] = value

    def get(self, key: str) -> Any:
        entry = _ENTRIES.get(key)
        if entry is None:
            raise ConfigurationError(f"unknown configuration key {key!r}")
        return self._settings.get(key, entry.default)

    # typed accessors
    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def batch_size(self) -> int:
        return self.get(BATCH_SIZE)

    @property
    def agg_capacity(self) -> int:
        return self.get(AGG_CAPACITY)

    @property
    def join_output_factor(self) -> int:
        return self.get(JOIN_OUTPUT_FACTOR)

    @property
    def task_slots(self) -> int:
        return self.get(TASK_SLOTS)

    @property
    def job_timeout_s(self) -> int:
        return self.get(JOB_TIMEOUT_S)

    def to_dict(self) -> Dict[str, Any]:
        d = {k: e.default for k, e in _ENTRIES.items()}
        d.update(self._settings)
        return d

    def __repr__(self):
        return f"BallistaConfig({self._settings})"


class BallistaConfigBuilder:
    def __init__(self):
        self._settings: Dict[str, Any] = {}

    def set(self, key: str, value: Any) -> "BallistaConfigBuilder":
        self._settings[key] = value
        return self

    def build(self) -> BallistaConfig:
        return BallistaConfig(self._settings)
