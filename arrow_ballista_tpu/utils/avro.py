"""Minimal Avro Object Container File codec (pure Python, no deps).

Parity: the reference reads avro through DataFusion's avro reader
(reference ballista/client/src/context.rs:358-530 register_avro +
SURVEY §1 ENGINE layer).  No avro library ships in this image, so this
module implements the container format directly:

- spec: magic 'Obj\\x01', file metadata map (avro.schema JSON, avro.codec),
  16-byte sync marker, then blocks of (row_count, byte_len, payload, sync);
- binary encoding: zigzag varints for int/long, little-endian IEEE for
  float/double, length-prefixed utf8 for string/bytes;
- supported schema shape: a top-level record of primitive fields
  (null/boolean/int/long/float/double/string/bytes) and nullable unions
  ``["null", prim]`` — the tabular subset; codecs: null, deflate.

Both directions are implemented (the writer exists so tests and datagen
can produce real files), and the reader returns a pyarrow Table so avro
scans ride the same physical path as parquet/csv/json.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .errors import ExecutionError

MAGIC = b"Obj\x01"

_PRIMITIVES = ("null", "boolean", "int", "long", "float", "double",
               "string", "bytes")


# --------------------------------------------------------------------------
# binary primitives
# --------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_varint(out: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read_varint(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                return _zigzag_decode(acc)
            shift += 7

    def read_bytes(self) -> bytes:
        n = self.read_varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out


def _read_value(r: _Reader, schema) -> Any:
    if isinstance(schema, list):  # union
        idx = r.read_varint()
        return _read_value(r, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: _read_value(r, f["type"])
                    for f in schema["fields"]}
        schema = t
    if schema == "null":
        return None
    if schema == "boolean":
        b = r.read(1)
        return b == b"\x01"
    if schema in ("int", "long"):
        return r.read_varint()
    if schema == "float":
        return struct.unpack("<f", r.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", r.read(8))[0]
    if schema == "string":
        return r.read_bytes().decode("utf-8")
    if schema == "bytes":
        return r.read_bytes()
    raise ExecutionError(f"unsupported avro type {schema!r} (supported: "
                         f"records of {_PRIMITIVES} and nullable unions)")


def _write_value(out: io.BytesIO, schema, v: Any) -> None:
    if isinstance(schema, list):  # union: pick the branch by value
        idx = 0 if v is None else next(
            i for i, s in enumerate(schema) if s != "null")
        _write_varint(out, idx)
        _write_value(out, schema[idx], v)
        return
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                _write_value(out, f["type"], v[f["name"]])
            return
        schema = t
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if v else b"\x00")
    elif schema in ("int", "long"):
        _write_varint(out, int(v))
    elif schema == "float":
        out.write(struct.pack("<f", float(v)))
    elif schema == "double":
        out.write(struct.pack("<d", float(v)))
    elif schema == "string":
        b = str(v).encode("utf-8")
        _write_varint(out, len(b))
        out.write(b)
    elif schema == "bytes":
        _write_varint(out, len(v))
        out.write(v)
    else:
        raise ExecutionError(f"unsupported avro type {schema!r}")


# --------------------------------------------------------------------------
# container files
# --------------------------------------------------------------------------


def read_avro(path_or_file) -> Tuple[dict, List[dict]]:
    """Read a container file -> (schema_json, list of row dicts)."""
    if hasattr(path_or_file, "read"):
        data = path_or_file.read()
    else:
        with open(path_or_file, "rb") as f:
            data = f.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ExecutionError("not an avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        n = r.read_varint()
        if n == 0:
            break
        if n < 0:  # negative block count: size prefix follows
            r.read_varint()
            n = -n
        for _ in range(n):
            k = r.read_bytes().decode("utf-8")
            meta[k] = r.read_bytes()
    if "avro.schema" not in meta:
        raise ExecutionError("avro file missing avro.schema metadata")
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = r.read(16)
    rows: List[dict] = []
    while r.pos < len(r.buf):
        count = r.read_varint()
        blen = r.read_varint()
        payload = r.read(blen)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        elif codec != "null":
            raise ExecutionError(f"unsupported avro codec {codec!r}")
        br = _Reader(payload)
        for _ in range(count):
            rows.append(_read_value(br, schema))
        if r.read(16) != sync:
            raise ExecutionError("avro sync marker mismatch (corrupt file)")
    return schema, rows


def write_avro(path: str, schema: dict, rows: List[dict],
               codec: str = "null", sync: Optional[bytes] = None) -> None:
    """Write rows as an avro object container file."""
    sync = sync or os.urandom(16)
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    _write_varint(out, len(meta))
    for k, v in meta.items():
        kb = k.encode()
        _write_varint(out, len(kb))
        out.write(kb)
        _write_varint(out, len(v))
        out.write(v)
    out.write(b"\x00")  # end of metadata map
    out.write(sync)
    body = io.BytesIO()
    for row in rows:
        _write_value(body, schema, row)
    payload = body.getvalue()
    if codec == "deflate":
        c = zlib.compressobj(9, zlib.DEFLATED, -15)
        payload = c.compress(payload) + c.flush()
    elif codec != "null":
        raise ExecutionError(f"unsupported avro codec {codec!r}")
    _write_varint(out, len(rows))
    _write_varint(out, len(payload))
    out.write(payload)
    out.write(sync)
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(out.getvalue())


def read_avro_schema(path_or_file) -> dict:
    """Header-only read: the writer schema from the file metadata map.
    O(header bytes) — registration/schema-inference must not decode the
    whole file."""
    if hasattr(path_or_file, "read"):
        data = path_or_file.read(1 << 16)
    else:
        with open(path_or_file, "rb") as f:
            data = f.read(1 << 16)
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ExecutionError("not an avro object container file")
    while True:
        n = r.read_varint()
        if n == 0:
            break
        if n < 0:
            r.read_varint()
            n = -n
        for _ in range(n):
            k = r.read_bytes().decode("utf-8")
            v = r.read_bytes()
            if k == "avro.schema":
                return json.loads(v.decode("utf-8"))
    raise ExecutionError("avro file missing avro.schema metadata")


def _avro_arrow_type(s):
    import pyarrow as pa

    if isinstance(s, list):
        non_null = [x for x in s if x != "null"]
        return _avro_arrow_type(non_null[0]) if non_null else pa.null()
    if isinstance(s, dict):
        return _avro_arrow_type(s["type"])
    return {"boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
            "float": pa.float32(), "double": pa.float64(),
            "string": pa.string(), "bytes": pa.binary(),
            "null": pa.null()}[s]


def avro_arrow_schema(schema: dict):
    """Avro record schema -> (pyarrow schema, nullable-by-column map)."""
    import pyarrow as pa

    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise ExecutionError("avro scans need a top-level record schema")
    fields = [pa.field(f["name"], _avro_arrow_type(f["type"]))
              for f in schema["fields"]]
    nullable = {f["name"]: isinstance(f["type"], list) and "null" in f["type"]
                for f in schema["fields"]}
    return pa.schema(fields), nullable


def avro_to_arrow(path_or_file):
    """Container file -> pyarrow Table (the scan entry point)."""
    import pyarrow as pa

    schema, rows = read_avro(path_or_file)
    pa_schema, _ = avro_arrow_schema(schema)
    names = [f["name"] for f in schema["fields"]]
    cols = {n: [] for n in names}
    for row in rows:
        for n in names:
            cols[n].append(row.get(n))
    arrays = [pa.array(cols[name], type=pa_schema.field(name).type)
              for name in names]
    return pa.table(arrays, names=names)
