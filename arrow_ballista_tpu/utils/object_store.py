"""Object-store registry: scheme-based filesystem resolution for scans.

Parity: the reference resolves s3/oss/azure/hdfs URLs per scheme behind one
`BallistaObjectStoreRegistry` feeding DataFusion's object-store machinery
(reference ballista/core/src/utils.rs:88-174).  Here the registry resolves a
path/URL to a `pyarrow.fs.FileSystem` + in-store path, so every provider and
scan works identically against local disk, S3 (`s3://`), GCS (`gs://`),
HDFS (`hdfs://`), Azure (`az://`), or any custom scheme registered at
runtime (fsspec filesystems plug in via `register_fsspec`).

Paths keep their scheme end-to-end (catalog -> plan -> task), and IO sites
resolve lazily — the same discipline as the reference, where each scan
carries its object-store URL and executors resolve it locally.
"""
from __future__ import annotations

import posixpath
import re
from typing import Callable, Dict, List, Optional, Tuple

from .errors import ExecutionError

_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.-]*)://")

# custom scheme -> factory(url) -> (pyarrow FileSystem, path-inside-store)
_FACTORIES: Dict[str, Callable] = {}


def register_scheme(scheme: str, factory: Callable) -> None:
    """Register a resolver for ``scheme://`` URLs.

    ``factory(url) -> (pyarrow.fs.FileSystem, path)``.
    """
    _FACTORIES[scheme.lower()] = factory


def register_fsspec(scheme: str, fs) -> None:
    """Register an fsspec filesystem instance under a scheme (wrapped via
    pyarrow's FSSpecHandler so every scan path works against it)."""
    import pyarrow.fs as pafs

    wrapped = pafs.PyFileSystem(pafs.FSSpecHandler(fs))

    def factory(url: str):
        return wrapped, _strip_scheme(url)

    register_scheme(scheme, factory)


def scheme_of(path: str) -> Optional[str]:
    m = _SCHEME_RE.match(path)
    if m is None:
        return None
    s = m.group(1).lower()
    if len(s) == 1:  # windows drive letter, not a scheme
        return None
    return s


def _strip_scheme(url: str) -> str:
    return _SCHEME_RE.sub("", url)


_FS_CACHE: Dict[Tuple[str, str], object] = {}


def resolve(path: str):
    """path/URL -> (pyarrow FileSystem, in-store path).

    Filesystem clients are cached per (scheme, authority): a 500-file S3
    scan must not construct 500 S3FileSystem clients (credential/region
    resolution each time)."""
    import pyarrow.fs as pafs

    s = scheme_of(path)
    if s is None or s == "file":
        local = _strip_scheme(path) if s == "file" else path
        fs = _FS_CACHE.get(("file", ""))
        if fs is None:
            fs = _FS_CACHE[("file", "")] = pafs.LocalFileSystem()
        return fs, local
    factory = _FACTORIES.get(s)
    if factory is not None:
        return factory(path)
    inner = _strip_scheme(path)
    authority = inner.split("/", 1)[0]
    fs = _FS_CACHE.get((s, authority))
    if fs is not None:
        return fs, inner
    try:
        # pyarrow understands s3://, gs://, hdfs://, az:// natively
        fs, p = pafs.FileSystem.from_uri(path)
        _FS_CACHE[(s, authority)] = fs
        return fs, p
    except Exception:
        pass
    try:
        # fsspec covers the long tail (http, memory, ftp, ...)
        import fsspec

        fs = pafs.PyFileSystem(pafs.FSSpecHandler(fsspec.filesystem(s)))
        _FS_CACHE[(s, authority)] = fs
        return fs, inner
    except Exception as e:  # noqa: BLE001
        raise ExecutionError(f"no object store registered for scheme "
                             f"{s!r} ({path}): {e}") from e


def _rejoin(original: str, inner: str) -> str:
    s = scheme_of(original)
    return f"{s}://{inner}" if s is not None and s != "file" else inner


def is_dir(path: str) -> bool:
    import pyarrow.fs as pafs

    fs, p = resolve(path)
    try:
        return fs.get_file_info(p).type == pafs.FileType.Directory
    except Exception:  # noqa: BLE001
        return False


def list_files(path: str, suffixes: Tuple[str, ...]) -> List[str]:
    """Expand a directory URL to its matching files (scheme preserved);
    a file URL passes through as a singleton."""
    import pyarrow.fs as pafs

    fs, p = resolve(path)
    info = fs.get_file_info(p)
    if info.type == pafs.FileType.Directory:
        sel = pafs.FileSelector(p, recursive=False)
        out = sorted(
            f.path for f in fs.get_file_info(sel)
            if f.type == pafs.FileType.File
            and any(f.path.endswith(sfx) for sfx in suffixes))
        return [_rejoin(path, f) for f in out]
    if info.type == pafs.FileType.File:
        return [path]
    # not found: pass through as a single file and let the read fail with a
    # clear error — plans must stay constructible/serde-round-trippable on
    # machines that don't hold the data (the reference ships plans whose
    # object-store URLs only resolve on executors)
    return [path]


def open_input(path: str):
    """Random-access input file handle (works for parquet/csv readers)."""
    fs, p = resolve(path)
    return fs.open_input_file(p)


def parquet_file(path: str, read_dictionary=None):
    import pyarrow.parquet as pq

    fs, p = resolve(path)
    return pq.ParquetFile(p, filesystem=fs, read_dictionary=read_dictionary)


def read_parquet_row_groups(path: str, row_groups, columns,
                            read_dictionary=None):
    """``read_dictionary``: column names to decode as DictionaryArray
    straight from the parquet pages — the engine's string columns are
    dictionary-coded on device anyway, and skipping the re-encode measured
    5.6x off the scan's host conversion (0.45 s -> 0.08 s per 1M-row
    lineitem partition)."""
    with parquet_file(path, read_dictionary=read_dictionary) as pf:
        return pf.read_row_groups(row_groups, columns=columns)
