"""Daemon logging: stderr or rotating files.

Parity with the reference's tracing-appender setup
(reference ballista/core/src/config.rs:290-310 LogRotationPolicy
{Minutely, Hourly, Daily, Never} + executor_process.rs:94-129 /
scheduler bin/main.rs:94-130 file-or-stdout selection): daemons log to
stderr by default, or to ``<log_dir>/<prefix>.log`` with time-based
rotation when ``--log-dir`` is given.

One daemon per (log_dir, prefix): TimedRotatingFileHandler's rollover
rename is not multi-process safe, so co-located daemons must use distinct
prefixes (e.g. ``--log-file-name-prefix executor-50052``) or distinct
dirs — same discipline the reference's tracing-appender needs.

Log <-> trace correlation: ``log_scope(job_id=..., trace_id=...,
span_id=...)`` sets a thread-ambient context (entered by the executor's
task wrapper and the scheduler's event dispatch), and ``ContextFilter``
stamps those fields onto every record emitted inside the scope.  The
text format appends a ``[job=... trace=...]`` suffix when present;
``ballista.log.format=json`` (or ``BALLISTA_LOG_FORMAT=json``) switches
to one-JSON-object-per-line structured output, fields included.
"""
from __future__ import annotations

import contextlib
import json
import logging
import logging.handlers
import os
import threading
import time
from typing import Callable, Dict, Optional

ROTATION_POLICIES = ("minutely", "hourly", "daily", "never")
LOG_FORMATS = ("text", "json")

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

# thread-ambient correlation fields (job_id / trace_id / span_id)
_log_ctx = threading.local()

_CTX_FIELDS = ("job_id", "trace_id", "span_id")


@contextlib.contextmanager
def log_scope(job_id: str = "", trace_id: str = "", span_id: str = ""):
    """Stamp records emitted on this thread (via ``ContextFilter``) with
    the given correlation ids.  Nests: the previous scope is restored on
    exit."""
    prev = getattr(_log_ctx, "fields", None)
    _log_ctx.fields = {"job_id": job_id, "trace_id": trace_id,
                       "span_id": span_id}
    try:
        yield
    finally:
        _log_ctx.fields = prev


class ContextFilter(logging.Filter):
    """Copies the ambient ``log_scope`` fields onto every record (empty
    strings outside any scope), so formatters and downstream handlers can
    rely on the attributes existing."""

    def filter(self, record: logging.LogRecord) -> bool:
        fields = getattr(_log_ctx, "fields", None)
        for k in _CTX_FIELDS:
            setattr(record, k, fields.get(k, "") if fields else "")
        return True


class TextFormatter(logging.Formatter):
    """The classic text format plus a ``[job=... trace=...]`` suffix when
    the record carries correlation ids."""

    def format(self, record: logging.LogRecord) -> str:
        s = super().format(record)
        job_id = getattr(record, "job_id", "")
        if job_id:
            trace_id = getattr(record, "trace_id", "")
            s += f" [job={job_id}" \
                 + (f" trace={trace_id}" if trace_id else "") + "]"
        return s


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/message plus any
    correlation fields that are set (log aggregators join on job_id or
    trace_id against the span store / flight recorder)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {"ts": round(record.created, 3),
               "level": record.levelname,
               "logger": record.name,
               "message": record.getMessage()}
        for k in _CTX_FIELDS:
            v = getattr(record, k, "")
            if v:
                out[k] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def _make_formatter(fmt: str) -> logging.Formatter:
    if fmt not in LOG_FORMATS:
        raise ValueError(f"unknown log format {fmt!r}; "
                         f"expected one of {LOG_FORMATS}")
    return JsonFormatter() if fmt == "json" else TextFormatter(_FORMAT)


def init_logging(level: str = "INFO", log_dir: Optional[str] = None,
                 file_prefix: str = "ballista", rotation: str = "daily",
                 fmt: Optional[str] = None) -> None:
    """Configure the root logger.  ``log_dir=None`` -> stderr only.
    ``fmt``: "text" (default) or "json"; None reads
    ``BALLISTA_LOG_FORMAT`` (daemons pass ``ballista.log.format``)."""
    if rotation not in ROTATION_POLICIES:
        raise ValueError(f"unknown rotation policy {rotation!r}; "
                         f"expected one of {ROTATION_POLICIES}")
    if fmt is None:
        fmt = os.environ.get("BALLISTA_LOG_FORMAT", "text")
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    formatter = _make_formatter(fmt)
    ctx_filter = ContextFilter()
    if log_dir is None:
        h: logging.Handler = logging.StreamHandler()
        h.setFormatter(formatter)
        h.addFilter(ctx_filter)
        root.addHandler(h)
        return
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"{file_prefix}.log")
    if rotation == "never":
        h = logging.FileHandler(path)
    else:
        when = {"minutely": "M", "hourly": "H", "daily": "midnight"}[rotation]
        h = logging.handlers.TimedRotatingFileHandler(
            path, when=when, interval=1, backupCount=72)
    h.setFormatter(formatter)
    h.addFilter(ctx_filter)
    root.addHandler(h)
    # operational errors still surface on the console while normal flow
    # goes to the file (same split as the reference's print_thread_info
    # stdout diagnostics next to file tracing)
    console = logging.StreamHandler()
    console.setLevel(logging.WARNING)
    console.setFormatter(formatter)
    console.addFilter(ctx_filter)
    root.addHandler(console)


class ThrottledLogger:
    """At most one record per ``interval_s`` per *key* (interval-class).

    Retry loops that log every iteration flood the log exactly when the
    operator needs it readable (scheduler down => one status-report warning
    per second per executor).  Suppressed occurrences are counted and the
    count is appended to the next record that does get through.
    """

    def __init__(self, logger: logging.Logger, interval_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self._logger = logger
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_emit: Dict[str, float] = {}
        self._suppressed: Dict[str, int] = {}

    def log(self, level: int, key: str, msg: str, *args,
            exc_info=False) -> bool:
        now = self._clock()
        with self._lock:
            last = self._last_emit.get(key)
            if last is not None and now - last < self.interval_s:
                self._suppressed[key] = self._suppressed.get(key, 0) + 1
                return False
            n = self._suppressed.pop(key, 0)
            self._last_emit[key] = now
        if n:
            msg = f"{msg} ({n} similar suppressed in the last " \
                  f"{self.interval_s:.0f}s)"
        self._logger.log(level, msg, *args, exc_info=exc_info)
        return True

    def warning(self, key: str, msg: str, *args, **kw) -> bool:
        return self.log(logging.WARNING, key, msg, *args, **kw)

    def error(self, key: str, msg: str, *args, **kw) -> bool:
        return self.log(logging.ERROR, key, msg, *args, **kw)
