"""Daemon logging: stderr or rotating files.

Parity with the reference's tracing-appender setup
(reference ballista/core/src/config.rs:290-310 LogRotationPolicy
{Minutely, Hourly, Daily, Never} + executor_process.rs:94-129 /
scheduler bin/main.rs:94-130 file-or-stdout selection): daemons log to
stderr by default, or to ``<log_dir>/<prefix>.log`` with time-based
rotation when ``--log-dir`` is given.

One daemon per (log_dir, prefix): TimedRotatingFileHandler's rollover
rename is not multi-process safe, so co-located daemons must use distinct
prefixes (e.g. ``--log-file-name-prefix executor-50052``) or distinct
dirs — same discipline the reference's tracing-appender needs.
"""
from __future__ import annotations

import logging
import logging.handlers
import os
import threading
import time
from typing import Callable, Dict, Optional

ROTATION_POLICIES = ("minutely", "hourly", "daily", "never")

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def init_logging(level: str = "INFO", log_dir: Optional[str] = None,
                 file_prefix: str = "ballista", rotation: str = "daily") -> None:
    """Configure the root logger.  ``log_dir=None`` -> stderr only."""
    if rotation not in ROTATION_POLICIES:
        raise ValueError(f"unknown rotation policy {rotation!r}; "
                         f"expected one of {ROTATION_POLICIES}")
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        root.removeHandler(h)
    fmt = logging.Formatter(_FORMAT)
    if log_dir is None:
        h: logging.Handler = logging.StreamHandler()
        h.setFormatter(fmt)
        root.addHandler(h)
        return
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"{file_prefix}.log")
    if rotation == "never":
        h = logging.FileHandler(path)
    else:
        when = {"minutely": "M", "hourly": "H", "daily": "midnight"}[rotation]
        h = logging.handlers.TimedRotatingFileHandler(
            path, when=when, interval=1, backupCount=72)
    h.setFormatter(fmt)
    root.addHandler(h)
    # operational errors still surface on the console while normal flow
    # goes to the file (same split as the reference's print_thread_info
    # stdout diagnostics next to file tracing)
    console = logging.StreamHandler()
    console.setLevel(logging.WARNING)
    console.setFormatter(fmt)
    root.addHandler(console)


class ThrottledLogger:
    """At most one record per ``interval_s`` per *key* (interval-class).

    Retry loops that log every iteration flood the log exactly when the
    operator needs it readable (scheduler down => one status-report warning
    per second per executor).  Suppressed occurrences are counted and the
    count is appended to the next record that does get through.
    """

    def __init__(self, logger: logging.Logger, interval_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self._logger = logger
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_emit: Dict[str, float] = {}
        self._suppressed: Dict[str, int] = {}

    def log(self, level: int, key: str, msg: str, *args,
            exc_info=False) -> bool:
        now = self._clock()
        with self._lock:
            last = self._last_emit.get(key)
            if last is not None and now - last < self.interval_s:
                self._suppressed[key] = self._suppressed.get(key, 0) + 1
                return False
            n = self._suppressed.pop(key, 0)
            self._last_emit[key] = now
        if n:
            msg = f"{msg} ({n} similar suppressed in the last " \
                  f"{self.interval_s:.0f}s)"
        self._logger.log(level, msg, *args, exc_info=exc_info)
        return True

    def warning(self, key: str, msg: str, *args, **kw) -> bool:
        return self.log(logging.WARNING, key, msg, *args, **kw)

    def error(self, key: str, msg: str, *args, **kw) -> bool:
        return self.log(logging.ERROR, key, msg, *args, **kw)
