"""Speculative-execution policy: when is a running task a straggler?

Spark-heritage engines treat speculative re-execution as table stakes: on
a TPU pod one slow host (thermal throttle, noisy neighbor, dying NIC)
stalls a whole stage, and heartbeats cannot tell "slow" from "healthy".
The policy here mirrors Spark's `spark.speculation.*` family: compare
every running task's age against a quantile of the *same stage's
completed* attempt durations scaled by a multiplier, floor the cutoff at
a minimum runtime, and bound concurrent duplicates per stage.

Pure functions over graph state — the scheduler's monitor thread posts a
tick into the event loop and the handler calls :func:`find_candidates`
there, so all graph reads happen single-threaded (no locks, no sleeps).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..obs.stats import nearest_rank_quantile


@dataclasses.dataclass
class SpeculationPolicy:
    """Knobs from the ``ballista.speculation.*`` config keys."""

    enabled: bool = False
    quantile: float = 0.75
    multiplier: float = 1.5
    min_runtime_s: float = 5.0
    max_concurrent: int = 2
    interval_s: float = 1.0


def speculation_cutoff_s(durations: Sequence[float], quantile: float,
                         multiplier: float,
                         min_runtime_s: float) -> Optional[float]:
    """Age (seconds) beyond which a running task counts as a straggler,
    or None when the stage has no completed attempts to compare against
    (speculating with no baseline would duplicate every first wave).

    The quantile is taken over completed-attempt durations with the
    nearest-rank method (q=0.75 over 4 samples -> 3rd smallest); the
    cutoff is ``max(quantile_duration * multiplier, min_runtime_s)``.
    """
    base = nearest_rank_quantile(durations, quantile)
    if base is None:
        return None
    return max(base * float(multiplier), float(min_runtime_s))


def find_candidates(graph, now: float,
                    policy: SpeculationPolicy) -> List[Tuple[int, int, str]]:
    """(stage_id, partition, running_executor_id) of tasks whose age
    exceeds their stage's cutoff and that have no duplicate in flight.
    ``now`` is a ``time.monotonic()`` reading (TaskInfo.started_at base).
    """
    out: List[Tuple[int, int, str]] = []
    if graph.status != "running":
        return out
    for stage in graph.stages.values():
        if stage.state != "running":
            continue
        budget = policy.max_concurrent - len(stage.speculative_tasks)
        if budget <= 0:
            continue
        cutoff = speculation_cutoff_s(stage.durations, policy.quantile,
                                      policy.multiplier, policy.min_runtime_s)
        if cutoff is None:
            continue
        # oldest stragglers first, so a tight max_concurrent budget goes to
        # the tasks most likely to be genuinely stuck
        stragglers = []
        for p, info in enumerate(stage.task_infos):
            if info is None or info.state != "running" or not info.started_at:
                continue
            if p in stage.speculative_tasks:
                continue
            age = now - info.started_at
            if age > cutoff:
                stragglers.append((age, p, info.executor_id))
        stragglers.sort(reverse=True)
        for _, p, executor_id in stragglers[:budget]:
            out.append((stage.stage_id, p, executor_id))
    return out
