"""Serving-path caches: prepared-plan templates and result/subplan reuse.

The serving workload ("millions of users") is thousands of small repeated
queries, not one long scan — and before this module every submission paid
SQL parse -> logical plan -> physical plan -> ExecutionGraph construction
-> plan validation, even for the query it just ran.  Flare (PAPERS.md) is
the precedent: reuse specialized query artifacts across executions instead
of re-deriving them per submission.  The process-wide compiled-program
cache (ops/physical.py shared_program) already applies that lever at the
kernel level; this module applies it at the plan and result level.

Three layers, all owned by the SchedulerServer and shared by every session:

- :class:`PlanCache` — normalized SQL text (literals extracted as bound
  parameters, see :func:`normalize_sql`) -> a validated, *pre-AQE* physical
  plan template.  A hit skips parse/plan/validate/scalar-subquery execution
  and only stamps a fresh job id and clones the template plan
  (:func:`clone_plan`; plans are mutated in place during stage resolution
  and AQE, so live plan objects are never shared across jobs).  Entries are
  keyed on the referenced tables' versions (resolved file list + mtimes,
  or a registration generation for in-memory tables — recomputed at every
  lookup, which is what re-resolves scan file lists) and on the session
  config fingerprint, so DDL, data changes, or config changes invalidate.
- :class:`ResultCache` — completed-query result bytes keyed on
  (plan fingerprint, table versions), served straight from the scheduler:
  a repeat query never plans, launches, or executes anything.
- subplan entries in the same :class:`ResultCache` — completed
  shuffle-stage outputs keyed on the stage's structural fingerprint
  (:func:`stage_fingerprint`), rehydrated into later jobs by
  pre-completing the matching stage from the cached bytes.

AQE cooperation: templates capture the plan BEFORE any stage resolves, so
every run re-optimizes from its own fresh shuffle statistics.  Validator
cooperation: a template is validated once at creation; rebinding skips
re-validation because any scan-layout change flips the table-version
fingerprint and forces a full replan instead.
"""
from __future__ import annotations

import dataclasses
import hashlib
import itertools
import os
import shutil
import tempfile
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs import journal
from ..sql.lexer import tokenize
from ..utils.config import (
    PLAN_CACHE_ENABLED,
    PLAN_CACHE_MAX_BYTES,
    PLAN_CACHE_MAX_ENTRIES,
    RESULT_CACHE_ENABLED,
    RESULT_CACHE_MAX_BYTES,
    RESULT_CACHE_MAX_ENTRIES,
    RESULT_CACHE_MAX_ENTRY_BYTES,
    RESULT_CACHE_SUBPLAN,
    BallistaConfig,
)

# --------------------------------------------------------------------------
# SQL normalization: literals -> bound parameters
# --------------------------------------------------------------------------

#: keywords whose following number literal is plan STRUCTURE, not data — a
#: LIMIT shapes the physical plan (fetch counts baked into operators), so
#: it stays in the template text rather than becoming a parameter
_STRUCTURAL_NUMBER_AFTER = {"LIMIT", "OFFSET"}


def normalize_sql(sql: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Token-level canonical form of a statement: whitespace, comments and
    literal spellings stop mattering; number/string literals are replaced
    by ``?`` slots and returned as the bound-parameter vector.

    Returns ``(normalized_text, params)`` where params is a tuple of
    ``(kind, value)`` in slot order.  Two submissions with the same
    normalized text share one template family; each distinct parameter
    vector binds its own validated plan under that family (planning
    decisions may inspect literal values, so a bound plan is only reused
    for the exact vector it was planned with)."""
    parts: List[str] = []
    params: List[Tuple[str, str]] = []
    keep_next_number = False
    for tok in tokenize(sql):
        if tok.kind == "eof":
            break
        if tok.kind == "number" and not keep_next_number:
            parts.append("?")
            params.append(("number", tok.value))
        elif tok.kind == "string":
            parts.append("?")
            params.append(("string", tok.value))
        else:
            parts.append(tok.value)
        keep_next_number = (tok.kind == "ident"
                            and tok.upper in _STRUCTURAL_NUMBER_AFTER)
    return " ".join(parts), tuple(params)


# --------------------------------------------------------------------------
# version fingerprints
# --------------------------------------------------------------------------

#: file suffixes any provider's paths may resolve to; a fingerprint lists
#: whatever matches so appends (new file) and rewrites (new mtime) both flip
_DATA_SUFFIXES = (".parquet", ".csv", ".tbl", ".json", ".jsonl", ".ndjson",
                  ".avro", ".arrow")

#: registration generation for providers: a re-registered table is a new
#: provider object and draws a fresh generation, so DROP+CREATE (or a
#: MemoryTable replace) invalidates even when the data looks identical
_provider_gen = itertools.count(1)
_provider_gen_lock = threading.Lock()


def _digest(obj: object) -> str:
    return hashlib.sha1(repr(obj).encode()).hexdigest()


def _file_version(path: str) -> Tuple[str, int, int]:
    from ..utils import object_store as obs

    try:
        fs, p = obs.resolve(path)
        info = fs.get_file_info(p)
        mtime = getattr(info, "mtime_ns", None)
        if mtime is None:
            mtime = hash(str(getattr(info, "mtime", "")))
        size = info.size if info.size is not None else -1
        return (path, int(size), int(mtime))
    except Exception:  # ballista: allow=recovery-path-logging — unreachable
        # store: version as (-1, -1) 'unknown', which never equals a real
        # stat and therefore invalidates rather than falsely matching
        return (path, -1, -1)


def provider_version(provider) -> tuple:
    """Version token for one table provider.  Path-backed tables version as
    their resolved file list + per-file (size, mtime); in-memory tables as
    their row count; every provider also carries a registration generation
    (see ``_provider_gen``)."""
    from ..utils import object_store as obs

    gen = getattr(provider, "_serving_gen", None)
    if gen is None:
        with _provider_gen_lock:
            gen = getattr(provider, "_serving_gen", None)
            if gen is None:
                provider._serving_gen = gen = next(_provider_gen)
    paths = getattr(provider, "paths", None)
    if paths is not None:
        files: List[Tuple[str, int, int]] = []
        for p in paths:
            try:
                names = obs.list_files(p, _DATA_SUFFIXES)
            except Exception:  # ballista: allow=recovery-path-logging —
                # unlistable prefix: version the raw path; _file_version's
                # own fallback then yields the 'unknown' token
                names = [p]
            files.extend(_file_version(f) for f in names)
        return (type(provider).__name__, gen, tuple(files))
    table = getattr(provider, "table", None)
    if table is not None:
        return (type(provider).__name__, gen, int(table.num_rows))
    return (type(provider).__name__, gen)


def table_versions_fp(catalog, tables) -> str:
    """Digest of the current versions of ``tables`` as resolved through
    ``catalog`` (session overlays resolve to their overriding provider, so
    sessions with private same-named tables never share entries).  A
    dropped table versions as 'missing' — which never matches the
    fingerprint taken when it existed."""
    versions = []
    for name in sorted(set(tables)):
        try:
            versions.append((name, provider_version(catalog.provider(name))))
        except Exception:  # ballista: allow=recovery-path-logging — dropped
            # table: 'missing' is a distinct version that can never match a
            # fingerprint taken while the table existed
            versions.append((name, "missing"))
    return _digest(tuple(versions))


def config_fingerprint(config: BallistaConfig) -> str:
    """Digest of every effective config value except the cache knobs
    themselves (resizing a cache must not invalidate its contents)."""
    items = [(k, v) for k, v in sorted(config.to_dict().items())
             if not k.startswith("ballista.plan.cache.")
             and not k.startswith("ballista.result.cache.")]
    return _digest(tuple(items))


class RecordingCatalog:
    """Catalog wrapper that records which tables a planning pass touched —
    the template's invalidation scope.  Wraps any Catalog/OverlayCatalog."""

    def __init__(self, parent):
        self.parent = parent
        self.used = set()

    def table_schema(self, name: str):
        self.used.add(name)
        return self.parent.table_schema(name)

    def table_names(self):
        return self.parent.table_names()

    def provider(self, name: str):
        self.used.add(name)
        return self.parent.provider(name)


# --------------------------------------------------------------------------
# plan template cloning
# --------------------------------------------------------------------------


def _shared_leaf(v) -> bool:
    """Values a plan clone SHARES with its template instead of copying:
    immutable heavyweight data (arrow tables) and lazily-created runtime
    state that must never be duplicated (locks, metrics, compiled
    closures).  Templates are pristine — cloned before any execution — so
    the runtime cases are defensive."""
    import pyarrow as pa

    from ..ops.physical import MetricsSet

    if isinstance(v, pa.Table):
        return True
    if isinstance(v, type(threading.Lock())):
        return True
    if isinstance(v, MetricsSet):
        return True
    return callable(v) and not isinstance(v, type)


def clone_plan(plan):
    """Deep-copy a physical plan tree into a fresh, independently mutable
    instance (stage splitting, shuffle resolution and AQE all rewrite plans
    in place), sharing immutable heavy leaves with the original."""
    import copy

    memo: Dict[int, object] = {}
    seen = set()

    def seed(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for v in vars(node).values():
            if _shared_leaf(v):
                memo[id(v)] = v
        for c in node.children():
            seed(c)

    seed(plan)
    return copy.deepcopy(plan, memo)


def estimate_plan_bytes(plan, norm_text: str = "") -> int:
    """Rough resident-size estimate for the LRU byte budget: shared table
    data is excluded (the template does not own it); every plan node and
    its expression baggage is charged a flat 2 KiB."""
    count = 0
    stack = [plan]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        count += 1
        stack.extend(node.children())
    return count * 2048 + 2 * len(norm_text)


# --------------------------------------------------------------------------
# stage structural fingerprint (subplan entries)
# --------------------------------------------------------------------------


def _fp_value(v, out: List[str]) -> None:
    import numpy as np
    import pyarrow as pa

    from ..ops.physical import ExecutionPlan

    if v is None or isinstance(v, (bool, int, float, str)):
        out.append(repr(v))
    elif isinstance(v, pa.Table):
        out.append(f"patable({v.num_rows},{v.schema})")
    elif isinstance(v, np.ndarray):
        out.append("ndarray(" + hashlib.sha1(
            np.ascontiguousarray(v).tobytes()).hexdigest() + ")")
    elif isinstance(v, ExecutionPlan):
        _fp_node(v, out)
    elif isinstance(v, (list, tuple)):
        out.append("[")
        for item in v:
            _fp_value(item, out)
        out.append("]")
    elif isinstance(v, dict):
        out.append("{")
        for k in sorted(v, key=repr):
            out.append(repr(k))
            _fp_value(v[k], out)
        out.append("}")
    elif dataclasses.is_dataclass(v):
        out.append(repr(v))
    else:
        # Schema, Partitioning and expressions are dataclasses (stable
        # repr); anything else contributes its type only — two plans that
        # differ in such a field MAY collide, but subplan entries are
        # additionally keyed on table versions + config, and the engine's
        # plan state is dataclass/primitive throughout
        out.append(type(v).__name__)


def _fp_node(node, out: List[str]) -> None:
    out.append(type(node).__name__)
    for k in sorted(vars(node)):
        if k.startswith("_"):
            continue  # lazy runtime state (compiled closures, caches)
        out.append(k)
        _fp_value(vars(node)[k], out)


def stage_fingerprint(stage_plan) -> str:
    """Structural digest of an UNRESOLVED stage plan (taken at graph build,
    before shuffle resolution installs job-specific locations).  Identical
    subtrees of DIFFERENT queries fingerprint identically, so a shared
    scan+partial-aggregate stage can be served across templates."""
    out: List[str] = []
    _fp_node(stage_plan, out)
    return hashlib.sha1("\x1f".join(out).encode()).hexdigest()


# --------------------------------------------------------------------------
# prepared-plan cache
# --------------------------------------------------------------------------


@dataclasses.dataclass
class PlanTemplate:
    """One bound, validated plan template (see module docstring)."""

    norm_text: str
    params: tuple
    config_fp: str
    master_plan: object          # pristine pre-AQE physical plan (never run)
    scalars: Dict[str, object]   # executed scalar-subquery values
    schema: object               # final output Schema
    tables: Tuple[str, ...]      # invalidation scope
    table_fp: str
    nbytes: int = 0
    hits: int = 0

    def key(self) -> tuple:
        return (self.norm_text, self.params, self.config_fp)

    def bind(self):
        """A fresh plan instance for one submission."""
        return clone_plan(self.master_plan)


class PlanCache:
    """LRU over bound plan templates with entry and estimated-byte budgets.
    Thread-safe: lookups run on scheduler launch-pool threads and client
    threads concurrently."""

    def __init__(self, max_entries: int = 256, max_bytes: int = 64 << 20,
                 metrics=None):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, PlanTemplate]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    def lookup(self, norm_text: str, params: tuple, config_fp: str,
               catalog) -> Optional[PlanTemplate]:
        """Template for (text, params, config) IF the referenced tables
        still carry the fingerprint the template was planned against.
        Recomputing that fingerprint re-resolves the scan file lists; any
        drift invalidates the entry and the caller replans."""
        key = (norm_text, params, config_fp)
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            self._miss()
            return None
        current_fp = table_versions_fp(catalog, entry.tables)
        if current_fp != entry.table_fp:
            with self._lock:
                if self._entries.get(key) is entry:
                    del self._entries[key]
                    self._bytes -= entry.nbytes
                self.invalidations += 1
            self._miss()
            return None
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
            entry.hits += 1
            self.hits += 1
        if self.metrics is not None:
            self.metrics.record_plan_cache_hit()
        if journal.enabled():
            journal.emit("cache.hit", cache="plan")
        return entry

    def _miss(self) -> None:
        with self._lock:
            self.misses += 1
        if self.metrics is not None:
            self.metrics.record_plan_cache_miss()
        if journal.enabled():
            journal.emit("cache.miss", cache="plan")

    def store(self, template: PlanTemplate) -> None:
        if template.nbytes <= 0:
            template.nbytes = estimate_plan_bytes(template.master_plan,
                                                  template.norm_text)
        evicted = 0
        with self._lock:
            key = template.key()
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = template
            self._bytes += template.nbytes
            while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.nbytes
                self.evictions += 1
                evicted += 1
        for _ in range(evicted):
            if self.metrics is not None:
                self.metrics.record_cache_eviction()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "resident_bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "templates": [
                    {"text": k[0][:200], "params": len(k[1]),
                     "hits": e.hits, "bytes": e.nbytes}
                    for k, e in list(self._entries.items())[-16:]
                ],
            }


# --------------------------------------------------------------------------
# result / subplan cache
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _CacheEntry:
    kind: str            # 'result' | 'subplan'
    payload: object
    nbytes: int
    hits: int = 0


def result_cache_key(norm_text: str, params: tuple, config_fp: str,
                     table_fp: str) -> tuple:
    return ("result", norm_text, params, config_fp, table_fp)


def subplan_cache_key(stage_fp: str, config_fp: str, table_fp: str) -> tuple:
    return ("subplan", stage_fp, config_fp, table_fp)


class ResultCache:
    """Byte-bounded LRU of completed results and shuffle-stage outputs.

    Result payloads are ``{"partitions": [(part, [file_bytes, ...]), ...],
    "schema": Schema}`` — the exact on-disk IPC bytes of the final stage,
    copied into memory at completion (the executor files themselves are
    deleted by the job-data cleanup timer, so paths cannot be cached).
    Subplan payloads are ``{"outputs": [(map_part, executor_id,
    [(output_partition, num_rows, num_bytes, checksum, file_bytes),
    ...]), ...]}``.  Entries are spooled back to disk on rehydration via
    :meth:`spool` (readers treat a ``port == 0`` location's path as
    authoritative, which only holds in-process / shared-filesystem — the
    caller gates on that)."""

    def __init__(self, max_entries: int = 512, max_bytes: int = 256 << 20,
                 max_entry_bytes: int = 32 << 20, metrics=None):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.max_entry_bytes = int(max_entry_bytes)
        self.metrics = metrics
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _CacheEntry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.subplan_hits = 0
        self.evictions = 0
        self.invalidations = 0
        self.rejected_oversize = 0
        self._spool_dir: Optional[str] = None
        # (norm_text, params, config_fp) -> referenced table names, learned
        # at capture: lets a later submission compute the table-version
        # fingerprint (and so probe the result cache) WITHOUT a plan-cache
        # template — the two caches stay independently toggleable
        self._tables_hint: "OrderedDict[tuple, tuple]" = OrderedDict()

    def get(self, key: tuple):
        """Payload for ``key`` or None.  Table versions are part of the key
        (recomputed by the caller per submission), so staleness manifests
        as a plain miss — stale entries age out by LRU."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                entry.hits += 1
                if entry.kind == "subplan":
                    self.subplan_hits += 1
                else:
                    self.hits += 1
        if entry is None:
            if journal.enabled():
                journal.emit("cache.miss", cache="result")
            return None
        if self.metrics is not None:
            self.metrics.record_result_cache_hit()
        if journal.enabled():
            journal.emit("cache.hit", cache=entry.kind)
        return entry.payload

    def put(self, key: tuple, payload, nbytes: int, kind: str = "result") -> None:
        if nbytes > self.max_entry_bytes:
            with self._lock:
                self.rejected_oversize += 1
            return
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _CacheEntry(kind, payload, int(nbytes))
            self._bytes += int(nbytes)
            while self._entries and (
                    len(self._entries) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, dropped = self._entries.popitem(last=False)
                self._bytes -= dropped.nbytes
                self.evictions += 1
                evicted += 1
        for _ in range(evicted):
            if self.metrics is not None:
                self.metrics.record_cache_eviction()

    def remember_tables(self, text_key: tuple, tables) -> None:
        with self._lock:
            self._tables_hint[text_key] = tuple(tables)
            self._tables_hint.move_to_end(text_key)
            while len(self._tables_hint) > 4 * self.max_entries:
                self._tables_hint.popitem(last=False)

    def tables_for(self, text_key: tuple):
        with self._lock:
            return self._tables_hint.get(text_key)

    def invalidate_where(self, pred) -> int:
        """Drop entries whose key matches ``pred`` (used on DDL to purge a
        table's results eagerly rather than waiting for LRU age-out)."""
        with self._lock:
            doomed = [k for k in self._entries if pred(k)]
            for k in doomed:
                self._bytes -= self._entries.pop(k).nbytes
            self.invalidations += len(doomed)
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # --- rehydration spool -----------------------------------------------
    def spool(self, job_id: str, stage_id: int, name: str, data: bytes) -> str:
        """Write cached stage bytes to a scheduler-local file a ``port==0``
        PartitionLocation can point at; files live under a per-job dir so
        :meth:`cleanup_job` (wired into the scheduler's job-data cleanup)
        removes them with the job."""
        with self._lock:
            if self._spool_dir is None:
                self._spool_dir = tempfile.mkdtemp(prefix="ballista-subplan-")
            root = self._spool_dir
        d = os.path.join(root, job_id, str(stage_id))
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, name)
        with open(path, "wb") as fh:
            fh.write(data)
        return path

    def cleanup_job(self, job_id: str) -> None:
        with self._lock:
            root = self._spool_dir
        if root is None:
            return
        shutil.rmtree(os.path.join(root, job_id), ignore_errors=True)

    def close(self) -> None:
        with self._lock:
            root, self._spool_dir = self._spool_dir, None
        if root is not None:
            shutil.rmtree(root, ignore_errors=True)

    def snapshot(self) -> dict:
        with self._lock:
            kinds: Dict[str, int] = {}
            for e in self._entries.values():
                kinds[e.kind] = kinds.get(e.kind, 0) + 1
            return {
                "entries": len(self._entries),
                "by_kind": kinds,
                "resident_bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "max_entry_bytes": self.max_entry_bytes,
                "hits": self.hits,
                "subplan_hits": self.subplan_hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "rejected_oversize": self.rejected_oversize,
            }


def caches_from_config(config: BallistaConfig, metrics=None
                       ) -> Tuple[PlanCache, ResultCache]:
    """Build the scheduler's cache pair from its startup config.  The
    enable knobs stay per-session (checked at submit), so one scheduler
    serves cache-on and cache-off sessions simultaneously; the budgets are
    fixed at scheduler startup."""
    plan = PlanCache(config.get(PLAN_CACHE_MAX_ENTRIES),
                     config.get(PLAN_CACHE_MAX_BYTES), metrics=metrics)
    result = ResultCache(config.get(RESULT_CACHE_MAX_ENTRIES),
                         config.get(RESULT_CACHE_MAX_BYTES),
                         config.get(RESULT_CACHE_MAX_ENTRY_BYTES),
                         metrics=metrics)
    return plan, result


def plan_cache_enabled(config: BallistaConfig) -> bool:
    return bool(config.get(PLAN_CACHE_ENABLED))


def result_cache_enabled(config: BallistaConfig) -> bool:
    return bool(config.get(RESULT_CACHE_ENABLED))


def subplan_cache_enabled(config: BallistaConfig) -> bool:
    return bool(config.get(RESULT_CACHE_ENABLED)) \
        and bool(config.get(RESULT_CACHE_SUBPLAN))


# --------------------------------------------------------------------------
# per-job serving info (threaded through SchedulerServer.submit_job)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ServingJobInfo:
    """What the serving path knows about a submitted SQL job: the cache key
    material for capture at completion, whether the graph was built from an
    already-validated template (skip re-validation), and whether subplan
    preload/capture applies (local-files deployments only)."""

    result_key: Optional[tuple] = None
    table_fp: str = ""
    config_fp: str = ""
    prevalidated: bool = False
    subplan: bool = False
    capture_result: bool = False
    # final result Schema, needed to decode the captured IPC bytes later;
    # set by the planning closure (or from the template on a hit)
    schema: object = None
    # referenced table names (for the result cache's tables hint)
    tables: Tuple[str, ...] = ()
    # stage_id -> structural fingerprint for every non-final stage, computed
    # at graph build; stages preloaded from cache are excluded from capture
    stage_fps: Dict[int, str] = dataclasses.field(default_factory=dict)
    preloaded: set = dataclasses.field(default_factory=set)
    # template created by this job's planning pass: stored into the plan
    # cache by the scheduler only after the graph VALIDATES, so a broken
    # plan can never become a reusable template
    pending_template: Optional[PlanTemplate] = None


def capture_result_payload(locations, schema,
                           max_entry_bytes: int) -> Optional[Tuple[dict, int]]:
    """Copy a completed job's final-stage IPC files into a result payload.
    Returns ``(payload, nbytes)`` or None when any file is unreadable on
    this host (remote executors without a shared filesystem) or the total
    exceeds the per-entry cap.  Row-empty locations are skipped exactly as
    the client-side readers skip them, so a cache hit decodes the same
    byte set the uncached path would have read."""
    partitions: List[Tuple[int, List[bytes]]] = []
    total = 0
    for part in sorted(locations):
        blobs: List[bytes] = []
        for loc in locations[part]:
            if not loc.num_rows:
                continue
            try:
                with open(loc.path, "rb") as fh:
                    data = fh.read()
            except OSError:
                return None
            total += len(data)
            if total > max_entry_bytes:
                return None
            blobs.append(data)
        partitions.append((part, blobs))
    return {"partitions": partitions, "schema": schema}, total


def capture_stage_payload(stage, max_entry_bytes: int
                          ) -> Optional[Tuple[dict, int]]:
    """Copy one completed shuffle stage's output files into a subplan
    payload (see :class:`ResultCache` docstring for the shape)."""
    outputs = []
    total = 0
    for map_part, (executor_id, writes) in sorted(stage.outputs.items()):
        rows = []
        for w in writes:
            try:
                with open(w.path, "rb") as fh:
                    data = fh.read()
            except OSError:
                return None
            total += len(data)
            if total > max_entry_bytes:
                return None
            rows.append((w.output_partition, w.num_rows, w.num_bytes,
                         w.checksum, data))
        outputs.append((map_part, executor_id, rows))
    return {"outputs": outputs}, total
