"""Adaptive query execution: runtime re-optimization at stage boundaries.

Flare-style re-specialization (PAPERS.md) for the stage graph: the
scheduler already knows, before a downstream stage launches, the *actual*
per-partition row/byte sizes its producers shuffled — so it re-optimizes
the not-yet-resolved part of the ExecutionGraph instead of trusting
plan-time estimates.  Three rewrites, each gated on observed numbers and
on ``ballista.aqe.*`` config keys (default on):

1. **Dynamic partition coalescing** (resolve time): adjacent tiny reduce
   partitions merge into one task up to a target row/byte size.  This
   generalizes the static all-or-nothing heuristic
   (``ExecutionStage.maybe_coalesce``): a 46-task final over a few hundred
   rows still collapses to one task, but a medium stage now coalesces to a
   handful of right-sized tasks instead of not at all.
2. **Shuffle-join -> broadcast switch** (stage completion): when a join
   build side's actual shuffle output is under the broadcast threshold,
   the downstream partitioned join flips to broadcast — and when the probe
   side's exchange feeds only that join and hasn't completed, the exchange
   stage is grafted away entirely (the join probes the producer's own
   partitions, skipping a full shuffle of the big side).
3. **Skew splitting** (resolve time): a hot partition (skew factor over
   ``ballista.aqe.skew.factor``, above a min-size floor) splits into
   several tasks, each reading a contiguous sub-range of the producer's
   map outputs; other inputs of the stage are replicated per split, which
   is exactly correct for probe-side splits of a join and for partial
   aggregation (states merge downstream).

Safety: every rewrite happens on the scheduler's single event-loop thread,
between resolution and first task launch; the plan validator re-checks the
mutated stage/graph after every rewrite (``analysis/plan_checks.py``
``validate_rewrite``); the ``scheduler.aqe.before_rewrite`` failpoint
fires between decision and mutation so chaos plans can perturb exactly
that window.  A ``raise`` from the failpoint (or any decision-stage error)
abandons the rewrite and leaves the graph untouched — AQE is an
optimization, never a correctness dependency.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Tuple

from .. import faults
from ..ops.operators import (
    CoalescePartitionsExec,
    FilterExec,
    HashAggregateExec,
    JoinExec,
    ProjectionExec,
    RenameExec,
)
from ..ops.shuffle import (
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
)
from .planner import collect_nodes
from .types import TaskId

log = logging.getLogger(__name__)

FAILPOINT = "scheduler.aqe.before_rewrite"


@dataclasses.dataclass
class AqePolicy:
    """Per-job AQE knobs (mirrors SpeculationPolicy; built from the
    session config by the scheduler, defaults apply otherwise)."""

    enabled: bool = True
    coalesce_enabled: bool = True
    coalesce_target_rows: int = 8192
    coalesce_target_bytes: int = 1 << 20
    broadcast_enabled: bool = True
    broadcast_threshold_rows: int = 4_000_000
    skew_enabled: bool = True
    skew_factor: float = 4.0
    skew_min_rows: int = 1_000_000
    # re-validate the mutated graph after every rewrite (tracks
    # ballista.analysis.plan_checks)
    validate: bool = True

    @staticmethod
    def from_config(cfg) -> "AqePolicy":
        if cfg is None:
            return AqePolicy()
        from ..utils import config as C

        return AqePolicy(
            enabled=cfg.get(C.AQE_ENABLED),
            coalesce_enabled=cfg.get(C.AQE_COALESCE_ENABLED),
            coalesce_target_rows=cfg.get(C.AQE_COALESCE_TARGET_ROWS),
            coalesce_target_bytes=cfg.get(C.AQE_COALESCE_TARGET_BYTES),
            broadcast_enabled=cfg.get(C.AQE_BROADCAST_ENABLED),
            broadcast_threshold_rows=cfg.get(C.AQE_BROADCAST_THRESHOLD_ROWS),
            skew_enabled=cfg.get(C.AQE_SKEW_ENABLED),
            skew_factor=cfg.get(C.AQE_SKEW_FACTOR),
            skew_min_rows=cfg.get(C.AQE_SKEW_MIN_ROWS),
            validate=cfg.get(C.ANALYSIS_PLAN_CHECKS),
        )


# --------------------------------------------------------------------------
# plan-shape analysis
# --------------------------------------------------------------------------

#: operators through which a sub-range of input rows is independently
#: processable per task: row-wise transforms plus the stage's root writer
#: (hash partitioning is row-wise; a final passthrough writer keeps slice
#: order because slices stay contiguous and in map order)
_ROW_WISE = (FilterExec, ProjectionExec, RenameExec, ShuffleWriterExec)


def _aligned_readers(plan) -> Tuple[List[ShuffleReaderExec], bool]:
    """Reader leaves whose partition index IS the stage's task partition
    index.  Descends every child except a broadcast join's build side and
    a CoalescePartitionsExec input — those subtrees are driven by their
    own partition counts, not the task index, and must not be remapped.
    ``ok`` is False when an aligned-position leaf is not a shuffle reader
    (a scan owns the stage's partitioning: nothing to rewrite)."""
    aligned: List[ShuffleReaderExec] = []
    ok = [True]

    def walk(node, al: bool) -> None:
        kids = node.children()
        if not kids:
            if not al:
                return
            if isinstance(node, ShuffleReaderExec):
                aligned.append(node)
            else:
                ok[0] = False
            return
        if isinstance(node, JoinExec) and node.dist == "broadcast":
            walk(node.left, al)
            walk(node.right, False)
            return
        if isinstance(node, CoalescePartitionsExec):
            walk(node.input, False)
            return
        for c in kids:
            walk(c, al)

    walk(plan, True)
    return aligned, ok[0]


def _path_to(node, target, path: List) -> bool:
    """Collect the operators strictly above ``target`` (bottom-up)."""
    if node is target:
        return True
    for c in node.children():
        if _path_to(c, target, path):
            path.append(node)
            return True
    return False


def _split_safe(root, reader) -> bool:
    """True when every operator between the stage root and ``reader`` can
    take a sub-range of the reader's rows per task without changing the
    union of the stage's outputs: row-wise ops, partial aggregation
    (partial states over a slice are still valid states — the downstream
    final agg merges them), and joins entered via the probe (left) side —
    each probe row still sees the full build input.  Everything else
    (final/single aggregation, sort, limit, full joins, build sides)
    deduplicates or orders across the whole partition and must see it
    intact."""
    path: List = []
    if not _path_to(root, reader, path):
        return False
    below = reader
    for node in path:
        if isinstance(node, JoinExec):
            if node.join_type == "full" or below is not node.left:
                return False
        elif isinstance(node, HashAggregateExec):
            if node.mode != "partial":
                return False
        elif not isinstance(node, _ROW_WISE):
            return False
        below = node
    return True


def _split_indices(weights: List[int], k: int) -> List[Tuple[int, int]]:
    """Partition ``range(len(weights))`` into ``k`` contiguous slices of
    roughly equal total weight (at least one element each)."""
    n = len(weights)
    k = max(1, min(k, n))
    total = sum(weights) or 1
    out: List[Tuple[int, int]] = []
    lo, acc = 0, 0
    for i, w in enumerate(weights):
        acc += w
        if len(out) < k - 1 and acc * k >= total * (len(out) + 1) \
                and (n - i - 1) >= (k - len(out) - 1):
            out.append((lo, i + 1))
            lo = i + 1
    out.append((lo, n))
    return out


# --------------------------------------------------------------------------
# resolve-time rewrite: dynamic coalescing + skew splitting
# --------------------------------------------------------------------------

def _plan_groups(stage, policy: AqePolicy,
                 readers: List[ShuffleReaderExec]):
    """Decide the stage's new task layout from the observed partition
    sizes.  Returns (groups, splits): ``groups`` is a list of task
    definitions, each a list of ``(source_partition, lo, hi)`` — ``lo/hi``
    are a map-output slice for skew splits, ``None`` for whole partitions;
    ``splits`` maps a hot partition to its target reader."""
    n = stage.partitions
    rows = [0] * n
    byts = [0] * n
    for r in readers:
        for q, locs in r.locations.items():
            if 0 <= q < n:
                rows[q] += sum(l.num_rows for l in locs)
                byts[q] += sum(l.num_bytes for l in locs)
    mean = sum(rows) / n if n else 0.0

    # skew: split the biggest contributor's map-output list for a hot
    # partition, provided the path to the stage root tolerates slicing
    splits: Dict[int, Tuple[ShuffleReaderExec, List[Tuple[int, int]]]] = {}
    if policy.skew_enabled and mean > 0:
        for q in range(n):
            if rows[q] < policy.skew_min_rows \
                    or rows[q] <= policy.skew_factor * mean:
                continue
            target = max(readers, key=lambda r: sum(
                l.num_rows for l in r.locations.get(q, [])))
            locs = target.locations.get(q, [])
            if len(locs) < 2 or not _split_safe(stage.resolved_plan, target):
                continue
            k = min(len(locs), max(2, round(rows[q] / max(mean, 1.0))))
            slices = _split_indices([l.num_rows for l in locs], k)
            if len(slices) > 1:
                splits[q] = (target, slices)

    # coalescing: greedy pack adjacent partitions while the merged task
    # stays under both targets (0 disables that dimension; both 0 = off)
    tgt_r = policy.coalesce_target_rows
    tgt_b = policy.coalesce_target_bytes
    can_coalesce = policy.coalesce_enabled and (tgt_r > 0 or tgt_b > 0)
    groups: List[List[Tuple[int, Optional[int], Optional[int]]]] = []
    cur: List[Tuple[int, Optional[int], Optional[int]]] = []
    cur_rows = cur_bytes = 0

    def flush():
        nonlocal cur, cur_rows, cur_bytes
        if cur:
            groups.append(cur)
        cur, cur_rows, cur_bytes = [], 0, 0

    for q in range(n):
        if q in splits:
            flush()
            for lo, hi in splits[q][1]:
                groups.append([(q, lo, hi)])
            continue
        fits = (not cur
                or ((tgt_r <= 0 or cur_rows + rows[q] <= tgt_r)
                    and (tgt_b <= 0 or cur_bytes + byts[q] <= tgt_b)))
        if not can_coalesce or not fits:
            flush()
        cur.append((q, None, None))
        cur_rows += rows[q]
        cur_bytes += byts[q]
    flush()
    return groups, splits


def _apply_groups(stage, groups, splits,
                  readers: List[ShuffleReaderExec]) -> None:
    """Remap every aligned reader to the new task layout.  The split
    target reader fetches only its slice of a hot partition; every other
    reader replicates the whole source partition into each slice task
    (the join build / secondary input every probe slice must see)."""
    for r in readers:
        new_locs: Dict[int, list] = {}
        for gi, group in enumerate(groups):
            merged = []
            for q, lo, hi in group:
                locs = r.locations.get(q, [])
                if lo is not None and q in splits and splits[q][0] is r:
                    merged.extend(locs[lo:hi])
                else:
                    merged.extend(locs)
            new_locs[gi] = merged
        if getattr(r, "_orig_partition_count", None) is None:
            # rollback rebuilds UnresolvedShuffleExec from this: it must
            # restore the PLANNED partitioning (same contract as the
            # static coalescing path)
            r._orig_partition_count = r.partition_count
        r.partition_count = len(groups)
        r.locations = new_locs


def _resize_stage(stage, n_new: int) -> None:
    if getattr(stage, "_orig_partitions", None) is None:
        stage._orig_partitions = stage.partitions
    stage.partitions = n_new
    stage.task_infos = [None] * n_new
    # budgets/attempt counters keep per-index monotonicity across
    # rollbacks; skew splitting can exceed the planned length, so extend
    # (never truncate — rollback restores the planned count)
    if len(stage.task_failures) < n_new:
        stage.task_failures.extend([0] * (n_new - len(stage.task_failures)))
    if len(stage.task_attempts) < n_new:
        stage.task_attempts.extend([0] * (n_new - len(stage.task_attempts)))


def rewrite_resolved_stage(graph, stage, policy: AqePolicy) -> None:
    """Dynamic coalesce + skew split on a just-resolved stage.  Called
    from ``ExecutionGraph.revive`` after ``resolved_plan`` is built and
    before any of the stage's tasks launch."""
    if not policy.enabled or stage.resolved_plan is None \
            or stage.partitions <= 1:
        return
    readers, ok = _aligned_readers(stage.resolved_plan)
    if not ok or not readers:
        return
    if any(r.partition_count != stage.partitions for r in readers):
        return  # already rewritten, or partition-count mismatch: hands off
    groups, splits = _plan_groups(stage, policy, readers)
    coalesced = sum(len(g) - 1 for g in groups if len(g) > 1)
    if not coalesced and not splits:
        return
    kinds = (["coalesce"] if coalesced else []) + (["skew"] if splits else [])
    if not _fire_failpoint(graph, stage.stage_id, "+".join(kinds)):
        return
    before = stage.partitions
    prior_schema = stage.resolved_plan.schema
    _apply_groups(stage, groups, splits, readers)
    _resize_stage(stage, len(groups))
    record = {
        "stage_id": stage.stage_id,
        "stage_attempt": stage.stage_attempt,
        "kinds": kinds,
        "partitions_before": before,
        "partitions_after": len(groups),
        "coalesced_partitions": coalesced,
        "skew_splits": [{"partition": q, "tasks": len(s)}
                        for q, (_r, s) in sorted(splits.items())],
    }
    _record(graph, stage, record)
    if coalesced:
        graph.aqe_events.append(("coalesce", coalesced))
    if splits:
        graph.aqe_events.append(("skew", len(splits)))
    if policy.validate:
        from ..analysis.plan_checks import validate_rewrite

        validate_rewrite(graph, stage, prior_schema)


# --------------------------------------------------------------------------
# completion-time rewrite: shuffle-join -> broadcast switch
# --------------------------------------------------------------------------

def maybe_broadcast_switch(graph, stage, events: List[Tuple[str, object]],
                           policy: AqePolicy) -> None:
    """On completion of ``stage``: if its actual shuffle output is under
    the broadcast threshold, flip every downstream partitioned join that
    builds from it to a broadcast join, and graft away the probe side's
    exchange when that exchange feeds only this join and hasn't finished
    (its in-flight tasks are cancelled via ``events``)."""
    if not (policy.enabled and policy.broadcast_enabled):
        return
    rows = sum(w.num_rows for _ex, writes in stage.outputs.values()
               for w in writes)
    if rows > policy.broadcast_threshold_rows:
        return
    for cid in list(stage.output_links):
        consumer = graph.stages.get(cid)
        if consumer is None or consumer.state != "unresolved":
            continue
        for join in collect_nodes(consumer.plan, JoinExec):
            if join.dist != "partitioned" or join.join_type == "full":
                continue
            if not isinstance(join.right, UnresolvedShuffleExec) \
                    or join.right.stage_id != stage.stage_id:
                continue
            if not _fire_failpoint(graph, cid, "broadcast"):
                continue
            join.dist = "broadcast"
            grafted = _maybe_graft_probe_exchange(graph, consumer, join,
                                                  events)
            record = {
                "stage_id": cid,
                "stage_attempt": consumer.stage_attempt,
                "kinds": ["broadcast"],
                "build_stage_id": stage.stage_id,
                "build_rows": rows,
                "grafted_stage_id": grafted,
            }
            _record(graph, consumer, record)
            graph.aqe_events.append(("broadcast", 1))
            if policy.validate:
                from ..analysis.plan_checks import validate_rewrite

                validate_rewrite(graph, consumer, None)


def _maybe_graft_probe_exchange(graph, consumer, join,
                                events) -> Optional[int]:
    """Replace the join's probe-side exchange with the exchange's own
    input subtree when nothing else reads it — the broadcast join no
    longer needs the probe co-partitioned, so the (usually big) probe
    shuffle is skipped entirely.  Returns the absorbed stage id."""
    left = join.left
    if not isinstance(left, UnresolvedShuffleExec):
        return None
    producer = graph.stages.get(left.stage_id)
    if producer is None or producer.state == "successful":
        return None  # work already done: keep reading its output
    if producer.state != "unresolved" and producer.producer_ids:
        # resolution mutates stage plans in place: a non-leaf exchange
        # that already resolved reads its upstreams through baked
        # ShuffleReaderExecs, and absorbing that subtree would sever the
        # lineage (orphaned producer stages, stale locations after a
        # rollback).  Keep the exchange — the broadcast flip alone stands.
        return None
    if producer.output_links != [consumer.stage_id]:
        return None  # another stage reads this exchange
    feeds = [u for u in collect_nodes(consumer.plan, UnresolvedShuffleExec)
             if u.stage_id == left.stage_id]
    if len(feeds) != 1:
        return None  # self-join: the exchange feeds the consumer twice
    # cancel the exchange's in-flight attempts before absorbing it
    infos = [i for i in producer.task_infos if i is not None] \
        + list(producer.speculative_tasks.values())
    for info in infos:
        if info.state == "running":
            events.append(("cancel_task", (
                info.executor_id,
                TaskId(graph.job_id, producer.stage_id, info.partition,
                       task_attempt=info.attempt,
                       stage_attempt=producer.stage_attempt,
                       speculative=info.speculative))))
    join.left = producer.plan.input
    del graph.stages[producer.stage_id]
    consumer.producer_ids = sorted(
        {u.stage_id for u in collect_nodes(consumer.plan,
                                           UnresolvedShuffleExec)})
    # the absorbed exchange's producers now feed the consumer directly
    for pid in producer.producer_ids:
        upstream = graph.stages.get(pid)
        if upstream is None:
            continue
        links = [consumer.stage_id if l == producer.stage_id else l
                 for l in upstream.output_links]
        seen = set()
        upstream.output_links = [l for l in links
                                 if not (l in seen or seen.add(l))]
    # the join now emits the grafted subtree's partitioning
    n = consumer.plan.output_partition_count()
    consumer.partitions = n
    consumer._orig_partitions = None
    consumer.task_infos = [None] * n
    if len(consumer.task_failures) < n:
        consumer.task_failures.extend(
            [0] * (n - len(consumer.task_failures)))
    if len(consumer.task_attempts) < n:
        consumer.task_attempts.extend(
            [0] * (n - len(consumer.task_attempts)))
    return producer.stage_id


# --------------------------------------------------------------------------
# shared plumbing
# --------------------------------------------------------------------------

def _fire_failpoint(graph, stage_id: int, kind: str) -> bool:
    """Evaluate the pre-mutation failpoint.  ``drop`` (and any injected
    error) abandons the rewrite — the graph is still unmutated here, so
    skipping is always safe."""
    try:
        rule = faults.inject(FAILPOINT, job_id=graph.job_id,
                             stage_id=stage_id, kind=kind)
    except Exception as e:  # injected raise: AQE degrades to a no-op
        log.warning("aqe: rewrite of %s stage %s abandoned: %s",
                    graph.job_id, stage_id, e)
        return False
    return rule is None or rule.action != "drop"


def _record(graph, stage, record: dict) -> None:
    stage.aqe_rewrites.append(record)
    graph.aqe_log.append(record)
