"""Cluster state: executor pool + job registry (in-memory backend).

Parity: reference ballista/scheduler/src/cluster/ — the ``ClusterState`` /
``JobState`` traits (cluster/mod.rs:199-372) and their in-memory
implementation (cluster/memory.rs).  Slot reservation is atomic under a
lock, with the reference's two distribution policies: **bias** (pack onto
the fewest executors, reference cluster/mod.rs reserve_slots_bias) and
**round-robin** (spread, reserve_slots_round_robin).

The KV/etcd-backed variants of the reference are future backends behind the
same interface (SURVEY.md §2.2 cluster abstraction).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from .execution_graph import ExecutionGraph
from .types import (
    ExecutorHeartbeat,
    ExecutorMetadata,
    ExecutorReservation,
    JobStatus,
)

DEFAULT_EXECUTOR_TIMEOUT_S = 180.0
# one documented drain-grace delta between "stop offering work" and
# "declare lost": offers stop at (timeout - grace) so a slow-heartbeat
# executor drains its in-flight tasks instead of receiving doomed ones,
# and the reaper expires it at the full timeout — no window where an
# executor is permanently unschedulable yet never declared lost (the old
# split 60s alive / 180s expired defaults had a 120s such window).  The
# grace is capped at half the timeout so short test timeouts keep a
# usable alive window.  Config key: ballista.cluster.executor_timeout_s.
OFFER_DRAIN_GRACE_S = 60.0


def alive_cutoff_s(timeout_s: float) -> float:
    """Heartbeat age beyond which an executor stops receiving offers."""
    return timeout_s - min(OFFER_DRAIN_GRACE_S, timeout_s / 2.0)


class ClusterState:
    """Executor slots + metadata + heartbeats."""

    def __init__(self, task_distribution: str = "bias"):
        assert task_distribution in ("bias", "round-robin")
        self.task_distribution = task_distribution
        self._lock = threading.Lock()
        self._executors: Dict[str, ExecutorMetadata] = {}
        self._heartbeats: Dict[str, ExecutorHeartbeat] = {}
        self._available: Dict[str, int] = {}  # free task slots
        self._rr_cursor = 0

    # --- registration ----------------------------------------------------
    def register_executor(self, meta: ExecutorMetadata) -> None:
        with self._lock:
            fresh = meta.executor_id not in self._executors
            self._executors[meta.executor_id] = meta
            if fresh:
                self._available[meta.executor_id] = meta.task_slots
            self._heartbeats[meta.executor_id] = ExecutorHeartbeat(meta.executor_id)

    def remove_executor(self, executor_id: str) -> None:
        with self._lock:
            self._executors.pop(executor_id, None)
            self._available.pop(executor_id, None)
            hb = self._heartbeats.get(executor_id)
            if hb is not None:
                hb.status = "dead"

    def save_heartbeat(self, hb: ExecutorHeartbeat) -> None:
        with self._lock:
            self._heartbeats[hb.executor_id] = hb

    def touch_heartbeat(self, executor_id: str) -> None:
        """Refresh the timestamp WITHOUT clobbering the status — poll_work
        arrivals must not flip a terminating executor back to active."""
        import time as _time

        with self._lock:
            hb = self._heartbeats.get(executor_id)
            if hb is not None:
                hb.timestamp = _time.time()
            else:
                self._heartbeats[executor_id] = ExecutorHeartbeat(executor_id)

    def executors(self) -> List[ExecutorMetadata]:
        with self._lock:
            return list(self._executors.values())

    def get_executor(self, executor_id: str) -> Optional[ExecutorMetadata]:
        with self._lock:
            return self._executors.get(executor_id)

    def alive_executors(self, timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S
                        ) -> List[str]:
        """Executors eligible for NEW work: active status and a heartbeat
        younger than ``alive_cutoff_s(timeout_s)`` — the same timeout the
        reaper uses, minus the drain grace (see OFFER_DRAIN_GRACE_S)."""
        cutoff = alive_cutoff_s(timeout_s)
        now = time.time()
        with self._lock:
            return [eid for eid, hb in self._heartbeats.items()
                    if hb.status == "active" and now - hb.timestamp <= cutoff
                    and eid in self._executors]

    def memory_pressure(self, executor_id: str) -> float:
        """Last heartbeated memory-governor pressure (0.0 for unknown or
        unbudgeted executors)."""
        with self._lock:
            hb = self._heartbeats.get(executor_id)
            return hb.memory_pressure if hb is not None else 0.0

    def min_alive_pressure(self, timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S
                           ) -> float:
        """The LEAST-pressured alive executor's memory pressure — the
        admission signal: while any executor has headroom new work can
        land somewhere, so only the fleet-wide floor crossing the shed
        threshold means the cluster's memory is saturated.  0.0 when no
        executor is alive (an empty cluster queues on slots, not memory)."""
        alive = self.alive_executors(timeout_s)
        if not alive:
            return 0.0
        with self._lock:
            return min(self._pressure_locked(eid) for eid in alive)

    def _pressure_locked(self, executor_id: str) -> float:
        hb = self._heartbeats.get(executor_id)
        return hb.memory_pressure if hb is not None else 0.0

    def expired_executors(self, timeout_s: float = DEFAULT_EXECUTOR_TIMEOUT_S
                          ) -> List[str]:
        """'terminating' executors are NOT expired while they still
        heartbeat: they get the drain grace period (reference honors
        Terminating with a termination grace, executor_manager.rs /
        executor_process.rs:309-320) — only 'dead' status or heartbeat
        timeout expires an executor."""
        now = time.time()
        with self._lock:
            return [eid for eid in self._executors
                    if (hb := self._heartbeats.get(eid)) is not None
                    and (hb.status == "dead" or now - hb.timestamp > timeout_s)]

    # --- slots -----------------------------------------------------------
    def reserve_slots(self, n: int, executors: Optional[List[str]] = None
                      ) -> List[ExecutorReservation]:
        """Atomically grab up to ``n`` free slots (reference
        cluster/mod.rs:265-304)."""
        with self._lock:
            pool = executors if executors is not None else list(self._available)
            pool = [e for e in pool if e in self._available]
            out: List[ExecutorReservation] = []
            if self.task_distribution == "bias":
                # pack: drain one executor before touching the next.
                # Memory pressure (heartbeated, bucketed to dampen jitter)
                # degrades the ordering: a near-OOM executor is offered
                # work only after every calmer one is full
                for eid in sorted(pool, key=lambda e: (
                        round(self._pressure_locked(e), 1),
                        -self._available[e])):
                    take = min(n - len(out), self._available[eid])
                    self._available[eid] -= take
                    out.extend(ExecutorReservation(eid) for _ in range(take))
                    if len(out) >= n:
                        break
            else:
                # round-robin: one slot per executor per cycle; pressured
                # executors cycle last so partial rounds favor calm hosts
                pool = sorted(pool, key=lambda e: (
                    round(self._pressure_locked(e), 1), e))
                while len(out) < n and pool:
                    progressed = False
                    for i in range(len(pool)):
                        eid = pool[(self._rr_cursor + i) % len(pool)]
                        if self._available[eid] > 0:
                            self._available[eid] -= 1
                            out.append(ExecutorReservation(eid))
                            progressed = True
                            if len(out) >= n:
                                self._rr_cursor = (self._rr_cursor + i + 1) % len(pool)
                                break
                    if not progressed:
                        break
            return out

    def cancel_reservations(self, reservations: List[ExecutorReservation]) -> None:
        with self._lock:
            for r in reservations:
                if r.executor_id in self._available:
                    self._available[r.executor_id] += 1

    def free_slots(self, executor_id: str, n: int = 1) -> None:
        with self._lock:
            if executor_id in self._available:
                cap = self._executors[executor_id].task_slots
                self._available[executor_id] = min(
                    cap, self._available[executor_id] + n)

    def total_available(self) -> int:
        with self._lock:
            return sum(self._available.values())

    def total_slots(self) -> int:
        """Registered capacity (free + occupied) — the denominator for
        per-tenant slot shares (admission control)."""
        with self._lock:
            return sum(m.task_slots for m in self._executors.values())


class JobState:
    """Job registry + graph store + completion signalling (parity:
    reference JobState trait, cluster/mod.rs:306-372)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._status: Dict[str, JobStatus] = {}
        self._graphs: Dict[str, ExecutionGraph] = {}
        self._events: List[Callable[[JobStatus], None]] = []
        self._done: Dict[str, threading.Event] = {}

    def accept_job(self, job_id: str) -> None:
        with self._lock:
            self._status[job_id] = JobStatus(job_id, "queued")
            self._done[job_id] = threading.Event()

    def submit_job(self, job_id: str, graph: ExecutionGraph) -> None:
        with self._lock:
            self._graphs[job_id] = graph
            self._status[job_id] = JobStatus(job_id, "running")

    def job_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._graphs)

    def get_graph(self, job_id: str) -> Optional[ExecutionGraph]:
        with self._lock:
            return self._graphs.get(job_id)

    def active_graphs(self) -> List[ExecutionGraph]:
        with self._lock:
            return [g for g in self._graphs.values() if g.status == "running"]

    def get_status(self, job_id: str) -> Optional[JobStatus]:
        with self._lock:
            return self._status.get(job_id)

    def set_status(self, status: JobStatus) -> None:
        with self._lock:
            self._status[status.job_id] = status
            done = self._done.get(status.job_id)
        if status.state in ("successful", "failed", "cancelled"):
            if done is not None:
                done.set()
        for cb in list(self._events):
            cb(status)

    def subscribe(self, cb: Callable[[JobStatus], None]) -> None:
        self._events.append(cb)

    def wait_for_completion(self, job_id: str, timeout: float = 300.0
                            ) -> JobStatus:
        with self._lock:
            done = self._done.get(job_id)
        if done is None:
            raise KeyError(job_id)
        done.wait(timeout)
        status = self.get_status(job_id)
        assert status is not None
        return status

    def remove_job(self, job_id: str) -> None:
        with self._lock:
            self._status.pop(job_id, None)
            self._graphs.pop(job_id, None)
            self._done.pop(job_id, None)
