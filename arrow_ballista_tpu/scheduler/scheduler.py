"""SchedulerServer: the event-driven scheduler state machine.

Parity with the reference scheduler
(reference ballista/scheduler/src/scheduler_server/):
- event set mirrors QueryStageSchedulerEvent (event.rs:14-57):
  JobQueued -> (async planning) -> JobSubmitted | JobPlanningFailed,
  ReservationOffering, TaskUpdating, ExecutorLost, JobCancel, JobFinished;
- all state transitions run on one EventLoop (query_stage_scheduler.rs);
- push scheduling via slot reservations: free slots are reserved
  atomically, filled with tasks from active jobs, and launched through the
  ``TaskLauncher`` seam (state/task_manager.rs:59-119) — the seam is what
  lets tests fabricate a whole cluster in-process (SURVEY.md §4);
- a reaper thread expires dead executors (scheduler_server/mod.rs:224-305).
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import random
import string
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from .. import faults
from ..admission import AdmissionController, AdmissionRequest
from ..analysis.plan_checks import validate_graph
from ..compile.fuse import CompilePolicy, fuse_resolved_stages
from ..obs import journal
from ..utils.config import ANALYSIS_PLAN_CHECKS
from .aqe import AqePolicy
from .cluster import ClusterState, JobState
from .event_loop import EventLoop
from .execution_graph import ExecutionGraph
from .quarantine import ExecutorQuarantine
from .speculation import SpeculationPolicy, find_candidates
from .types import (
    DEADLINE_EXCEEDED,
    FETCH_PARTITION_ERROR,
    POISON_QUERY,
    RESOURCE_EXHAUSTED,
    ExecutorHeartbeat,
    ExecutorMetadata,
    ExecutorReservation,
    JobStatus,
    TaskDescription,
    TaskId,
    TaskStatus,
)

log = logging.getLogger(__name__)


def random_job_id() -> str:
    """7-char alphanumeric job ids (reference task_manager.rs generates the
    same shape)."""
    return "".join(random.choices(string.ascii_lowercase + string.digits, k=7))


class TaskLauncher:
    """Launch seam (reference TaskLauncher trait, task_manager.rs:59-67)."""

    def launch_tasks(self, executor_id: str, tasks: List[TaskDescription]) -> None:
        raise NotImplementedError

    def cancel_tasks(self, executor_id: str, job_id: str) -> None:
        """Best-effort cancellation of a job's running tasks."""

    def cancel_task(self, executor_id: str, task: TaskId) -> None:
        """Best-effort cancellation of ONE running attempt — used to reap
        the losing duplicate once a speculative race has a winner."""

    def clean_job_data(self, executor_id: str, job_id: str) -> None:
        """Best-effort removal of a finished job's shuffle data on one
        executor (reference ExecutorGrpc.remove_job_data fanout,
        executor_manager.rs:231-253)."""

    def stop(self) -> None:
        pass


# --- events (reference scheduler_server/event.rs) -------------------------
@dataclasses.dataclass
class JobQueued:
    job_id: str
    plan_fn: Callable[[], Tuple[object, Dict[str, object]]]
    # plan_fn() -> (root physical plan, scalar values) — planning runs inside
    # the event loop worker, failures become JobPlanningFailed


@dataclasses.dataclass
class JobPlanned:
    job_id: str
    graph: Optional[ExecutionGraph]
    error: str = ""


@dataclasses.dataclass
class TaskUpdating:
    executor_id: str
    # None = drain the executor's status inbox (coalesced intake: many
    # update_task_status calls fold into one event); a non-None list is
    # processed verbatim (direct posts from tests/chaos harnesses)
    statuses: Optional[List[TaskStatus]]


@dataclasses.dataclass
class ExecutorLost:
    executor_id: str
    reason: str = ""


@dataclasses.dataclass
class JobCancel:
    job_id: str


@dataclasses.dataclass
class JobDeadline:
    """Posted by the deadline scan thread when a job's wall clock expired;
    the handler re-checks on the event loop (scan and completion race) and
    fails the job with the DeadlineExceeded terminal status."""

    job_id: str


@dataclasses.dataclass
class Offer:
    """Try to hand out tasks (reference ReservationOffering)."""


@dataclasses.dataclass
class SpeculationTick:
    """Periodic straggler scan: posted by the speculation monitor thread so
    all graph reads/mutations stay on the event loop (the thread itself
    never touches a graph)."""


@dataclasses.dataclass
class PollWork:
    """Pull-mode work request (reference SchedulerGrpc.poll_work,
    grpc.rs:57-136): absorb statuses, then fill the executor's free slots.
    The reply travels back through ``reply`` (filled on the event loop)."""

    executor_id: str
    num_free_slots: int
    statuses: List[TaskStatus]
    reply: "queue.Queue"


class SchedulerConfig:
    def __init__(self, task_distribution: str = "bias",
                 executor_timeout_s: Optional[float] = None,
                 reaper_interval_s: float = 15.0,
                 event_buffer_size: int = 10000,
                 policy: str = "push",
                 job_data_cleanup_delay_s: float = 30.0,
                 quarantine_failures: Optional[int] = None,
                 quarantine_probation_s: Optional[float] = None,
                 speculation_enabled: Optional[bool] = None,
                 speculation_quantile: Optional[float] = None,
                 speculation_multiplier: Optional[float] = None,
                 speculation_min_runtime_s: Optional[float] = None,
                 speculation_max_concurrent: Optional[int] = None,
                 speculation_interval_s: Optional[float] = None,
                 stats_history_capacity: Optional[int] = None,
                 stats_history_interval_s: Optional[float] = None,
                 fleet_lease_ttl_s: Optional[float] = None,
                 fleet_lease_renew_s: Optional[float] = None,
                 fleet_adopt_interval_s: Optional[float] = None,
                 fleet_registry_stale_s: Optional[float] = None,
                 live_enabled: Optional[bool] = None,
                 live_doctor_interval_s: Optional[float] = None,
                 slo_p99_target_ms: Optional[float] = None,
                 slo_window_s: Optional[float] = None,
                 memory_shed_threshold: Optional[float] = None,
                 query_deadline_s: Optional[float] = None,
                 poison_distinct_executors: Optional[int] = None,
                 deadline_scan_interval_s: float = 1.0):
        from ..utils.config import (BallistaConfig,
                                    CLUSTER_EXECUTOR_TIMEOUT_S,
                                    FLEET_ADOPT_INTERVAL_S,
                                    FLEET_LEASE_RENEW_S,
                                    FLEET_LEASE_TTL_S,
                                    FLEET_REGISTRY_STALE_S,
                                    LIVE_DOCTOR_INTERVAL_S,
                                    LIVE_ENABLED,
                                    MEM_PRESSURE_SHED,
                                    POISON_DISTINCT_EXECUTORS,
                                    QUARANTINE_FAILURES,
                                    QUARANTINE_PROBATION_S,
                                    QUERY_DEADLINE_S,
                                    SLO_P99_TARGET_MS,
                                    SLO_WINDOW_S,
                                    SPECULATION_ENABLED,
                                    SPECULATION_INTERVAL_S,
                                    SPECULATION_MAX_CONCURRENT,
                                    SPECULATION_MIN_RUNTIME_S,
                                    SPECULATION_MULTIPLIER,
                                    SPECULATION_QUANTILE,
                                    STATS_HISTORY_CAPACITY,
                                    STATS_HISTORY_INTERVAL_S)

        assert policy in ("push", "pull")  # reference TaskSchedulingPolicy
        defaults = BallistaConfig()
        self.task_distribution = task_distribution
        # one key drives both "stop offering" (minus the drain grace, see
        # cluster.alive_cutoff_s) and "declare lost" (the reaper):
        # ballista.cluster.executor_timeout_s
        self.executor_timeout_s = float(
            executor_timeout_s if executor_timeout_s is not None
            else defaults.get(CLUSTER_EXECUTOR_TIMEOUT_S))
        self.quarantine_failures = int(
            quarantine_failures if quarantine_failures is not None
            else defaults.get(QUARANTINE_FAILURES))
        self.quarantine_probation_s = float(
            quarantine_probation_s if quarantine_probation_s is not None
            else defaults.get(QUARANTINE_PROBATION_S))
        # straggler mitigation (scheduler/speculation.py): knobs default
        # from the ballista.speculation.* config-registry entries
        self.speculation = SpeculationPolicy(
            enabled=bool(speculation_enabled
                         if speculation_enabled is not None
                         else defaults.get(SPECULATION_ENABLED)),
            quantile=float(speculation_quantile
                           if speculation_quantile is not None
                           else defaults.get(SPECULATION_QUANTILE)),
            multiplier=float(speculation_multiplier
                             if speculation_multiplier is not None
                             else defaults.get(SPECULATION_MULTIPLIER)),
            min_runtime_s=float(speculation_min_runtime_s
                                if speculation_min_runtime_s is not None
                                else defaults.get(SPECULATION_MIN_RUNTIME_S)),
            max_concurrent=int(speculation_max_concurrent
                               if speculation_max_concurrent is not None
                               else defaults.get(SPECULATION_MAX_CONCURRENT)),
            interval_s=float(speculation_interval_s
                             if speculation_interval_s is not None
                             else defaults.get(SPECULATION_INTERVAL_S)))
        # cluster time-series sampler (obs/stats.py ClusterHistory): knobs
        # default from the ballista.stats.* config-registry entries
        self.stats_history_capacity = int(
            stats_history_capacity if stats_history_capacity is not None
            else defaults.get(STATS_HISTORY_CAPACITY))
        self.stats_history_interval_s = float(
            stats_history_interval_s if stats_history_interval_s is not None
            else defaults.get(STATS_HISTORY_INTERVAL_S))
        self.reaper_interval_s = reaper_interval_s
        self.event_buffer_size = event_buffer_size
        self.policy = policy
        # delay before the remove_job_data fanout for a finished job: long
        # enough for the client to fetch final-stage partitions, short
        # enough that shuffle files don't pile up (reference delayed
        # clean_up_job_data, executor_manager.rs:231-253).  <0 disables;
        # in daemon deployments the executor TTL janitor remains as
        # backstop, in standalone mode the work dir dies with the cluster
        # (StandaloneCluster.shutdown).
        self.job_data_cleanup_delay_s = job_data_cleanup_delay_s
        # scheduler fleet HA (ballista.fleet.*): job-ownership lease TTL,
        # renewal cadence (0 = ttl/3), expired-lease adoption scan interval
        # and shard-registry freshness (client failover + /api/autoscale)
        self.fleet_lease_ttl_s = float(
            fleet_lease_ttl_s if fleet_lease_ttl_s is not None
            else defaults.get(FLEET_LEASE_TTL_S))
        self.fleet_lease_renew_s = float(
            fleet_lease_renew_s if fleet_lease_renew_s is not None
            else defaults.get(FLEET_LEASE_RENEW_S))
        self.fleet_adopt_interval_s = float(
            fleet_adopt_interval_s if fleet_adopt_interval_s is not None
            else defaults.get(FLEET_ADOPT_INTERVAL_S))
        self.fleet_registry_stale_s = float(
            fleet_registry_stale_s if fleet_registry_stale_s is not None
            else defaults.get(FLEET_REGISTRY_STALE_S))
        # live observability plane (ballista.live.* / ballista.slo.*): the
        # in-flight doctor cadence and the latency-SLO objective
        self.live_enabled = bool(
            live_enabled if live_enabled is not None
            else defaults.get(LIVE_ENABLED))
        self.live_doctor_interval_s = float(
            live_doctor_interval_s if live_doctor_interval_s is not None
            else defaults.get(LIVE_DOCTOR_INTERVAL_S))
        self.slo_p99_target_ms = float(
            slo_p99_target_ms if slo_p99_target_ms is not None
            else defaults.get(SLO_P99_TARGET_MS))
        self.slo_window_s = float(
            slo_window_s if slo_window_s is not None
            else defaults.get(SLO_WINDOW_S))
        # memory backpressure (ballista.memory.pressure.shed.threshold):
        # when every alive executor heartbeats governor pressure at or
        # above this, admission queues/sheds new jobs with a retriable
        # ResourceExhausted instead of piling work onto a fleet about
        # to spill or OOM.  <= 0 disables the admission feed.
        self.memory_shed_threshold = float(
            memory_shed_threshold if memory_shed_threshold is not None
            else defaults.get(MEM_PRESSURE_SHED))
        # query lifecycle guardrails (ballista.query.* / ballista.poison.*):
        # scheduler-wide deadline default (a job's session/per-submit config
        # overrides it), the distinct-executor threshold for poison
        # classification, and the deadline scan cadence
        self.query_deadline_s = float(
            query_deadline_s if query_deadline_s is not None
            else defaults.get(QUERY_DEADLINE_S))
        self.poison_distinct_executors = int(
            poison_distinct_executors if poison_distinct_executors is not None
            else defaults.get(POISON_DISTINCT_EXECUTORS))
        self.deadline_scan_interval_s = float(deadline_scan_interval_s)


class SchedulerServer:
    def __init__(self, launcher: TaskLauncher,
                 config: Optional[SchedulerConfig] = None,
                 metrics: Optional["SchedulerMetricsCollector"] = None,
                 job_backend=None, scheduler_id: Optional[str] = None,
                 cluster_state=None, observability=None):
        import uuid

        from ..obs import ClusterHistory, JobObservability
        from .metrics import InMemoryMetricsCollector

        self.config = config or SchedulerConfig()
        # pluggable: in-memory (single scheduler) or KV-backed (N schedulers
        # sharing one cluster, scheduler/kv.py KvClusterState)
        self.cluster = cluster_state or ClusterState(self.config.task_distribution)
        self.jobs = JobState()
        self.launcher = launcher
        self.metrics = metrics if metrics is not None else InMemoryMetricsCollector()
        # tracing + profile retention (arrow_ballista_tpu/obs/): phase
        # spans per job, task span intake, /api/job/<id>/profile|trace
        self.obs = observability if observability is not None \
            else JobObservability()
        # optional persistence: checkpoint graphs on every transition so a
        # restarted/sibling scheduler can adopt them (reference JobState
        # backends + try_acquire_job)
        self.job_backend = job_backend
        self.scheduler_id = scheduler_id or f"scheduler-{uuid.uuid4().hex[:8]}"
        # flight recorder (obs/journal.py): enable-only switch — a shard
        # never force-disables a journal a test/session already turned on
        # (standalone runs share one process-global journal across the
        # scheduler and its in-proc executors)
        from ..utils.config import (BallistaConfig, JOURNAL_CAPACITY,
                                    JOURNAL_ENABLED, JOURNAL_SPILL_PATH,
                                    env_flag)
        _defaults = BallistaConfig()
        if env_flag("BALLISTA_JOURNAL") or bool(_defaults.get(JOURNAL_ENABLED)):
            journal.set_enabled(True)
        if journal.enabled():
            journal.configure(
                capacity=int(_defaults.get(JOURNAL_CAPACITY)),
                spill_path=str(_defaults.get(JOURNAL_SPILL_PATH)))
            if not journal.actor():
                # first process identity wins (in-proc fleets share one
                # journal; lease events carry scheduler_id explicitly)
                journal.set_actor(self.scheduler_id)
        # delta base for sync_journal_metrics (journal counters are
        # process-global; this collector folds only deltas it hasn't seen)
        self._journal_last = (0, 0)  # ballista: guarded-by=none
        # fleet HA: lease-capable backends (KvJobStateBackend) get epoch-
        # fenced TTL ownership; file/legacy backends keep the PR-4 lock path
        self._lease_capable = job_backend is not None \
            and hasattr(job_backend, "acquire_lease")
        # "host:port" this shard serves clients on, published in the lease
        # and the shard registry for client failover; set by the net
        # service once its RPC port is known, before init()
        self.client_endpoint = ""  # ballista: guarded-by=none
        # _lease_lock guards _leases (job_id -> held lease epoch): written
        # by event-loop handlers (checkpoint/terminal release), the lease-
        # renewal thread and the adoption scanner
        self._lease_lock = threading.Lock()
        self._leases: Dict[str, int] = {}
        # _meta_lock guards the per-job bookkeeping dicts below
        # (_queued_at_ms, _job_configs, _serving_info): they are touched
        # from submit threads, admission callbacks (sweeper thread), event
        # -loop handlers and planning closures.  Scope is always one dict
        # op — never held across a call that takes another lock
        self._meta_lock = threading.Lock()
        self._queued_at_ms: Dict[str, int] = {}
        # job_id -> submitting session's BallistaConfig (popped at planning
        # or terminal shed/cancel; entries are only written before JobQueued)
        self._job_configs: Dict[str, object] = {}
        # serving caches (scheduler/serving_cache.py): plan templates +
        # result/subplan entries, shared by every session; per-session
        # enable knobs are honoured at submit by the serving entry points
        from ..utils.config import BallistaConfig
        from .serving_cache import caches_from_config

        self.plan_cache, self.result_cache = caches_from_config(
            BallistaConfig(), metrics=self.metrics)
        # job_id -> ServingJobInfo for SQL jobs on the serving path (popped
        # at capture on success, or by the terminal-status backstop)
        self._serving_info: Dict[str, object] = {}
        # status-report coalescing: executors append under the lock; the
        # event loop drains an executor's whole inbox in ONE TaskUpdating,
        # so a flood of single-status reports costs one event, not N
        self._status_lock = threading.Lock()
        self._status_inbox: Dict[str, List[TaskStatus]] = {}
        self._event_loop = EventLoop("scheduler-events", self._on_event,
                                     self.config.event_buffer_size,
                                     on_error=self._on_event_error)
        self._launch_pool = ThreadPoolExecutor(max_workers=8,
                                               thread_name_prefix="launch")
        # loop threads: written once by init() before any concurrency on
        # them, read only by shutdown() (init happens-before shutdown)
        self._reaper: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._spec_monitor: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._history_sampler: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._lease_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._adopt_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._live_doctor_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._deadline_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        # live observability plane: in-flight doctor state machine (scan
        # thread starts in init() only when ballista.live.enabled) and the
        # latency-SLO tracker (null object when no target is configured)
        from ..obs.live import LiveDoctor
        from ..obs.slo import NullSloTracker, SloPolicy, SloTracker

        self.live_doctor = LiveDoctor()
        if self.config.slo_p99_target_ms > 0:
            self.slo = SloTracker(SloPolicy(self.config.slo_p99_target_ms,
                                            self.config.slo_window_s))
        else:
            self.slo = NullSloTracker()
        # cluster time series behind GET /api/cluster/history: periodic
        # utilization / queue-depth / event-loop-lag samples in a bounded
        # ring buffer (obs/stats.py)
        self.history = ClusterHistory(self.config.stats_history_capacity,
                                      self.config.stats_history_interval_s)
        self._stopped = threading.Event()
        self._cleanup_timers: Dict[str, threading.Timer] = {}
        self._cleanup_lock = threading.Lock()
        # quarantine: executors racking up consecutive retryable failures
        # stop receiving offers until probation re-admits them
        self.quarantine = ExecutorQuarantine(
            threshold=self.config.quarantine_failures,
            probation_s=self.config.quarantine_probation_s)
        # poison-query containment: (job, stage, partition) -> per-executor
        # failure signatures, plus jobs whose classification completed this
        # intake round.  Event-loop-only state (written by
        # _record_quarantine_signals, drained by _absorb_statuses)
        self._poison_evidence: Dict[Tuple[str, int, int], Dict[str, str]] = {}
        self._poison_suspects: set = set()
        # admission gate between submit_job and JobQueued planning; with no
        # ballista.admission.* limits configured this is pass-through
        self.admission = AdmissionController(
            admit_cb=self._admission_admit,
            fail_cb=self._admission_reject,
            pending_tasks_fn=self.pending_task_count,
            total_slots_fn=self.cluster.total_slots,
            memory_pressure_fn=self._fleet_memory_pressure,
            memory_shed_threshold=self.config.memory_shed_threshold,
            metrics=self.metrics)
        # terminal transitions release the tenant's concurrency reservation
        # and pull the next admissible job out of the wait queue
        self.jobs.subscribe(self._on_job_terminal)

    # --- lifecycle -------------------------------------------------------
    def init(self, start_reaper: bool = True) -> None:
        self._event_loop.start()
        if start_reaper:
            self._reaper = threading.Thread(target=self._reap_loop,
                                            name="executor-reaper", daemon=True)
            self._reaper.start()
            if self.config.deadline_scan_interval_s > 0:
                # finer-grained than the executor reaper: a deadline must
                # land within seconds of expiry, not a reaper interval
                self._deadline_thread = threading.Thread(
                    target=self._deadline_loop, name="deadline-reaper",
                    daemon=True)
                self._deadline_thread.start()
        if self.config.speculation.enabled:
            self._spec_monitor = threading.Thread(
                target=self._speculation_loop, name="speculation-monitor",
                daemon=True)
            self._spec_monitor.start()
        self._history_sampler = threading.Thread(
            target=self._history_loop, name="cluster-history-sampler",
            daemon=True)
        self._history_sampler.start()
        if start_reaper and self._lease_capable:
            self._lease_thread = threading.Thread(
                target=self._lease_loop, name="lease-renewal", daemon=True)
            self._lease_thread.start()
            if self.config.fleet_adopt_interval_s > 0:
                self._adopt_thread = threading.Thread(
                    target=self._adopt_loop, name="lease-adoption",
                    daemon=True)
                self._adopt_thread.start()
        if self.config.live_enabled \
                and self.config.live_doctor_interval_s > 0:
            self._live_doctor_thread = threading.Thread(
                target=self._live_doctor_loop, name="live-doctor",
                daemon=True)
            self._live_doctor_thread.start()

    def shutdown(self, withdraw: bool = True) -> None:
        # withdraw=False is the chaos harness's crash-simulation: skip the
        # registry goodbye so the shard vanishes exactly like kill -9
        # (its entry ages out of scheduler_registry at the stale cutoff)
        # order matters: stop the event loop BEFORE closing the launch pool,
        # so no event handler can race a _launch_pool.submit against
        # pool.shutdown (round-2 bench crash: "cannot schedule new futures
        # after shutdown" killed the event loop mid-run)
        self._stopped.set()
        self.admission.stop()
        # bounded joins: every loop waits on _stopped (already set), so
        # each returns within one in-flight iteration; the timeout keeps a
        # wedged iteration from hanging shutdown (daemons regardless)
        if self._reaper is not None:
            self._reaper.join(timeout=5.0)
        if self._spec_monitor is not None:
            self._spec_monitor.join(timeout=5.0)
        if self._history_sampler is not None:
            self._history_sampler.join(timeout=5.0)
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=5.0)
        if self._adopt_thread is not None:
            self._adopt_thread.join(timeout=5.0)
        if self._live_doctor_thread is not None:
            self._live_doctor_thread.join(timeout=5.0)
        if self._deadline_thread is not None:
            self._deadline_thread.join(timeout=5.0)
        # clean shutdown deliberately does NOT release job leases: a
        # shard stopping mid-job should look exactly like a crash so a
        # sibling adopts its jobs after one TTL.  Only the registry entry
        # (client routing hint) is withdrawn.
        if self._lease_capable and withdraw:
            store = getattr(self.job_backend, "store", None)
            if store is not None:
                try:
                    from .kv import remove_scheduler
                    remove_scheduler(store, self.scheduler_id)
                except Exception:  # noqa: BLE001 — KV may already be gone
                    log.info("shard registry withdrawal failed",
                             exc_info=True)
        with self._cleanup_lock:
            timers = list(self._cleanup_timers.values())
            self._cleanup_timers.clear()
        for t in timers:
            t.cancel()
        self._event_loop.stop()
        self._launch_pool.shutdown(wait=False)
        self.launcher.stop()
        self.result_cache.close()

    def _submit_work(self, fn, *args) -> None:
        """Submit to the launch pool, tolerating shutdown races."""
        if self._stopped.is_set():
            return
        try:
            self._launch_pool.submit(fn, *args)
        except RuntimeError:  # pool closed between the check and the submit
            log.info("dropping work submitted during shutdown")

    # --- public API (the SchedulerGrpc surface, ballista.proto:665-689) --
    def register_executor(self, meta: ExecutorMetadata) -> None:
        self.cluster.register_executor(meta)
        self._event_loop.post(Offer())

    def heartbeat(self, hb: ExecutorHeartbeat) -> None:
        known = self.cluster.get_executor(hb.executor_id) is not None
        self.cluster.save_heartbeat(hb)
        if not known:
            if hb.metadata is not None:
                # auto re-register: heals push-mode executors after a
                # scheduler restart (reference grpc.rs:174-241)
                log.info("re-registering unknown heartbeater %s", hb.executor_id)
                self.register_executor(hb.metadata)
                # registration installs a fresh 'active' heartbeat; re-apply
                # the REPORTED status so a terminating executor stays
                # unschedulable through its re-registration
                self.cluster.save_heartbeat(hb)
            else:
                log.info("heartbeat from unknown executor %s", hb.executor_id)
        if hb.running:
            # zombie-task reconciliation: the executor's in-flight set is
            # ground truth for "still burning cycles"; diff it against the
            # scheduler's job states and re-issue kills for tasks whose job
            # is terminal or unknown (closes the lost-cancel-RPC leak —
            # NetTaskLauncher.cancel_tasks logs and swallows delivery
            # failures, so without this a dropped fanout leaks the task
            # until it finishes on its own)
            self._reconcile_running(hb.executor_id, hb.running)

    def _reconcile_running(self, executor_id: str,
                           running: List[tuple]) -> None:
        by_job: Dict[str, int] = {}
        for entry in running:
            job_id = entry[0]
            by_job[job_id] = by_job.get(job_id, 0) + 1
        reaped = 0
        for job_id, count in sorted(by_job.items()):
            if not self._job_is_zombie(job_id):
                continue
            reaped += count
            log.warning("reaping %d zombie task(s) of job %s on %s",
                        count, job_id, executor_id)
            if journal.enabled():
                journal.emit_job("zombie.reaped", job_id,
                                 executor_id=executor_id, tasks=str(count))
            self._submit_work(self.launcher.cancel_tasks, executor_id, job_id)
        if reaped:
            self.metrics.record_zombies_reaped(reaped)

    def _job_is_zombie(self, job_id: str) -> bool:
        """A running task is a zombie when its job can no longer use the
        result: the job is terminal here, or nobody in the fleet knows it."""
        st = self.jobs.get_status(job_id)
        if st is not None:
            return st.state in ("successful", "failed", "cancelled")
        # unknown locally: in a fleet another shard may own the job, so
        # consult the shared backend before declaring it dead
        if self.job_backend is not None:
            try:
                obj = self.job_backend.load_job(job_id)
            except Exception:  # noqa: BLE001 — backend hiccup
                log.warning("zombie check: job backend load failed for %s"
                            " — sparing the task", job_id, exc_info=True)
                return False  # don't kill on bad data
            if obj is not None:
                return False  # some shard still tracks it
        return True

    def executor_stopped(self, executor_id: str, reason: str = "") -> None:
        self._event_loop.post(ExecutorLost(executor_id, reason))

    def submit_job(self, job_id: str,
                   plan_fn: Callable[[], Tuple[object, Dict[str, object]]],
                   admission: Optional[AdmissionRequest] = None,
                   trace: Optional[Dict[str, str]] = None,
                   config: Optional[object] = None,
                   serving: Optional[object] = None) -> None:
        """``config``: the submitting session's BallistaConfig — consulted
        at planning time for ``ballista.analysis.plan_checks`` (None = all
        defaults).  Stashed here because the admission queue only carries
        (job_id, plan_fn).  ``serving``: ServingJobInfo for SQL jobs going
        through the serving caches (scheduler/serving.py) — drives template
        storage, validation skipping, subplan preload and result capture."""
        self.jobs.accept_job(job_id)
        self.obs.on_submitted(job_id, trace)
        if journal.enabled():
            journal.emit_job("job.submitted", job_id)
        with self._meta_lock:
            if config is not None:
                self._job_configs[job_id] = config
            if serving is not None:
                self._serving_info[job_id] = serving
            self._queued_at_ms[job_id] = int(time.time() * 1000)
        self.admission.submit(job_id, plan_fn, admission)

    # --- admission callbacks (see arrow_ballista_tpu/admission/) ---------
    def _admission_admit(self, job_id: str, plan_fn: Callable) -> None:
        if self._stopped.is_set():
            return
        self.obs.on_admitted(job_id)
        if journal.enabled():
            journal.emit_job("job.admitted", job_id)
        self._event_loop.post(JobQueued(job_id, plan_fn))

    def _admission_reject(self, job_id: str, message: str) -> None:
        """Shed (queue full / queue timeout): a *retriable* failure — the
        client should back off and resubmit, not treat it as a query
        error."""
        with self._meta_lock:
            self._queued_at_ms.pop(job_id, None)
            self._job_configs.pop(job_id, None)
        if journal.enabled():
            journal.emit_job("job.shed", job_id, reason=message)
        self.jobs.set_status(JobStatus(job_id, "failed", error=message,
                                       retriable=True))
        self.metrics.record_failed(job_id)

    def _on_job_terminal(self, status: JobStatus) -> None:
        if status.state in ("successful", "failed", "cancelled"):
            self.admission.release(status.job_id)
            # fleet: completion releases the ownership lease (the terminal
            # checkpoint is already durable) so the lock never lingers as
            # an adoptable expired lease
            self._release_lease(status.job_id)
            # backstop: success pops this at capture time; failed/cancelled
            # (and crashed-handler) paths release the serving info here
            with self._meta_lock:
                self._serving_info.pop(status.job_id, None)
            # finalize the job's trace/profile off the retained graph —
            # one hook covers success, failure, cancel and admission shed
            try:
                self.obs.on_finished(status,
                                     self.jobs.get_graph(status.job_id))
            except Exception:  # noqa: BLE001 — observability is best-effort
                log.exception("profile finalization failed for %s",
                              status.job_id)

    def update_task_status(self, executor_id: str,
                           statuses: List[TaskStatus]) -> None:
        # coalesce: append to the executor's inbox, and post a drain event
        # only when the inbox was empty — N reports landing while one event
        # is in flight are absorbed together by that single event
        with self._status_lock:
            box = self._status_inbox.setdefault(executor_id, [])
            was_empty = not box
            box.extend(statuses)
        if was_empty:
            self._event_loop.post(TaskUpdating(executor_id, None))

    def cancel_job(self, job_id: str) -> None:
        self._event_loop.post(JobCancel(job_id))

    def get_job_status(self, job_id: str) -> Optional[JobStatus]:
        return self.jobs.get_status(job_id)

    def wait_for_job(self, job_id: str, timeout: float = 300.0) -> JobStatus:
        return self.jobs.wait_for_completion(job_id, timeout)

    def pending_task_count(self) -> int:
        return sum(g.available_task_count() for g in self.jobs.active_graphs())

    # --- event machine ---------------------------------------------------
    def _on_event_error(self, event: object, exc: BaseException) -> None:
        """A handler crash must not strand the affected job in 'running'
        forever — clients poll status, and without this they wait out the
        full job deadline on a job no handler will ever touch again."""
        job_ids = set()
        jid = getattr(event, "job_id", None)
        if jid:
            job_ids.add(jid)
        # TaskUpdating has no job_id field; its affected jobs ride in the
        # statuses' task ids.  A drain event (statuses=None) crashed before
        # emptying its inbox — pull the unprocessed reports out now, or the
        # jobs they belong to hang until the job deadline
        statuses = getattr(event, "statuses", None)
        if statuses is None and isinstance(event, TaskUpdating):
            with self._status_lock:
                statuses = self._status_inbox.pop(event.executor_id, [])
        for st in statuses or []:
            task = getattr(st, "task", None)
            if task is not None and getattr(task, "job_id", None):
                job_ids.add(task.job_id)
        for job_id in job_ids:
            st = self.jobs.get_status(job_id)
            if st is not None and st.state in ("successful", "failed",
                                               "cancelled"):
                continue
            # stop the graph too, or the scheduler keeps launching its
            # remaining tasks and a late 'job_successful' event would
            # overwrite the failed status the client already saw
            graph = self.jobs.get_graph(job_id)
            if graph is not None and graph.status == "running":
                graph.status = "failed"
            with self._meta_lock:
                self._queued_at_ms.pop(job_id, None)
            self.jobs.set_status(JobStatus(
                job_id, "failed",
                error=f"scheduler event handler crashed: "
                      f"{type(exc).__name__}: {exc}"))
            self.metrics.record_failed(job_id)

    def _on_event(self, event: object) -> None:
        # log <-> trace correlation: job-scoped events stamp their job id
        # onto every record the handler emits (utils/logsetup.ContextFilter)
        job_id = getattr(event, "job_id", "")
        if job_id:
            from ..utils.logsetup import log_scope

            with log_scope(job_id=job_id):
                self._dispatch_event(event)
        else:
            self._dispatch_event(event)

    def _dispatch_event(self, event: object) -> None:
        if isinstance(event, JobQueued):
            self._on_job_queued(event)
        elif isinstance(event, JobPlanned):
            self._on_job_planned(event)
        elif isinstance(event, TaskUpdating):
            self._on_task_updating(event)
        elif isinstance(event, ExecutorLost):
            self._on_executor_lost(event)
        elif isinstance(event, JobCancel):
            self._on_job_cancel(event)
        elif isinstance(event, JobDeadline):
            self._on_job_deadline(event)
        elif isinstance(event, Offer):
            self._offer()
        elif isinstance(event, SpeculationTick):
            self._on_speculation_tick()
        elif isinstance(event, PollWork):
            self._on_poll_work(event)
        else:
            log.warning("unknown scheduler event %r", event)

    def _on_job_queued(self, ev: JobQueued) -> None:
        # planning (incl. scalar subquery evaluation) can take seconds —
        # run it off the event loop so scheduling stays responsive
        # (reference spawns planning too, query_stage_scheduler.rs:106-148)
        def plan():
            try:
                with self._meta_lock:
                    cfg = self._job_configs.pop(ev.job_id, None)
                    serving = self._serving_info.get(ev.job_id)
                plan, scalars = ev.plan_fn()
                graph = ExecutionGraph.build(ev.job_id, plan)
                if serving is not None and serving.prevalidated:
                    # template hit: the plan validated at template creation
                    # and any scan-layout change would have invalidated the
                    # template (table-version fingerprint), so skip
                    pass
                elif cfg is None or cfg.get(ANALYSIS_PLAN_CHECKS):
                    # pre-launch sanity validation (analysis/plan_checks.py):
                    # reject broken stage wiring before any task runs
                    validate_graph(graph)
                if serving is not None and serving.pending_template is not None:
                    # only a plan whose graph built (and validated) above
                    # may become a reusable template
                    self.plan_cache.store(serving.pending_template)
                    serving.pending_template = None
                # runtime re-optimization knobs for this job's lifetime
                # (ballista.aqe.*, defaults apply when no session config)
                graph.aqe = AqePolicy.from_config(cfg)
                # whole-stage compiler (ballista.compile.*): the policy
                # arms revive()-time fusion for downstream stages; the
                # leaf stages that resolved during graph build are fused
                # here, after validation and before any task launches
                graph.compiler = CompilePolicy.from_config(cfg)
                fuse_resolved_stages(graph)
                graph.scalars = scalars
                graph.addr_resolver = self._resolve_addr
                # server-side deadline: a positive session/per-submit
                # ballista.query.deadline.seconds overrides the scheduler
                # default; the clock runs from SUBMISSION (queued time
                # counts), and the absolute expiry rides the checkpoint
                deadline_s = self.config.query_deadline_s
                if cfg is not None:
                    from ..utils.config import QUERY_DEADLINE_S

                    v = float(cfg.get(QUERY_DEADLINE_S))
                    if v > 0:
                        deadline_s = v
                if deadline_s > 0:
                    with self._meta_lock:
                        queued_at = self._queued_at_ms.get(ev.job_id, 0)
                    start = queued_at / 1000.0 if queued_at else time.time()
                    graph.deadline_s = deadline_s
                    graph.deadline_ts = start + deadline_s
                if serving is not None and serving.subplan:
                    self._preload_subplans(graph, serving)
                self._event_loop.post(JobPlanned(ev.job_id, graph))
            except Exception as e:  # noqa: BLE001 — planning failure fails the job
                log.exception("planning failed for job %s", ev.job_id)
                self._event_loop.post(JobPlanned(ev.job_id, None,
                                                 f"planning error: {e}"))

        self._submit_work(plan)

    def _preload_subplans(self, graph: ExecutionGraph, serving) -> None:
        """Fingerprint every non-final stage and complete those whose
        shuffle output is already cached (serving subplan cache).  Runs on
        the planning worker BEFORE the graph is published to the event
        loop, so graph access is single-threaded; cached bytes are spooled
        to scheduler-local files that port-0 locations point at."""
        from ..ops.shuffle import ShuffleWritePartition
        from .serving_cache import stage_fingerprint, subplan_cache_key

        for sid, stage in graph.stages.items():
            if not stage.output_links:
                continue  # final stage: the result cache's domain
            if stage.producer_ids:
                # only LEAF stages: a leaf's fingerprint fully determines
                # its computation, while a downstream stage's plan sees its
                # inputs only as UnresolvedShuffleExec stubs — two queries
                # with different upstream filters would fingerprint alike
                continue
            try:
                serving.stage_fps[sid] = stage_fingerprint(stage.plan)
            except Exception:  # noqa: BLE001 — unfingerprintable plan shape
                log.warning("stage fingerprint failed for job %s stage %d",
                            graph.job_id, sid, exc_info=True)
        # ascending stage ids are topological (the planner numbers stages
        # bottom-up), so producers complete before consumers resolve
        for sid in sorted(serving.stage_fps):
            key = subplan_cache_key(serving.stage_fps[sid],
                                    serving.config_fp, serving.table_fp)
            payload = self.result_cache.get(key)
            if payload is None:
                continue
            outputs = {}
            for map_part, _executor_id, rows in payload["outputs"]:
                writes = []
                for i, (out_part, num_rows, num_bytes, crc, data) in \
                        enumerate(rows):
                    path = self.result_cache.spool(
                        graph.job_id, sid, f"{map_part}-{i}.arrow", data)
                    writes.append(ShuffleWritePartition(
                        out_part, path, num_rows, num_bytes, crc))
                outputs[map_part] = ("subplan-cache", writes)
            if graph.preload_stage(sid, outputs):
                serving.preloaded.add(sid)

    def _capture_serving(self, graph: ExecutionGraph, locations,
                         serving) -> None:
        """Copy a successful job's result (and completed non-preloaded
        stage outputs) into the result cache.  Runs on a worker thread
        right after the terminal status — well inside the
        job-data-cleanup delay, after which the source files vanish."""
        from .serving_cache import (
            capture_result_payload,
            capture_stage_payload,
            subplan_cache_key,
        )

        try:
            if serving.capture_result and serving.result_key is not None \
                    and serving.schema is not None:
                cap = capture_result_payload(
                    locations, serving.schema,
                    self.result_cache.max_entry_bytes)
                if cap is not None:
                    self.result_cache.put(serving.result_key, cap[0], cap[1])
                    if serving.tables:
                        # key[1:4] = (norm_text, params, config_fp)
                        self.result_cache.remember_tables(
                            tuple(serving.result_key[1:4]), serving.tables)
            if serving.subplan:
                for sid, fp in serving.stage_fps.items():
                    if sid in serving.preloaded:
                        continue
                    stage = graph.stages.get(sid)
                    if stage is None or stage.state != "successful":
                        continue
                    cap = capture_stage_payload(
                        stage, self.result_cache.max_entry_bytes)
                    if cap is not None:
                        self.result_cache.put(
                            subplan_cache_key(fp, serving.config_fp,
                                              serving.table_fp),
                            cap[0], cap[1], kind="subplan")
        except Exception:  # noqa: BLE001 — capture is best-effort
            log.exception("serving-cache capture failed for job %s",
                          graph.job_id)

    def _on_job_planned(self, ev: JobPlanned) -> None:
        if ev.graph is None:
            if journal.enabled():
                journal.emit_job("job.plan_failed", ev.job_id, error=ev.error)
            self.jobs.set_status(JobStatus(ev.job_id, "failed", error=ev.error))
            self.metrics.record_failed(ev.job_id)
            with self._meta_lock:
                self._queued_at_ms.pop(ev.job_id, None)
            return
        self.obs.on_planned(ev.job_id)
        if journal.enabled():
            journal.emit_job("job.planned", ev.job_id,
                             stages=len(ev.graph.stages))
        # hand the execution span's context to every task of this job
        ev.graph.trace = self.obs.task_parent(ev.job_id)
        self.jobs.submit_job(ev.job_id, ev.graph)
        with self._meta_lock:
            queued_at = self._queued_at_ms.get(ev.job_id, 0)
        self.metrics.record_submitted(ev.job_id, queued_at,
                                      int(time.time() * 1000))
        self._checkpoint(ev.graph)
        self._offer()

    def _checkpoint(self, graph: ExecutionGraph) -> bool:
        """Persist the graph.  Returns False only when this shard lost the
        job's lease (another shard adopted it) — the caller must stop
        driving the job; plain persistence failures stay best-effort."""
        if self.job_backend is None:
            return True
        if journal.enabled():
            # the checkpoint carries the job's merged timeline, so the
            # flight record survives failover (the adopter seeds from it)
            graph.journal = journal.job_timeline(graph.job_id)
        if not self._lease_capable:
            try:
                self.job_backend.try_acquire_job(graph.job_id,
                                                 self.scheduler_id)
                self.job_backend.save_job(graph)
            except Exception:  # noqa: BLE001 — persistence is best-effort
                log.exception("job checkpoint failed for %s", graph.job_id)
            return True
        from .kv import LeaseLost

        try:
            epoch = self._acquire_job_lease(graph.job_id)
            if epoch is None:
                self._on_lease_lost(graph.job_id,
                                    "lease held by another shard")
                return False
            self.job_backend.save_job(graph, owner=self.scheduler_id,
                                      epoch=epoch)
            return True
        except LeaseLost as e:
            self._on_lease_lost(graph.job_id, str(e))
            return False
        except Exception:  # noqa: BLE001 — persistence is best-effort
            log.exception("job checkpoint failed for %s", graph.job_id)
            return True

    def _acquire_job_lease(self, job_id: str) -> Optional[int]:
        """The epoch this shard holds the job's lease at, acquiring the
        lease on first use (fresh jobs claim at first checkpoint)."""
        with self._lease_lock:
            epoch = self._leases.get(job_id)
        if epoch is not None:
            return epoch
        lease = self.job_backend.acquire_lease(
            job_id, self.scheduler_id, endpoint=self.client_endpoint,
            ttl_s=self.config.fleet_lease_ttl_s)
        if lease is None:
            return None
        with self._lease_lock:
            self._leases[job_id] = lease.epoch
        if journal.enabled():
            journal.set_job_epoch(job_id, lease.epoch)
            journal.emit_job("lease.acquire", job_id, epoch=lease.epoch,
                             scheduler_id=self.scheduler_id)
        return lease.epoch

    def _release_lease(self, job_id: str) -> None:
        if not self._lease_capable:
            return
        with self._lease_lock:
            held = self._leases.pop(job_id, None)
        if held is None:
            return
        try:
            self.job_backend.release_lease(job_id, self.scheduler_id)
        except Exception:  # noqa: BLE001 — lease will expire regardless
            log.exception("lease release failed for %s", job_id)

    def _on_lease_lost(self, job_id: str, why: str) -> None:
        """Fencing kicked in: another shard owns the job now.  Drop every
        local trace of it and reap our in-flight tasks — the adopter
        relaunches them and records all further state."""
        with self._lease_lock:
            self._leases.pop(job_id, None)
        if self.jobs.get_status(job_id) is None:
            return
        log.warning("lost lease on job %s (%s): abandoning local drive",
                    job_id, why)
        if journal.enabled():
            # emitted BEFORE the epoch clears, so the stand-down is stamped
            # with the fenced-off epoch this shard last held
            journal.emit_job("lease.stand_down", job_id, why=why,
                             scheduler_id=self.scheduler_id)
            journal.set_job_epoch(job_id, 0)
        # retain this shard's half of the job trace with a stand-down
        # marker before the job is dropped locally (the adopter's spans
        # continue the same trace_id via the checkpointed context)
        self.obs.on_stand_down(job_id, why)
        graph = self.jobs.get_graph(job_id)
        self.jobs.remove_job(job_id)
        with self._meta_lock:
            self._queued_at_ms.pop(job_id, None)
            self._serving_info.pop(job_id, None)
        self.admission.release(job_id)
        if graph is not None:
            self._submit_work(self._cancel_running, graph)

    def recover_jobs(self) -> List[str]:
        """Adopt persisted unfinished jobs (reference try_acquire_job,
        cluster/mod.rs:347-350).  Call after init() once executors have a
        chance to re-register."""
        if self.job_backend is None:
            return []
        adopted = []
        for job_id in self.job_backend.list_jobs():
            if self.jobs.get_status(job_id) is not None:
                continue
            if self._lease_capable:
                if self._adopt_one(job_id):
                    adopted.append(job_id)
                continue
            if not self.job_backend.try_acquire_job(job_id, self.scheduler_id):
                continue
            graph = self.job_backend.load_job(job_id)
            if graph is None or graph.status != "running":
                continue
            graph.addr_resolver = self._resolve_addr
            self.jobs.accept_job(job_id)
            self.jobs.submit_job(job_id, graph)
            adopted.append(job_id)
            log.info("adopted persisted job %s", job_id)
        if adopted:
            self._event_loop.post(Offer())
        return adopted

    # --- fleet HA: lease renewal + adoption ------------------------------
    def _lease_loop(self) -> None:
        """Lease heartbeat: renew every held job lease and refresh this
        shard's registry entry.  Not an event handler — blocking KV calls
        are fine here (same idiom as ``_reap_loop``)."""
        ttl = self.config.fleet_lease_ttl_s
        interval = self.config.fleet_lease_renew_s or ttl / 3.0
        while not self._stopped.wait(interval):
            with self._lease_lock:
                held = dict(self._leases)
            for job_id, epoch in held.items():
                try:
                    faults.inject("scheduler.lease.renew", job_id=job_id,
                                  scheduler_id=self.scheduler_id)
                except Exception as e:  # noqa: BLE001 — injected partition
                    log.warning("lease renewal suppressed for %s: %s",
                                job_id, e)
                    continue
                try:
                    if self.job_backend.renew_lease(
                            job_id, self.scheduler_id, epoch) is None:
                        self._on_lease_lost(job_id, "renewal refused")
                    elif journal.enabled():
                        journal.emit("lease.renew", job_id=job_id,
                                     epoch=epoch,
                                     scheduler_id=self.scheduler_id)
                except Exception:  # noqa: BLE001 — KV blip; TTL still runs
                    log.exception("lease renewal failed for %s", job_id)
            self._publish_registry()

    def _publish_registry(self) -> None:
        store = getattr(self.job_backend, "store", None)
        if store is None:
            return
        from .kv import publish_scheduler

        try:
            publish_scheduler(store, self.scheduler_id, self.client_endpoint,
                              sample=self._registry_sample())
        except Exception:  # noqa: BLE001 — registry is advisory
            log.exception("shard registry publish failed")

    _REGISTRY_KEYS = ("pending_tasks", "active_jobs",
                      "admission_queue_depth", "utilization", "total_slots",
                      "available_slots", "executors_alive")

    def _registry_sample(self) -> Dict:
        s = self.cluster_sample()
        out = {k: s[k] for k in self._REGISTRY_KEYS}
        # SLO piggyback: raw (count, violations) pairs per burn window so
        # any shard can merge a fleet-wide burn rate by summation (empty
        # for the null tracker — wire shape unchanged when SLO is off)
        out.update(self.slo.sample())
        return out

    def _adopt_loop(self) -> None:
        while not self._stopped.wait(self.config.fleet_adopt_interval_s):
            try:
                self.adopt_expired_jobs()
            except Exception:  # noqa: BLE001 — scan again next interval
                log.exception("lease adoption scan failed")

    def adopt_expired_jobs(self) -> List[str]:
        """Scan the shared KV for jobs whose owner stopped renewing (crash,
        partition, kill -9) and adopt them: take the lease over — bumping
        the fencing epoch — reload the graph from its last checkpoint, and
        resume driving it."""
        if not self._lease_capable or self._stopped.is_set():
            return []
        adopted: List[str] = []
        for stale in self.job_backend.expired_leases(
                self.config.fleet_lease_ttl_s):
            if stale.owner == self.scheduler_id:
                continue  # our own expiry: the renewal loop handles it
            if self.jobs.get_status(stale.job_id) is not None:
                continue
            if self._adopt_one(stale.job_id, prev_owner=stale.owner):
                adopted.append(stale.job_id)
        if adopted:
            self._event_loop.post(Offer())
        return adopted

    def _adopt_one(self, job_id: str, prev_owner: str = "") -> bool:
        lease = self.job_backend.acquire_lease(
            job_id, self.scheduler_id, endpoint=self.client_endpoint,
            ttl_s=self.config.fleet_lease_ttl_s)
        if lease is None:
            return False  # the owner came back, or another shard won
        faults.inject("scheduler.adopt.before_resume", job_id=job_id,
                      scheduler_id=self.scheduler_id)
        graph = self.job_backend.load_job(job_id)
        if graph is None or graph.status != "running":
            # the ex-owner finished the job (adoption raced completion) or
            # it never reached a running checkpoint: nothing to drive —
            # drop the claim so the lock doesn't linger as expired
            self.job_backend.release_lease(job_id, self.scheduler_id)
            return False
        with self._lease_lock:
            self._leases[job_id] = lease.epoch
        graph.addr_resolver = self._resolve_addr
        self.jobs.accept_job(job_id)
        self.jobs.submit_job(job_id, graph)
        if journal.enabled():
            # continue the ex-owner's flight record under the same job id
            # (the checkpoint carried its timeline), then mark the
            # ownership change at the new fencing epoch
            journal.seed_job(job_id,
                             list(getattr(graph, "journal", []) or []))
            journal.set_job_epoch(job_id, lease.epoch)
            journal.emit_job("lease.adopt", job_id, epoch=lease.epoch,
                             prev_owner=prev_owner,
                             scheduler_id=self.scheduler_id)
        # trace continuity across the failover: open this shard's side of
        # the job trace (same trace_id as the ex-owner when the checkpoint
        # carried it) with the fencing epoch annotated, then re-parent the
        # relaunched tasks under the adopter's execution phase
        self.obs.on_adopted(job_id, lease.epoch, prev_owner=prev_owner,
                            scheduler_id=self.scheduler_id,
                            trace=dict(getattr(graph, "trace", {}) or {}))
        graph.trace = self.obs.task_parent(job_id)
        log.info("adopted job %s at lease epoch %d", job_id, lease.epoch)
        return True

    def _on_task_updating(self, ev: TaskUpdating) -> None:
        statuses = ev.statuses
        if statuses is None:
            with self._status_lock:
                statuses = self._status_inbox.pop(ev.executor_id, [])
        if not statuses:
            # a sibling event already drained this inbox
            return
        self.cluster.free_slots(ev.executor_id, len(statuses))
        self._absorb_statuses(ev.executor_id, statuses)
        self._offer()

    def _on_executor_lost(self, ev: ExecutorLost) -> None:
        log.info("executor %s lost: %s", ev.executor_id, ev.reason)
        self.cluster.remove_executor(ev.executor_id)
        self.quarantine.remove(ev.executor_id)
        for graph in self.jobs.active_graphs():
            graph.executor_lost(ev.executor_id)
            # rolled-back stages re-resolve inside executor_lost, which may
            # re-apply AQE rewrites — surface their metric events too
            self._drain_aqe_events(graph)
        self._offer()

    def _on_job_cancel(self, ev: JobCancel) -> None:
        graph = self.jobs.get_graph(ev.job_id)
        if graph is None or graph.status != "running":
            # the job may still be waiting in the admission queue: pull it
            # out so it never plans, and free its tenant's queue slot
            if self.admission.take_queued(ev.job_id):
                with self._meta_lock:
                    self._queued_at_ms.pop(ev.job_id, None)
                    self._job_configs.pop(ev.job_id, None)
                if journal.enabled():
                    journal.emit_job("job.cancelled", ev.job_id, queued=True)
                self.jobs.set_status(JobStatus(ev.job_id, "cancelled"))
                self.metrics.record_cancelled(ev.job_id)
            return
        if journal.enabled():
            journal.emit_job("job.cancelled", ev.job_id)
        graph.cancel()
        self.jobs.set_status(JobStatus(ev.job_id, "cancelled"))
        self.metrics.record_cancelled(ev.job_id)
        with self._meta_lock:
            self._queued_at_ms.pop(ev.job_id, None)
        self._drop_poison_evidence(ev.job_id)
        self._cancel_running(graph)
        self._schedule_job_data_cleanup(graph)

    # --- job-data cleanup ------------------------------------------------
    def _schedule_job_data_cleanup(self, graph: ExecutionGraph) -> None:
        """Schedule a delayed remove_job_data fanout to every executor
        holding shuffle output for this finished job (reference
        clean_up_job_data, executor_manager.rs:231-253).  The TTL janitor
        on each executor remains the backstop for fanouts that miss."""
        delay = self.config.job_data_cleanup_delay_s
        if delay < 0 or self._stopped.is_set():
            return
        executors = {eid for stage in graph.stages.values()
                     for (eid, _w) in stage.outputs.values()}
        status = self.jobs.get_status(graph.job_id)
        if status is None or status.state != "successful":
            # a cancelled/expired/poisoned job can have stalled tasks that
            # wake AFTER the terminal verdict and write shuffle files no
            # stage ever registered — fan the remove to the whole fleet,
            # not just the executors with recorded outputs
            executors |= {m.executor_id for m in self.cluster.executors()}
        executors = sorted(executors)
        if not executors:
            return
        job_id = graph.job_id

        def fanout():
            with self._cleanup_lock:
                self._cleanup_timers.pop(job_id, None)
            if self._stopped.is_set():
                return
            # subplan spool files rehydrated for this job die with it
            self.result_cache.cleanup_job(job_id)
            for eid in executors:
                try:
                    self.launcher.clean_job_data(eid, job_id)
                except Exception:  # noqa: BLE001 — best effort
                    log.warning("clean_job_data on %s failed", eid,
                                exc_info=True)

        timer = threading.Timer(delay, fanout)
        timer.daemon = True
        with self._cleanup_lock:
            old = self._cleanup_timers.pop(job_id, None)
            self._cleanup_timers[job_id] = timer
        if old is not None:
            old.cancel()
        timer.start()

    def _cancel_one(self, executor_id: str, task_id: TaskId) -> None:
        try:
            self.launcher.cancel_task(executor_id, task_id)
        except Exception:  # noqa: BLE001 — best effort
            log.warning("cancel_task on %s failed for %s", executor_id,
                        task_id, exc_info=True)

    def _cancel_running(self, graph: ExecutionGraph) -> None:
        executors = {eid for _, _, eid in graph.running_tasks()}
        for eid in executors:
            try:
                self.launcher.cancel_tasks(eid, graph.job_id)
            except Exception:  # noqa: BLE001
                log.exception("cancel_tasks failed for %s", eid)

    def poll_work(self, executor_id: str, num_free_slots: int,
                  statuses: List[TaskStatus],
                  timeout: float = 10.0) -> List[TaskDescription]:
        """Pull-mode entry (blocking): returns up to num_free_slots tasks."""
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        self._event_loop.post(PollWork(executor_id, num_free_slots,
                                       statuses, reply))
        try:
            return reply.get(timeout=timeout)
        except queue.Empty:
            return []

    def _on_poll_work(self, ev: PollWork) -> None:
        tasks: List[TaskDescription] = []
        try:
            # timestamp-only refresh: a poll from a draining executor must
            # not flip its 'terminating' status back to active
            self.cluster.touch_heartbeat(ev.executor_id)
            if ev.statuses:
                self._absorb_statuses(ev.executor_id, ev.statuses)
            if self.quarantine.is_quarantined(ev.executor_id):
                return  # reply with no tasks (finally still runs)
            graphs = self.jobs.active_graphs()
            # retry anti-affinity context (see pop_next_task)
            alive = set(self.quarantine.filter(
                self.cluster.alive_executors(self.config.executor_timeout_s)))
            gate = self.admission.slot_gate(
                lambda: {g.job_id: len(g.running_tasks()) for g in graphs})
            while len(tasks) < ev.num_free_slots:
                task = None
                for graph in graphs:
                    if gate is not None and not gate.allows(graph.job_id):
                        continue
                    task = graph.pop_next_task(ev.executor_id, alive=alive)
                    if task is not None:
                        if gate is not None:
                            gate.took(graph.job_id)
                        break
                if task is None:
                    break
                tasks.append(task)
        finally:
            ev.reply.put(tasks)

    def _absorb_statuses(self, executor_id: str,
                         statuses: List[TaskStatus]) -> None:
        """Shared status intake (used by push TaskUpdating and pull
        PollWork)."""
        self._record_quarantine_signals(executor_id, statuses)
        by_job: Dict[str, List[TaskStatus]] = {}
        for st in statuses:
            if st.device_stats:
                # fleet-wide device-observatory fold: each status carries
                # the task's own delta, so summing on intake is exact
                self.metrics.record_device_stats(st.device_stats)
            if st.journal:
                # executor flight-record piggyback: merge into the job's
                # timeline (wire contract mirrors device_stats)
                journal.absorb(st.task.job_id, st.journal)
            if journal.enabled():
                journal.emit("task.finish", job_id=st.task.job_id,
                             parent_key=("task", st.task.job_id,
                                         st.task.stage_id,
                                         st.task.partition,
                                         st.task.task_attempt),
                             stage_id=st.task.stage_id,
                             partition=st.task.partition,
                             attempt=st.task.task_attempt,
                             state=st.state,
                             executor_id=st.executor_id or executor_id)
            by_job.setdefault(st.task.job_id, []).append(st)
        for job_id, sts in by_job.items():
            graph = self.jobs.get_graph(job_id)
            if graph is None:
                continue
            if job_id in self._poison_suspects:
                # containment beats retry: fail the job NOW, before the
                # graph's retry bookkeeping re-launches the poison
                # partition and burns another executor's slot
                self._poison_suspects.discard(job_id)
                try:
                    self._fail_poisoned(job_id, graph)
                except Exception:  # noqa: BLE001 — scope the blast radius
                    log.exception("poison containment crashed for job %s",
                                  job_id)
                continue
            try:
                self._absorb_job_statuses(job_id, graph, sts)
            except Exception as e:  # noqa: BLE001 — scope the blast radius
                # a crash absorbing ONE job's statuses must not fail the
                # other jobs in the batch (their updates were already
                # applied, or will be, independently)
                log.exception("status absorption crashed for job %s", job_id)
                st = self.jobs.get_status(job_id)
                if st is not None and st.state in ("successful", "failed",
                                                   "cancelled"):
                    # the crash happened AFTER a terminal status was
                    # published (e.g. in metrics/cleanup scheduling) —
                    # don't overwrite what clients already saw
                    continue
                if graph.status == "running":
                    graph.status = "failed"
                with self._meta_lock:
                    self._queued_at_ms.pop(job_id, None)
                # durable before visible, same as the success path below
                self._checkpoint(graph)
                self.jobs.set_status(JobStatus(
                    job_id, "failed",
                    error=f"status absorption crashed: "
                          f"{type(e).__name__}: {e}"))
                self.metrics.record_failed(job_id)

    def _fleet_memory_pressure(self) -> float:
        """Fleet-wide memory-pressure floor (admission's shed signal);
        0.0 for cluster backends without pressure tracking."""
        fn = getattr(self.cluster, "min_alive_pressure", None)
        return fn(self.config.executor_timeout_s) if fn is not None else 0.0

    def _record_quarantine_signals(self, executor_id: str,
                                   statuses: List[TaskStatus]) -> None:
        """Feed the quarantine counter: a success clears the reporting
        executor's streak; a *retryable* failure (IOError/ExecutorLost/
        ResultLost) extends it.  Fetch failures blame the producer's data
        and fatal ExecutionErrors fail the job outright — neither says this
        executor is sick, so neither counts.  ResourceExhausted is
        retryable but ALSO exempt: a governor denial means the executor
        protected itself from OOM — blaming it into quarantine would
        quarantine the whole fleet exactly when memory is tight."""
        for st in statuses:
            eid = st.executor_id or executor_id
            if st.state == "success":
                self.quarantine.record_success(eid)
            elif (st.state == "failed" and st.failure is not None
                  and st.failure.kind == RESOURCE_EXHAUSTED):
                # no strike, no streak reset: memory back-pressure says
                # nothing about this executor's health either way
                pass
            elif (st.state == "failed" and st.failure is not None
                  and st.failure.kind == FETCH_PARTITION_ERROR
                  and "integrity check failed" in st.failure.message):
                # a checksum/decode failure that survived the fetcher's
                # in-loop retries: the PRODUCER's data is damaged — count
                # the producing executor, not the reporting fetcher, so a
                # host serving corrupt partitions gets quarantined
                self.metrics.record_integrity_failure(st.failure.executor_id)
                if st.failure.executor_id and self.quarantine.record_failure(
                        st.failure.executor_id):
                    log.warning(
                        "executor %s quarantined: served corrupt shuffle "
                        "data (%s)", st.failure.executor_id,
                        st.failure.message)
                    self.metrics.record_quarantined(st.failure.executor_id)
                    if journal.enabled():
                        journal.emit("quarantine.enter",
                                     job_id=st.task.job_id,
                                     executor_id=st.failure.executor_id,
                                     reason="corrupt shuffle data")
            elif (st.state == "failed" and st.failure is not None
                  and st.failure.retryable):
                if self._note_poison_evidence(eid, st):
                    # a DIFFERENT executor already failed this exact
                    # partition the same way: the evidence points at the
                    # query, not this host — corroborating failures carry
                    # no quarantine strike
                    continue
                if self.quarantine.record_failure(eid):
                    log.warning(
                        "executor %s quarantined after %d consecutive "
                        "retryable task failures (probation in %.0fs)", eid,
                        self.quarantine.threshold,
                        self.quarantine.probation_s)
                    self.metrics.record_quarantined(eid)
                    if journal.enabled():
                        journal.emit("quarantine.enter",
                                     job_id=st.task.job_id,
                                     executor_id=eid,
                                     reason="consecutive retryable failures")
        self.metrics.set_quarantined_executors(self.quarantine.count())

    # --- poison-query containment ----------------------------------------
    def _note_poison_evidence(self, eid: str, st: TaskStatus) -> bool:
        """Record one retryable failure as poison evidence.  Returns True
        when the quarantine strike should be SUPPRESSED because another
        executor already failed the same partition with an equivalent
        error (the query is the prime suspect, not this host).  Once the
        same signature lands on ``poison_distinct_executors`` distinct
        non-quarantined executors, the job is queued for containment.
        Event-loop only (push TaskUpdating and pull PollWork both absorb
        on the loop)."""
        k = self.config.poison_distinct_executors
        if k <= 0 or st.failure is None:
            return False
        key = (st.task.job_id, st.task.stage_id, st.task.partition)
        sig = f"{st.failure.kind}: {st.failure.message[:160]}"
        ev = self._poison_evidence.setdefault(key, {})
        corroborated = any(e != eid and s == sig for e, (s, _w) in ev.items())
        # a witness counts if it was healthy when it FIRST testified —
        # judged at record time, because the poison query's own strikes
        # may quarantine an executor before the Kth failure lands, and a
        # host the query itself knocked out is still a valid witness
        if eid in ev:
            ev[eid] = (sig, ev[eid][1])
        else:
            ev[eid] = (sig, not self.quarantine.is_quarantined(eid))
        distinct = {e for e, (s, w) in ev.items() if s == sig and w}
        if len(distinct) >= k:
            self._poison_suspects.add(st.task.job_id)
        return corroborated

    def _drop_poison_evidence(self, job_id: str) -> None:
        """Forget a terminal job's poison bookkeeping (event-loop only)."""
        self._poison_suspects.discard(job_id)
        for key in [k for k in self._poison_evidence if k[0] == job_id]:
            del self._poison_evidence[key]

    def _fail_poisoned(self, job_id: str, graph) -> None:
        """Containment: the same partition failed with equivalent errors on
        K distinct executors — the query is the culprit.  Fail it
        immediately (skipping the per-task retry budget), refund every
        implicated executor's quarantine streak, and attach a forensics
        bundle so the failure is diagnosable post-mortem."""
        if graph.status != "running":
            self._drop_poison_evidence(job_id)
            return
        k = self.config.poison_distinct_executors
        evidence: Dict[str, Dict[str, str]] = {}
        implicated = set()
        for (jid, sid, p), ev in self._poison_evidence.items():
            if jid != job_id:
                continue
            evidence[f"{sid}/{p}"] = {e: s for e, (s, _w) in ev.items()}
            implicated.update(ev)
        # zero quarantine strikes: the poison query burned healthy hosts,
        # so wipe the streaks it charged them (forced poison queries must
        # end with an empty quarantine set)
        for eid in sorted(implicated):
            self.quarantine.record_success(eid)
        self.metrics.set_quarantined_executors(self.quarantine.count())
        message = (f"{POISON_QUERY}: same partition failed with equivalent "
                   f"errors on {k}+ distinct executors — job classified "
                   f"poison, retries abandoned")
        if journal.enabled():
            # before the checkpoint, so the terminal event (with its
            # per-executor evidence) rides the persisted timeline
            journal.emit_job("job.poisoned", job_id,
                             distinct_executors=str(k),
                             evidence=evidence)
        graph.status = "failed"
        graph.error = message
        with self._meta_lock:
            queued_at = self._queued_at_ms.pop(job_id, None)
        self._drop_poison_evidence(job_id)
        if not self._checkpoint(graph):
            return  # lease lost: the adopter owns this job now
        self.jobs.set_status(JobStatus(job_id, "failed", error=message,
                                       retriable=False))
        self.metrics.record_failed(job_id)
        self.metrics.record_poisoned(job_id)
        self.slo.record(
            int(time.time() * 1000) - queued_at if queued_at else 0.0,
            ok=False)
        log.warning("job %s classified poison: %s", job_id, message)
        self._cancel_running(graph)
        self._schedule_job_data_cleanup(graph)
        try:
            from ..obs.doctor import assemble_forensics
            graph.forensics = assemble_forensics(self, job_id)
        except Exception:  # noqa: BLE001 — forensics are best-effort
            log.warning("forensics assembly failed for %s", job_id,
                        exc_info=True)

    def _absorb_job_statuses(self, job_id: str, graph,
                             sts: List[TaskStatus]) -> None:
        checkpointed = False
        for kind, payload in graph.update_task_status(sts):
            if kind == "speculative_win":
                stage_id, partition = payload
                log.info("speculative attempt won: job %s stage %d "
                         "partition %d", job_id, stage_id, partition)
                self.metrics.record_speculative_win(job_id)
                if journal.enabled():
                    journal.emit("speculation.win", job_id=job_id,
                                 stage_id=stage_id, partition=partition)
            elif kind == "cancel_task":
                # first result won the race: reap the losing duplicate so
                # it stops burning a slot (its late status is discarded by
                # the graph's attempt bookkeeping either way)
                executor_id, task_id = payload
                if journal.enabled():
                    journal.emit("task.cancel", job_id=job_id,
                                 stage_id=task_id.stage_id,
                                 partition=task_id.partition,
                                 attempt=task_id.task_attempt,
                                 executor_id=executor_id)
                self._submit_work(self._cancel_one, executor_id, task_id)
            elif kind == "job_successful":
                # terminal state must be durable BEFORE waiters wake:
                # set_status releases wait_for_job, and a restarted
                # scheduler must never see a completed job as running
                if journal.enabled():
                    # before the checkpoint, so the terminal event is IN
                    # the persisted timeline
                    journal.emit_job("job.successful", job_id)
                if not self._checkpoint(graph):
                    return  # lease lost: the adopter owns this job now
                checkpointed = True
                with self._meta_lock:
                    serving = self._serving_info.pop(job_id, None)
                if serving is not None and (serving.capture_result
                                            or serving.subplan):
                    self._submit_work(self._capture_serving, graph, payload,
                                      serving)
                self.jobs.set_status(
                    JobStatus(job_id, "successful", locations=payload))
                with self._meta_lock:
                    queued_at = self._queued_at_ms.pop(job_id, 0)
                done_ms = int(time.time() * 1000)
                self.metrics.record_completed(job_id, queued_at, done_ms)
                if queued_at:
                    # SLO sample: queue-to-done wall time, the latency a
                    # waiting client observed (no-op on the null tracker)
                    self.slo.record(done_ms - queued_at, ok=True)
                self._drop_poison_evidence(job_id)
                self._schedule_job_data_cleanup(graph)
            elif kind == "job_failed":
                if journal.enabled():
                    journal.emit_job("job.failed", job_id,
                                     error=str(payload))
                if not self._checkpoint(graph):
                    return  # lease lost: the adopter owns this job now
                checkpointed = True
                self.jobs.set_status(
                    JobStatus(job_id, "failed", error=str(payload)))
                self.metrics.record_failed(job_id)
                with self._meta_lock:
                    queued_at = self._queued_at_ms.pop(job_id, None)
                # a failed job always burns SLO budget, whatever its wall time
                self.slo.record(
                    int(time.time() * 1000) - queued_at if queued_at else 0.0,
                    ok=False)
                self._drop_poison_evidence(job_id)
                self._cancel_running(graph)
                self._schedule_job_data_cleanup(graph)
        self._drain_aqe_events(graph)
        if not checkpointed:
            self._checkpoint(graph)  # False = abandoned; nothing more to do

    def _drain_aqe_events(self, graph) -> None:
        """Fold the graph's buffered AQE rewrite events into the metrics
        collector (rewrites happen inside graph mutation, which has no
        collector handle; the scheduler drains after every absorb)."""
        events = getattr(graph, "aqe_events", None)
        if not events:
            return
        for kind, n in events:
            if journal.enabled():
                journal.emit("aqe.rewrite", job_id=graph.job_id,
                             rewrite=kind, partitions=n)
            if kind == "coalesce":
                self.metrics.record_aqe_coalesce(n)
            elif kind == "broadcast":
                self.metrics.record_aqe_broadcast_switch(n)
            elif kind == "skew":
                self.metrics.record_aqe_skew_split(n)
        events.clear()

    def _resolve_addr(self, executor_id: str):
        # (host, data-plane port, control-plane port): the data plane may be
        # the native whole-file server, so streaming fetches dial grpc_port
        # (the Python RPC server, which speaks fetch_partition_stream)
        meta = self.cluster.get_executor(executor_id)
        return (meta.host, meta.port, meta.grpc_port) \
            if meta is not None else ("", 0, 0)

    # --- push scheduling -------------------------------------------------
    def _offer(self) -> None:
        """Reserve free slots and fill them with tasks (reference
        state/mod.rs:195-233 offer_reservation + fill_reservations)."""
        pending = self.pending_task_count()
        self.metrics.set_pending_tasks_queue_size(pending)
        # every scheduling round re-evaluates the admission queue against
        # live signals (completions, executor registrations/losses all
        # funnel through here)
        self.admission.pump()
        if self.config.policy != "push":
            return  # pull mode: executors come to us via poll_work
        alive = set(self.quarantine.filter(
            self.cluster.alive_executors(self.config.executor_timeout_s)))
        if pending == 0 or not alive:
            return
        reservations = self.cluster.reserve_slots(pending, sorted(alive))
        if not reservations:
            return
        assignments: Dict[str, List[TaskDescription]] = {}
        unused: List[ExecutorReservation] = []
        graphs = self.jobs.active_graphs()
        if self._lease_capable:
            # slots go only to jobs whose lease THIS shard holds: a job we
            # were fenced off of is the adopter's to drive, even if its
            # local teardown hasn't landed yet
            with self._lease_lock:
                owned = set(self._leases)
            graphs = [g for g in graphs if g.job_id in owned]
        gate = self.admission.slot_gate(
            lambda: {g.job_id: len(g.running_tasks()) for g in graphs})

        def fill(rs: List[ExecutorReservation]) -> List[ExecutorReservation]:
            leftovers: List[ExecutorReservation] = []
            for r in rs:
                task = None
                for graph in graphs:
                    if gate is not None and not gate.allows(graph.job_id):
                        continue
                    task = graph.pop_next_task(r.executor_id, alive=alive)
                    if task is not None:
                        if gate is not None:
                            gate.took(graph.job_id)
                        break
                if task is None:
                    leftovers.append(r)
                else:
                    assignments.setdefault(r.executor_id, []).append(task)
            return leftovers

        unused = fill(reservations)
        if unused:
            # Retry anti-affinity can veto every reserved executor while a
            # DIFFERENT alive executor could legally run the pending task
            # (a retried partition is steered away from executors that
            # already failed it).  Once an idle fleet's offer round comes
            # up empty no further event re-triggers it, so convert the
            # veto into a steer with one bounded second pass over the
            # executors the first reservation round never tried.
            vetoed = {r.executor_id for r in unused}
            self.cluster.cancel_reservations(unused)
            unused = []
            retry_pool = sorted(alive - vetoed)
            if retry_pool:
                unused = fill(self.cluster.reserve_slots(
                    len(vetoed), retry_pool))
                if unused:
                    self.cluster.cancel_reservations(unused)
        for executor_id, tasks in assignments.items():
            self._submit_work(self._launch, executor_id, tasks)

    def _launch(self, executor_id: str, tasks: List[TaskDescription]) -> None:
        try:
            self.launcher.launch_tasks(executor_id, tasks)
        except Exception as e:  # noqa: BLE001 — treat as executor failure
            log.exception("launch on %s failed", executor_id)
            self.cluster.free_slots(executor_id, len(tasks))
            self._event_loop.post(ExecutorLost(executor_id, f"launch failed: {e}"))

    # --- speculative execution (straggler mitigation) --------------------
    def _speculation_loop(self) -> None:
        """Monitor thread: periodically posts a tick; the straggler scan
        itself runs on the event loop (single-threaded graph access)."""
        while not self._stopped.wait(self.config.speculation.interval_s):
            self._event_loop.post(SpeculationTick())

    def _on_speculation_tick(self) -> None:
        policy = self.config.speculation
        alive = set(self.quarantine.filter(
            self.cluster.alive_executors(self.config.executor_timeout_s)))
        if len(alive) < 2:
            return  # a duplicate must land on a DIFFERENT executor
        now = time.monotonic()
        for graph in self.jobs.active_graphs():
            for stage_id, partition, running_on in find_candidates(
                    graph, now, policy):
                pool = sorted(alive - {running_on})
                if not pool:
                    continue
                reservations = self.cluster.reserve_slots(1, pool)
                if not reservations:
                    continue
                executor_id = reservations[0].executor_id
                task = graph.launch_speculative(stage_id, partition,
                                                executor_id)
                if task is None:
                    self.cluster.cancel_reservations(reservations)
                    continue
                log.info(
                    "speculative attempt %d: job %s stage %d partition %d "
                    "on %s (original still running on %s)",
                    task.task.task_attempt, graph.job_id, stage_id,
                    partition, executor_id, running_on)
                self.metrics.record_speculative_launched(graph.job_id)
                if journal.enabled():
                    journal.emit("speculation.launch", job_id=graph.job_id,
                                 stage_id=stage_id, partition=partition,
                                 attempt=task.task.task_attempt,
                                 executor_id=executor_id,
                                 running_on=running_on)
                self._submit_work(self._launch, executor_id, [task])

    # --- cluster time series (obs/stats.py ClusterHistory) ---------------
    def cluster_sample(self) -> Dict:
        """One utilization/saturation sample (pure read — also served fresh
        as the ``now`` field of GET /api/cluster/history)."""
        total = self.cluster.total_slots()
        available = self.cluster.total_available()
        ev = self._event_loop.stats()
        return {
            "ts": round(time.time(), 3),
            "executors_alive": len(self.cluster.alive_executors(
                self.config.executor_timeout_s)),
            "executors_total": len(self.cluster.executors()),
            "total_slots": total,
            "available_slots": available,
            "utilization": round((total - available) / total, 4)
            if total else 0.0,
            "pending_tasks": self.pending_task_count(),
            "active_jobs": len(self.jobs.active_graphs()),
            "admission_queue_depth": self.admission.queue_depth(),
            "event_queue_depth": ev["queue_depth"],
            "event_loop_lag_s": ev["last_lag_s"],
            "event_loop_max_lag_s": ev["max_lag_s"],
            "event_handler_seconds_mean": ev["handler_seconds_mean"],
            "slow_events": ev["slow_events"],
        }

    def autoscale_signal(self) -> Dict:
        """KEDA-style scaling signal behind GET /api/autoscale: pending
        work, utilization and queue depths — aggregated across every live
        shard via the shared-KV shard registry when one exists, so any
        shard answers for the whole fleet (reference external_scaler.rs
        generalized from one scheduler to N)."""
        local = self.cluster_sample()
        shards = [{"scheduler_id": self.scheduler_id,
                   "endpoint": self.client_endpoint,
                   **{k: local[k] for k in self._REGISTRY_KEYS}}]
        store = getattr(self.job_backend, "store", None) \
            if self._lease_capable else None
        if store is not None:
            from .kv import scheduler_registry

            try:
                reg = scheduler_registry(store,
                                         self.config.fleet_registry_stale_s)
            except Exception:  # noqa: BLE001 — fall back to local-only
                log.exception("shard registry read failed")
                reg = {}
            for sid in sorted(reg):
                if sid == self.scheduler_id:
                    continue
                obj = reg[sid]
                sample = obj.get("sample") or {}
                shards.append({"scheduler_id": sid,
                               "endpoint": obj.get("endpoint", ""),
                               **{k: sample.get(k, 0)
                                  for k in self._REGISTRY_KEYS}})
        # flow is per-shard (each shard owns distinct jobs) so it sums;
        # capacity is the SHARED executor pool seen by every shard through
        # the common KV (executors multi-register), so summing would
        # multiply it by the shard count — take the freshest full view
        out = {k: sum(s.get(k, 0) for s in shards)
               for k in ("pending_tasks", "active_jobs",
                         "admission_queue_depth")}
        out.update({k: max(s.get(k, 0) for s in shards)
                    for k in ("total_slots", "available_slots",
                              "executors_alive")})
        total, avail = out["total_slots"], out["available_slots"]
        out["utilization"] = round((total - avail) / total, 4) if total else 0.0
        # slots needed for everything runnable now, in executor units at
        # the fleet's current mean slots-per-executor
        backlog = out["pending_tasks"] + out["admission_queue_depth"] \
            + (total - avail)
        per_exec = max(1.0, total / max(1, out["executors_alive"]))
        out["desired_executors"] = int(-(-backlog // per_exec))
        if self.slo.enabled:
            # SLO-aware term: a burn rate above 1.0 means the latency
            # budget is being consumed faster than it refills — ask for
            # extra executors proportional to the overshoot even when the
            # raw backlog alone would not scale (queueing shows up in
            # latency before it shows up in slot arithmetic)
            snap = self.slo.snapshot(
                shard_samples=self._sibling_slo_samples())
            burn = max(snap["windows"]["fast"]["burn_rate"],
                       snap["windows"]["slow"]["burn_rate"])
            # ceil(burn - 1), capped: a cold window with one slow job can
            # read burn=100x, which must not demand 99 extra executors
            boost = min(int(-(-(burn - 1.0) // 1)), 4) if burn > 1.0 else 0
            out["desired_executors"] += boost
            out["slo"] = {"burn_rate": burn, "scale_boost": boost,
                          "windows": snap["windows"]}
        out["inflight_tasks"] = out["pending_tasks"]  # /api/scaler parity
        out["shards"] = shards
        return out

    def _sibling_slo_samples(self) -> List[Dict]:
        """Sibling shards' SLO (count, violations) pairs from the shard
        registry — the fleet half of every burn-rate merge."""
        store = getattr(self.job_backend, "store", None) \
            if self._lease_capable else None
        if store is None:
            return []
        from .kv import scheduler_registry

        try:
            reg = scheduler_registry(store,
                                     self.config.fleet_registry_stale_s)
        except Exception:  # noqa: BLE001 — fall back to local-only
            log.exception("shard registry read failed")
            return []
        return [{k: v for k, v in (obj.get("sample") or {}).items()
                 if k.startswith("slo_")}
                for sid, obj in reg.items() if sid != self.scheduler_id]

    def slo_report(self) -> Dict:
        """GET /api/slo: the fleet-merged burn-rate report (or
        ``{"enabled": false}`` when no objective is configured)."""
        return self.slo.snapshot(shard_samples=self._sibling_slo_samples())

    def _history_loop(self) -> None:
        """Sampler thread: appends a cluster sample to the ring buffer and
        refreshes the event-loop gauges.  Not an event handler — blocking
        waits are fine here (same idiom as ``_reap_loop``)."""
        while not self._stopped.wait(self.config.stats_history_interval_s):
            try:
                sample = self.cluster_sample()
            except Exception:  # noqa: BLE001 — sampling must outlive one bad read
                log.exception("cluster history sampling failed")
                continue
            self.history.record(sample)
            self.metrics.set_event_queue_depth(sample["event_queue_depth"])
            self.metrics.set_event_loop_lag(sample["event_loop_lag_s"])
            self.sync_journal_metrics()
            if self.slo.enabled:
                # shard-local burn gauges (fleet merge happens at
                # /api/slo; prometheus sums/maxes across shards itself)
                snap = self.slo.snapshot()
                self.metrics.set_slo_burn_rate(
                    "fast", snap["windows"]["fast"]["burn_rate"])
                self.metrics.set_slo_burn_rate(
                    "slow", snap["windows"]["slow"]["burn_rate"])

    def _live_doctor_loop(self) -> None:
        """In-flight doctor cadence (obs/live.py): evaluate the live rule
        subset over running jobs, raise/clear journal alerts with
        hysteresis, refresh the alerts_active gauge.  A sampler-style
        thread (blocking waits allowed), never an event handler."""
        while not self._stopped.wait(self.config.live_doctor_interval_s):
            try:
                self.live_doctor.scan(self)
            except Exception:  # noqa: BLE001 — scan again next interval
                log.exception("live doctor scan failed")
            self.metrics.set_alerts_active(self.live_doctor.alerts_active())

    def sync_journal_metrics(self) -> None:
        """Fold the process-global journal counters into this collector as
        deltas (called by the history sampler and the REST /api/metrics
        handler; cheap and idempotent)."""
        tot, drop = journal.counters()
        last_tot, last_drop = self._journal_last
        if tot > last_tot:
            self.metrics.record_journal_events(tot - last_tot)
        if drop > last_drop:
            self.metrics.record_journal_dropped(drop - last_drop)
        self._journal_last = (tot, drop)

    def cluster_history(self) -> Dict:
        """Fleet-aware GET /api/cluster/history: this shard's sample ring
        plus a live per-shard breakdown and fleet rollup when a shard
        registry exists (same merge discipline as ``autoscale_signal``:
        per-shard flow sums, shared capacity takes the freshest full
        view)."""
        out = self.history.snapshot()
        out["now"] = self.cluster_sample()
        shards = [{"scheduler_id": self.scheduler_id,
                   "endpoint": self.client_endpoint, "local": True,
                   **{k: out["now"][k] for k in self._REGISTRY_KEYS}}]
        store = getattr(self.job_backend, "store", None) \
            if self._lease_capable else None
        if store is not None:
            from .kv import scheduler_registry

            try:
                reg = scheduler_registry(store,
                                         self.config.fleet_registry_stale_s)
            except Exception:  # noqa: BLE001 — fall back to local-only
                log.exception("shard registry read failed")
                reg = {}
            for sid in sorted(reg):
                if sid == self.scheduler_id:
                    continue
                obj = reg[sid]
                sample = obj.get("sample") or {}
                shards.append({"scheduler_id": sid,
                               "endpoint": obj.get("endpoint", ""),
                               "local": False,
                               **{k: sample.get(k, 0)
                                  for k in self._REGISTRY_KEYS}})
            fleet = {k: sum(s.get(k, 0) for s in shards)
                     for k in ("pending_tasks", "active_jobs",
                               "admission_queue_depth")}
            fleet.update({k: max(s.get(k, 0) for s in shards)
                          for k in ("total_slots", "available_slots",
                                    "executors_alive")})
            total, avail = fleet["total_slots"], fleet["available_slots"]
            fleet["utilization"] = round((total - avail) / total, 4) \
                if total else 0.0
            out["fleet"] = fleet
        out["shards"] = shards
        return out

    # --- failure detection ----------------------------------------------
    def _reap_loop(self) -> None:
        """Dead-executor reaper (reference expire_dead_executors,
        scheduler_server/mod.rs:224-305)."""
        while not self._stopped.wait(self.config.reaper_interval_s):
            for eid in self.cluster.expired_executors(self.config.executor_timeout_s):
                self._event_loop.post(ExecutorLost(eid, "heartbeat timeout"))

    # --- server-side deadlines -------------------------------------------
    def _deadline_loop(self) -> None:
        """Deadline scan: posts JobDeadline for any active job whose
        absolute expiry passed.  Read-only off the loop — the handler
        re-checks graph state ON the loop before acting, so a job that
        finished between scan and dispatch is untouched."""
        while not self._stopped.wait(self.config.deadline_scan_interval_s):
            now = time.time()
            for graph in self.jobs.active_graphs():
                ts = getattr(graph, "deadline_ts", 0.0)
                if ts and now >= ts:
                    self._event_loop.post(JobDeadline(graph.job_id))

    def _on_job_deadline(self, ev: JobDeadline) -> None:
        graph = self.jobs.get_graph(ev.job_id)
        if (graph is None or graph.status != "running"
                or not getattr(graph, "deadline_ts", 0.0)
                or time.time() < graph.deadline_ts):
            return  # finished/cancelled in flight, or a stale scan
        budget = getattr(graph, "deadline_s", 0.0)
        message = (f"{DEADLINE_EXCEEDED}: job exceeded its "
                   f"{budget:.1f}s deadline")
        if journal.enabled():
            # before the checkpoint, so the terminal event is IN the
            # persisted timeline
            journal.emit_job("job.deadline_exceeded", ev.job_id,
                             deadline_s=f"{budget:.3f}", retriable="false")
        graph.status = "failed"
        graph.error = message
        with self._meta_lock:
            queued_at = self._queued_at_ms.pop(ev.job_id, None)
        self._drop_poison_evidence(ev.job_id)
        # durable before visible: a restarted/adopting scheduler must see
        # the deadline verdict, never resurrect the job past its budget
        if not self._checkpoint(graph):
            return  # lease lost: the adopter owns this job now
        self.jobs.set_status(JobStatus(ev.job_id, "failed", error=message,
                                       retriable=False))
        self.metrics.record_failed(ev.job_id)
        self.metrics.record_deadline_exceeded(ev.job_id)
        # a deadline miss always burns SLO budget, whatever its wall time
        self.slo.record(
            int(time.time() * 1000) - queued_at if queued_at else 0.0,
            ok=False)
        log.warning("job %s cancelled fleet-wide: %s", ev.job_id, message)
        self._cancel_running(graph)
        self._schedule_job_data_cleanup(graph)
