"""Generic single-consumer event loop (parity: reference
ballista/core/src/event_loop.rs:27-142 — mpsc-backed EventLoop/EventAction).

Python rendition: a daemon thread draining a bounded queue.  The scheduler
state machine (``QueryStageScheduler``) is the one EventAction; everything
that mutates scheduler state flows through here, exactly as in the
reference, so state transitions are single-threaded by construction.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)


class EventLoop:
    def __init__(self, name: str, on_receive: Callable[[object], None],
                 buffer_size: int = 10000,
                 slow_event_threshold_s: float = 1.0,
                 on_error: Optional[Callable[[object, BaseException], None]] = None):
        self.name = name
        self._on_receive = on_receive
        # on_error: last-resort hook when a handler raises — the loop itself
        # must survive, but whoever owns the loop may need to fail the
        # affected job so clients aren't left polling a forever-"running"
        # status (observed: a repr() crash inside a handler stranded the
        # job until its deadline)
        self._on_error = on_error
        # entries are (enqueue_monotonic, event) so the consumer can measure
        # queue lag — the ROADMAP item 3 saturation signal
        self._queue: "queue.Queue[object]" = queue.Queue(maxsize=buffer_size)
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.slow_event_threshold_s = slow_event_threshold_s
        # lag/latency counters: written only by the consumer thread, read by
        # the metrics sampler — single-writer, so plain attributes suffice
        self._events_processed = 0
        self._slow_events = 0
        self._last_lag_s = 0.0
        self._max_lag_s = 0.0
        self._handler_seconds_total = 0.0
        self._handler_seconds_max = 0.0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stopped.set()
        self._queue.put(None)  # wake the consumer
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def post(self, event: object) -> None:
        if self._stopped.is_set():
            return
        self._queue.put((time.monotonic(), event))

    def queue_depth(self) -> int:
        return self._queue.qsize()

    def stats(self) -> dict:
        """Lag/latency snapshot for the metrics sampler and
        ``/api/cluster/history`` (lag = dequeue time - enqueue time)."""
        n = self._events_processed
        return {
            "queue_depth": self._queue.qsize(),
            "events_processed": n,
            "slow_events": self._slow_events,
            "last_lag_s": round(self._last_lag_s, 6),
            "max_lag_s": round(self._max_lag_s, 6),
            "handler_seconds_total": round(self._handler_seconds_total, 6),
            "handler_seconds_max": round(self._handler_seconds_max, 6),
            "handler_seconds_mean":
                round(self._handler_seconds_total / n, 6) if n else 0.0,
        }

    def _run(self) -> None:
        while not self._stopped.is_set():
            item = self._queue.get()
            if item is None:
                continue
            enqueued_at, event = item
            t0 = time.monotonic()
            self._last_lag_s = t0 - enqueued_at
            if self._last_lag_s > self._max_lag_s:
                self._max_lag_s = self._last_lag_s
            try:
                self._on_receive(event)
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                log.exception("%s: event handler raised", self.name)
                if self._on_error is not None:
                    try:
                        self._on_error(event, exc)
                    except Exception:  # noqa: BLE001
                        log.exception("%s: on_error hook raised", self.name)
            dt = time.monotonic() - t0
            self._events_processed += 1
            self._handler_seconds_total += dt
            if dt > self._handler_seconds_max:
                self._handler_seconds_max = dt
            if dt > self.slow_event_threshold_s:
                self._slow_events += 1
                # reference slow-event watchdog
                # (query_stage_scheduler.rs:378-389)
                log.warning("%s: slow event %r took %.2fs", self.name, event, dt)

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the queue is empty (test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            # ballista: allow=no-blocking-in-event-loop — drain() runs on the calling (test) thread, never the loop thread
            time.sleep(0.005)
        return False
