"""Generic single-consumer event loop (parity: reference
ballista/core/src/event_loop.rs:27-142 — mpsc-backed EventLoop/EventAction).

Python rendition: a daemon thread draining a bounded queue.  The scheduler
state machine (``QueryStageScheduler``) is the one EventAction; everything
that mutates scheduler state flows through here, exactly as in the
reference, so state transitions are single-threaded by construction.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)


class EventLoop:
    def __init__(self, name: str, on_receive: Callable[[object], None],
                 buffer_size: int = 10000,
                 slow_event_threshold_s: float = 1.0,
                 on_error: Optional[Callable[[object, BaseException], None]] = None):
        self.name = name
        self._on_receive = on_receive
        # on_error: last-resort hook when a handler raises — the loop itself
        # must survive, but whoever owns the loop may need to fail the
        # affected job so clients aren't left polling a forever-"running"
        # status (observed: a repr() crash inside a handler stranded the
        # job until its deadline)
        self._on_error = on_error
        self._queue: "queue.Queue[object]" = queue.Queue(maxsize=buffer_size)
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self.slow_event_threshold_s = slow_event_threshold_s

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stopped.set()
        self._queue.put(None)  # wake the consumer
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def post(self, event: object) -> None:
        if self._stopped.is_set():
            return
        self._queue.put(event)

    def _run(self) -> None:
        while not self._stopped.is_set():
            event = self._queue.get()
            if event is None:
                continue
            t0 = time.monotonic()
            try:
                self._on_receive(event)
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                log.exception("%s: event handler raised", self.name)
                if self._on_error is not None:
                    try:
                        self._on_error(event, exc)
                    except Exception:  # noqa: BLE001
                        log.exception("%s: on_error hook raised", self.name)
            dt = time.monotonic() - t0
            if dt > self.slow_event_threshold_s:
                # reference slow-event watchdog
                # (query_stage_scheduler.rs:378-389)
                log.warning("%s: slow event %r took %.2fs", self.name, event, dt)

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the queue is empty (test helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            # ballista: allow=no-blocking-in-event-loop — drain() runs on the calling (test) thread, never the loop thread
            time.sleep(0.005)
        return False
