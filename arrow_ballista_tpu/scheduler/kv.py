"""Pluggable cluster state: a keyspace'd KV store with transactions + locks.

Parity: the reference makes scheduler state pluggable over a
``KeyValueStore`` trait (get/scan/put/apply_txn/lock/watch/delete,
reference ballista/scheduler/src/cluster/storage/mod.rs:30-147) with sled
(embedded) and etcd drivers (cluster/storage/sled.rs:34-395,
etcd.rs:37-346), and implements ClusterState/JobState over it
(cluster/kv.rs:63-110).  That's what makes the scheduler HA: two
schedulers share executor slots atomically and adopt each other's jobs.

Here the trait is ``KeyValueStore`` with two embedded drivers:

- ``MemoryKv`` — in-process (tests, standalone mode; sled's try_new_temporary
  analog);
- ``SqliteKv`` — file-backed, **multi-process safe**: transactions run as
  ``BEGIN IMMEDIATE`` so concurrent schedulers on a shared filesystem get
  real atomicity (the embedded-store role sled plays for the reference).

``KvJobStateBackend`` (job checkpoints + ownership locks) and
``KvClusterState`` (executors, heartbeats, atomic slot reservations) build
on the trait, so every backend gains HA semantics through one conformance
suite (tests/test_kv.py; reference cluster/test/mod.rs:218-446).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import faults, serde
from .execution_graph import ExecutionGraph
from .types import JobLease


# --------------------------------------------------------------------------
# the trait
# --------------------------------------------------------------------------


class TxnGuardFailed(Exception):
    """A transaction's compare guard did not hold; nothing was applied."""


class KeyValueStore:
    """Keyspace'd KV with atomic transactions and owner locks.

    Keys are (keyspace, key) string pairs.  ``txn`` applies a batch of
    put/delete ops atomically, optionally guarded by compare conditions
    (key must currently equal an expected value, None = absent)."""

    def get(self, space: str, key: str) -> Optional[str]:
        raise NotImplementedError

    def scan(self, space: str) -> List[Tuple[str, str]]:
        raise NotImplementedError

    def put(self, space: str, key: str, value: str) -> None:
        self.txn([("put", space, key, value)])

    def delete(self, space: str, key: str) -> None:
        self.txn([("del", space, key, None)])

    def txn(self, ops: List[Tuple[str, str, str, Optional[str]]],
            guards: Optional[List[Tuple[str, str, Optional[str]]]] = None) -> None:
        """ops: ('put'|'del', space, key, value).  guards: (space, key,
        expected_value_or_None).  Raises TxnGuardFailed when a guard fails."""
        raise NotImplementedError

    def lock(self, space: str, key: str, owner: str, ttl_s: float) -> bool:
        """Acquire an owner lock with a TTL lease.  Re-acquire by the same
        owner refreshes the lease.  Expired locks are taken over atomically
        (exactly one contender wins)."""
        now = time.time()
        val = self.get(space, key)
        holder = json.loads(val) if val else None
        if holder is not None and holder.get("owner") != owner \
                and now - holder.get("ts", 0) <= ttl_s:
            return False
        new = json.dumps({"owner": owner, "ts": now})
        try:
            self.txn([("put", space, key, new)], guards=[(space, key, val)])
            return True
        except TxnGuardFailed:
            return False

    def unlock(self, space: str, key: str, owner: str) -> None:
        val = self.get(space, key)
        if val and json.loads(val).get("owner") == owner:
            try:
                self.txn([("del", space, key, None)], guards=[(space, key, val)])
            except TxnGuardFailed:
                pass

    def watch(self, space: str, poll_interval_s: float = 0.2) -> "Watch":
        """Subscribe to changes in a keyspace (reference KeyValueStore::watch,
        storage/mod.rs:30-147 — etcd watch streams; sled subscriber).  The
        base implementation polls scan() and diffs snapshots, which works for
        ANY driver including multi-process sqlite; push-capable drivers
        (MemoryKv, RemoteKv) override with real event streams."""
        return _PollingWatch(self, space, poll_interval_s)

    def close(self) -> None:
        pass


class WatchEvent:
    __slots__ = ("op", "space", "key", "value")

    def __init__(self, op: str, space: str, key: str, value: Optional[str]):
        # 'put' | 'del' | 'resync' ('resync' = the stream lost history:
        # consumers mirroring the keyspace must clear their mirror; a full
        # snapshot follows as puts)
        self.op = op
        self.space = space
        self.key = key
        self.value = value

    def __repr__(self):
        return f"WatchEvent({self.op}, {self.space}/{self.key})"


class Watch:
    """Event stream handle.  ``get(timeout)`` returns the next WatchEvent or
    None on timeout; iterate for a blocking stream; ``close()`` releases."""

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __iter__(self):
        while True:
            ev = self.get(timeout=None)
            if ev is None:
                return
            yield ev


class _QueueWatch(Watch):
    def __init__(self, on_close=None):
        import queue

        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._closed = False
        self._close_started = False
        self._on_close = on_close

    def _push(self, ev: Optional[WatchEvent]) -> None:
        self._q.put(ev)

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        import queue

        if self._closed:
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is None:
            self._closed = True
        return ev

    def close(self) -> None:
        if not self._close_started:
            self._close_started = True
            # sentinel (get() flips _closed when it sees None): a consumer
            # blocked in get(timeout=None) / `for ev in watch` must wake up
            # and terminate; queued events before the sentinel still drain
            self._q.put(None)
            if self._on_close is not None:
                self._on_close(self)


class _PollingWatch(_QueueWatch):
    """Snapshot-diff poller: the watch fallback that works across processes
    (sqlite on a shared filesystem has no push channel)."""

    def __init__(self, store: KeyValueStore, space: str, interval_s: float):
        super().__init__()
        self._stop = threading.Event()
        self._snapshot = dict(store.scan(space))

        def run():
            while not self._stop.wait(interval_s):
                try:
                    now = dict(store.scan(space))
                # routine on shutdown: the store closes under the watcher
                # ballista: allow=recovery-path-logging — watcher exits here
                except Exception:  # noqa: BLE001 — store closing
                    break
                for k, v in now.items():
                    old = self._snapshot.get(k)
                    if old is None or old != v:
                        self._push(WatchEvent("put", space, k, v))
                for k in self._snapshot:
                    if k not in now:
                        self._push(WatchEvent("del", space, k, None))
                self._snapshot = now

        self._thread = threading.Thread(target=run, name=f"kv-watch-{space}",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        super().close()


class MemoryKv(KeyValueStore):
    def __init__(self):
        self._data: Dict[Tuple[str, str], str] = {}
        self._lock = threading.RLock()
        self._watchers: Dict[str, List[_QueueWatch]] = {}

    def get(self, space, key):
        with self._lock:
            return self._data.get((space, key))

    def scan(self, space):
        with self._lock:
            return sorted((k, v) for (s, k), v in self._data.items() if s == space)

    def txn(self, ops, guards=None):
        with self._lock:
            for space, key, expected in guards or []:
                if self._data.get((space, key)) != expected:
                    raise TxnGuardFailed(f"{space}/{key}")
            for op, space, key, value in ops:
                if op == "put":
                    self._data[(space, key)] = value
                else:
                    self._data.pop((space, key), None)
                # deliver under the lock: queue puts never block, and
                # delivering outside would let two racing txns enqueue their
                # events in the opposite order of their commits (a watcher
                # mirroring state would diverge permanently)
                for w in self._watchers.get(space, ()):
                    w._push(WatchEvent(
                        "put" if op == "put" else "del", space, key, value))

    def watch(self, space, poll_interval_s: float = 0.2):
        def on_close(w):
            with self._lock:
                lst = self._watchers.get(space, [])
                if w in lst:
                    lst.remove(w)

        w = _QueueWatch(on_close)
        with self._lock:
            self._watchers.setdefault(space, []).append(w)
        return w


class SqliteKv(KeyValueStore):
    """File-backed store safe across processes (WAL + BEGIN IMMEDIATE)."""

    def __init__(self, path: str):
        import sqlite3

        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self.path = path
        self._local = threading.local()
        self._sqlite3 = sqlite3
        conn = self._conn()
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("CREATE TABLE IF NOT EXISTS kv ("
                     "space TEXT NOT NULL, key TEXT NOT NULL, value TEXT, "
                     "PRIMARY KEY (space, key))")
        conn.commit()

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._sqlite3.connect(self.path, timeout=30.0,
                                         isolation_level=None)
            conn.execute("PRAGMA busy_timeout=30000")
            self._local.conn = conn
        return conn

    def get(self, space, key):
        cur = self._conn().execute(
            "SELECT value FROM kv WHERE space=? AND key=?", (space, key))
        row = cur.fetchone()
        return row[0] if row else None

    def scan(self, space):
        cur = self._conn().execute(
            "SELECT key, value FROM kv WHERE space=? ORDER BY key", (space,))
        return list(cur.fetchall())

    def txn(self, ops, guards=None):
        conn = self._conn()
        conn.execute("BEGIN IMMEDIATE")  # write lock: guards+ops are atomic
        try:
            for space, key, expected in guards or []:
                cur = conn.execute(
                    "SELECT value FROM kv WHERE space=? AND key=?", (space, key))
                row = cur.fetchone()
                current = row[0] if row else None
                if current != expected:
                    raise TxnGuardFailed(f"{space}/{key}")
            for op, space, key, value in ops:
                if op == "put":
                    conn.execute(
                        "INSERT INTO kv (space, key, value) VALUES (?,?,?) "
                        "ON CONFLICT (space, key) DO UPDATE SET value=excluded.value",
                        (space, key, value))
                else:
                    conn.execute("DELETE FROM kv WHERE space=? AND key=?",
                                 (space, key))
            conn.execute("COMMIT")
        except BaseException:
            conn.execute("ROLLBACK")
            raise

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def open_store(url: str) -> KeyValueStore:
    """Backend factory (reference BallistaCluster::new_from_config,
    cluster/mod.rs:76-192): 'memory://', 'sqlite:///path/state.db', or a
    bare filesystem path (sqlite)."""
    if url == "memory://" or url == "memory":
        return MemoryKv()
    if url.startswith("sqlite://"):
        return SqliteKv(url[len("sqlite://"):].lstrip("/") if url.startswith("sqlite:///")
                        else url[len("sqlite://"):])
    return SqliteKv(url)


# --------------------------------------------------------------------------
# JobState over the trait
# --------------------------------------------------------------------------

JOBS = "jobs"
JOB_LOCKS = "job_locks"
EXECUTORS = "executors"
HEARTBEATS = "heartbeats"
SLOTS = "slots"
SESSIONS = "sessions"
SCHEDULERS = "schedulers"  # shard registry: scheduler_id -> endpoint + sample


class LeaseLost(Exception):
    """A fenced job write was refused: the writer no longer holds the
    job's lease at the epoch it claims (another shard adopted the job).
    The only correct reaction is to stop driving the job locally — the
    adopter owns it now."""


class KvJobStateBackend:
    """Drop-in for FileJobStateBackend over any KeyValueStore (reference
    KeyValueState's JobState half, cluster/kv.rs save_job/get_job +
    try_acquire_job, cluster/mod.rs:347-350), extended with epoch-fenced
    TTL leases so a fleet of schedulers can fail over without a
    partitioned ex-owner double-driving a job."""

    def __init__(self, store: KeyValueStore, lease_ttl_s: float = 15.0):
        self.store = store
        self.lease_ttl_s = lease_ttl_s

    def save_job(self, graph: ExecutionGraph, owner: Optional[str] = None,
                 epoch: Optional[int] = None) -> None:
        """Persist a graph checkpoint.  With ``owner``/``epoch`` the write
        is fenced: it only applies while that lease is held at that epoch
        (raises LeaseLost otherwise).  Without them it is a plain put —
        the single-scheduler/recovery path."""
        blob = json.dumps(serde.graph_to_obj(graph), separators=(",", ":"))
        if owner is None:
            self.store.put(JOBS, graph.job_id, blob)
            return
        self.fenced_txn(graph.job_id, owner, epoch or 0,
                        [("put", JOBS, graph.job_id, blob)], op="save_job")

    def load_job(self, job_id: str) -> Optional[ExecutionGraph]:
        val = self.store.get(JOBS, job_id)
        return serde.graph_from_obj(json.loads(val)) if val else None

    def list_jobs(self) -> List[str]:
        return [k for k, _ in self.store.scan(JOBS)]

    def remove_job(self, job_id: str) -> None:
        self.store.txn([("del", JOBS, job_id, None),
                        ("del", JOB_LOCKS, job_id, None)])

    def try_acquire_job(self, job_id: str, owner: str,
                        stale_after_s: float = 60.0) -> bool:
        return self.acquire_lease(job_id, owner,
                                  ttl_s=stale_after_s) is not None

    def renew_lock(self, job_id: str, owner: str) -> None:
        lease = self.get_lease(job_id)
        if lease is None:
            self.acquire_lease(job_id, owner)
        elif lease.owner == owner:
            self.renew_lease(job_id, owner, lease.epoch)

    # --- epoch-fenced TTL leases -----------------------------------------
    def _parse_lease(self, job_id: str, val: Optional[str]
                     ) -> Optional[JobLease]:
        if not val:
            return None
        try:
            obj = json.loads(val)
        except ValueError:
            return None
        obj["job_id"] = job_id
        return serde.job_lease_from_obj(obj)

    @staticmethod
    def _lease_value(lease: JobLease) -> str:
        return json.dumps({"owner": lease.owner, "epoch": lease.epoch,
                           "ts": lease.ts, "endpoint": lease.endpoint},
                          separators=(",", ":"))

    def get_lease(self, job_id: str) -> Optional[JobLease]:
        return self._parse_lease(job_id, self.store.get(JOB_LOCKS, job_id))

    def leases(self) -> List[JobLease]:
        out = []
        for job_id, val in self.store.scan(JOB_LOCKS):
            lease = self._parse_lease(job_id, val)
            if lease is not None:
                out.append(lease)
        return out

    def expired_leases(self, ttl_s: Optional[float] = None) -> List[JobLease]:
        ttl = self.lease_ttl_s if ttl_s is None else ttl_s
        now = time.time()
        return [l for l in self.leases() if now - l.ts > ttl]

    def acquire_lease(self, job_id: str, owner: str, endpoint: str = "",
                      ttl_s: Optional[float] = None) -> Optional[JobLease]:
        """Claim (or re-claim) the job's lease via a guarded CAS.  A fresh
        claim or a takeover of an expired lease bumps the epoch — that is
        the fencing token; a same-owner re-acquire keeps it (renewal).
        Returns the held lease, or None while another owner's lease is
        still fresh (or a racer won the CAS)."""
        ttl = self.lease_ttl_s if ttl_s is None else ttl_s
        now = time.time()
        val = self.store.get(JOB_LOCKS, job_id)
        cur = self._parse_lease(job_id, val)
        if cur is not None and cur.owner != owner and now - cur.ts <= ttl:
            return None
        if cur is not None and cur.owner == owner:
            epoch = cur.epoch
            endpoint = endpoint or cur.endpoint
        else:
            epoch = (cur.epoch if cur is not None else 0) + 1
        lease = JobLease(job_id, owner, epoch, now, endpoint)
        try:
            self.store.txn([("put", JOB_LOCKS, job_id,
                             self._lease_value(lease))],
                           guards=[(JOB_LOCKS, job_id, val)])
            return lease
        except TxnGuardFailed:
            return None

    def renew_lease(self, job_id: str, owner: str, epoch: int
                    ) -> Optional[JobLease]:
        """Refresh the lease timestamp iff still held at (owner, epoch).
        Returns the renewed lease, or None when ownership moved — the
        caller must stop driving the job."""
        for _ in range(4):
            val = self.store.get(JOB_LOCKS, job_id)
            cur = self._parse_lease(job_id, val)
            if cur is None or cur.owner != owner or cur.epoch != epoch:
                return None
            lease = JobLease(job_id, owner, epoch, time.time(), cur.endpoint)
            try:
                self.store.txn([("put", JOB_LOCKS, job_id,
                                 self._lease_value(lease))],
                               guards=[(JOB_LOCKS, job_id, val)])
                return lease
            except TxnGuardFailed:
                continue  # racing fenced write/renewal; re-read and retry
        return None

    def release_lease(self, job_id: str, owner: str) -> None:
        val = self.store.get(JOB_LOCKS, job_id)
        cur = self._parse_lease(job_id, val)
        if cur is not None and cur.owner == owner:
            try:
                self.store.txn([("del", JOB_LOCKS, job_id, None)],
                               guards=[(JOB_LOCKS, job_id, val)])
            except TxnGuardFailed:
                pass  # adopted or renewed concurrently; not ours to delete

    def fenced_txn(self, job_id: str, owner: str, epoch: int,
                   ops: List[Tuple[str, str, str, Optional[str]]],
                   op: str = "txn") -> None:
        """Apply ``ops`` atomically, guarded on the job's lease standing at
        (owner, epoch).  The guard covers the whole lease value, so a
        concurrent self-renewal (ts bump) just retries; an owner or epoch
        change raises LeaseLost and nothing is applied."""
        for _ in range(8):
            val = self.store.get(JOB_LOCKS, job_id)
            cur = self._parse_lease(job_id, val)
            if cur is None or cur.owner != owner or cur.epoch != epoch:
                held = (f"{cur.owner}@e{cur.epoch}" if cur is not None
                        else "nobody")
                raise LeaseLost(f"job {job_id} {op}: lease held by {held}, "
                                f"writer is {owner}@e{epoch}")
            faults.inject("scheduler.kv.txn", job_id=job_id, owner=owner,
                          op=op)
            try:
                self.store.txn(list(ops), guards=[(JOB_LOCKS, job_id, val)])
                return
            except TxnGuardFailed:
                continue  # lease value moved under us; re-read and re-check
        raise LeaseLost(f"job {job_id} {op}: lease CAS kept failing for "
                        f"{owner}@e{epoch}")


# --- shard registry (client failover + /api/autoscale aggregation) --------


def publish_scheduler(store: KeyValueStore, scheduler_id: str, endpoint: str,
                      sample: Optional[dict] = None) -> None:
    """Announce a shard's client endpoint (and optionally its latest
    cluster sample) in the shared KV; refreshed from the lease thread so
    freshness doubles as shard liveness."""
    obj = {"scheduler_id": scheduler_id, "endpoint": endpoint,
           "ts": time.time()}
    if sample is not None:
        obj["sample"] = sample
    store.put(SCHEDULERS, scheduler_id, json.dumps(obj, separators=(",", ":")))


def scheduler_registry(store: KeyValueStore, stale_s: float = 30.0
                       ) -> Dict[str, dict]:
    now = time.time()
    out: Dict[str, dict] = {}
    for sid, val in store.scan(SCHEDULERS):
        try:
            obj = json.loads(val)
        except ValueError:
            continue
        if now - obj.get("ts", 0) <= stale_s:
            out[sid] = obj
    return out


def remove_scheduler(store: KeyValueStore, scheduler_id: str) -> None:
    store.delete(SCHEDULERS, scheduler_id)


# --------------------------------------------------------------------------
# ClusterState over the trait (multi-scheduler slot sharing)
# --------------------------------------------------------------------------


class KvClusterState:
    """Executor pool + atomic slot accounting over a shared KV store, so
    N schedulers see one cluster (reference KeyValueState's ClusterState
    half: Keyspace::{Slots, Executors, Heartbeats}, cluster/kv.rs:63-110;
    reservation atomicity stressed by test_fuzz_reservations,
    cluster/test/mod.rs:218-313).

    Matches the in-memory ClusterState surface used by SchedulerServer
    (scheduler/cluster.py)."""

    def __init__(self, store: KeyValueStore, task_distribution: str = "bias"):
        from .cluster import ExecutorHeartbeat, ExecutorMetadata  # noqa: F401

        self.store = store
        self.task_distribution = task_distribution

    # --- executors -------------------------------------------------------
    def register_executor(self, meta) -> None:
        from ..serde import executor_metadata_to_obj

        self.store.txn([
            ("put", EXECUTORS, meta.executor_id,
             json.dumps(executor_metadata_to_obj(meta), separators=(",", ":"))),
            ("put", SLOTS, meta.executor_id, str(meta.task_slots)),
            ("put", HEARTBEATS, meta.executor_id,
             json.dumps({"ts": time.time(), "status": "active"})),
        ])

    def remove_executor(self, executor_id: str) -> None:
        self.store.txn([
            ("del", EXECUTORS, executor_id, None),
            ("del", SLOTS, executor_id, None),
            ("put", HEARTBEATS, executor_id,
             json.dumps({"ts": time.time(), "status": "dead"})),
        ])

    def save_heartbeat(self, hb) -> None:
        row = {"ts": hb.timestamp, "status": hb.status}
        # same omit-when-zero contract as the heartbeat wire format
        if getattr(hb, "memory_pressure", 0.0):
            row["mp"] = hb.memory_pressure
        self.store.put(HEARTBEATS, hb.executor_id,
                       json.dumps(row))

    def touch_heartbeat(self, executor_id: str) -> None:
        """Timestamp-only refresh preserving the status (see
        cluster.ClusterState.touch_heartbeat)."""
        val = self.store.get(HEARTBEATS, executor_id)
        prev = json.loads(val) if val else {}
        row = {"ts": time.time(), "status": prev.get("status", "active")}
        if prev.get("mp"):
            row["mp"] = prev["mp"]
        self.store.put(HEARTBEATS, executor_id, json.dumps(row))

    def memory_pressure(self, executor_id: str) -> float:
        val = self.store.get(HEARTBEATS, executor_id)
        return float(json.loads(val).get("mp", 0.0)) if val else 0.0

    def min_alive_pressure(self, timeout_s: float = 60.0) -> float:
        """Fleet-wide memory-pressure floor over alive executors (see
        cluster.ClusterState.min_alive_pressure)."""
        now = time.time()
        known = {k for k, _ in self.store.scan(EXECUTORS)}
        floor = None
        for eid, v in self.store.scan(HEARTBEATS):
            hb = json.loads(v)
            if eid in known and hb["status"] == "active" \
                    and now - hb["ts"] <= timeout_s:
                p = float(hb.get("mp", 0.0))
                floor = p if floor is None else min(floor, p)
        return floor or 0.0

    def executors(self):
        from ..serde import executor_metadata_from_obj

        return [executor_metadata_from_obj(json.loads(v))
                for _, v in self.store.scan(EXECUTORS)]

    def total_slots(self) -> int:
        """Registered capacity (free + occupied) — the slot-share
        denominator (see cluster.ClusterState.total_slots)."""
        return sum(m.task_slots for m in self.executors())

    def get_executor(self, executor_id: str):
        from ..serde import executor_metadata_from_obj

        val = self.store.get(EXECUTORS, executor_id)
        return executor_metadata_from_obj(json.loads(val)) if val else None

    def alive_executors(self, timeout_s: float = 60.0) -> List[str]:
        now = time.time()
        known = {k for k, _ in self.store.scan(EXECUTORS)}
        out = []
        for eid, v in self.store.scan(HEARTBEATS):
            hb = json.loads(v)
            if eid in known and hb["status"] == "active" \
                    and now - hb["ts"] <= timeout_s:
                out.append(eid)
        return out

    def expired_executors(self, timeout_s: float = 180.0) -> List[str]:
        now = time.time()
        known = {k for k, _ in self.store.scan(EXECUTORS)}
        out = []
        for eid, v in self.store.scan(HEARTBEATS):
            hb = json.loads(v)
            if eid in known and (hb["status"] == "dead"
                                 or now - hb["ts"] > timeout_s):
                out.append(eid)
        return out

    # --- slots -----------------------------------------------------------
    def reserve_slots(self, n: int, executors: Optional[List[str]] = None):
        """Atomic multi-executor slot grab: read free counts, then commit
        the decrements guarded on every read value — a concurrent reserver
        forces a retry, so no slot is ever double-booked (reference
        reserve_slots txn, cluster/kv.rs + storage/mod.rs apply_txn)."""
        from .types import ExecutorReservation

        # heartbeated memory pressure degrades the pick order the same way
        # the in-memory ClusterState does (bucketed to dampen jitter)
        mp = {eid: round(float(json.loads(v).get("mp", 0.0)), 1)
              for eid, v in self.store.scan(HEARTBEATS)}
        for _ in range(16):  # optimistic retries under contention
            snapshot = {k: v for k, v in self.store.scan(SLOTS)}
            if executors is not None:
                snapshot = {k: v for k, v in snapshot.items() if k in executors}
            order = sorted(snapshot,
                           key=lambda k: (mp.get(k, 0.0), -int(snapshot[k]))) \
                if self.task_distribution == "bias" \
                else sorted(snapshot, key=lambda k: (mp.get(k, 0.0), k))
            picks: List[str] = []
            remaining = n
            if self.task_distribution == "bias":
                for eid in order:
                    take = min(int(snapshot[eid]), remaining)
                    picks.extend([eid] * take)
                    remaining -= take
                    if remaining == 0:
                        break
            else:  # round robin
                free = {k: int(v) for k, v in snapshot.items()}
                while remaining > 0:
                    progressed = False
                    for eid in order:
                        if remaining == 0:
                            break
                        if free.get(eid, 0) > 0:
                            free[eid] -= 1
                            picks.append(eid)
                            remaining -= 1
                            progressed = True
                    if not progressed:
                        break
            if not picks:
                return []
            taken: Dict[str, int] = {}
            for eid in picks:
                taken[eid] = taken.get(eid, 0) + 1
            try:
                self.store.txn(
                    [("put", SLOTS, eid, str(int(snapshot[eid]) - c))
                     for eid, c in taken.items()],
                    guards=[(SLOTS, eid, snapshot[eid]) for eid in taken],
                )
                return [ExecutorReservation(eid) for eid in picks]
            except TxnGuardFailed:
                continue  # raced another scheduler; re-read and retry
        return []

    def cancel_reservations(self, reservations) -> None:
        counts: Dict[str, int] = {}
        for r in reservations:
            counts[r.executor_id] = counts.get(r.executor_id, 0) + 1
        self.free_slots_many(counts)

    def free_slots(self, executor_id: str, n: int) -> None:
        if n > 0:
            self.free_slots_many({executor_id: n})

    def free_slots_many(self, counts: Dict[str, int]) -> None:
        # must NOT give up: an abandoned free leaks slots forever (observed
        # under RPC-latency contention with a bounded retry count).  Guard
        # failures are transient by construction — some other reserver/freer
        # committed first — so retry with jitter until it lands.
        import random as _random

        attempt = 0
        while True:
            guards, ops = [], []
            for eid, c in counts.items():
                cur = self.store.get(SLOTS, eid)
                if cur is None:
                    continue  # executor gone
                meta = self.get_executor(eid)
                cap = meta.task_slots if meta else int(cur) + c
                guards.append((SLOTS, eid, cur))
                ops.append(("put", SLOTS, eid, str(min(int(cur) + c, cap))))
            if not ops:
                return
            try:
                self.store.txn(ops, guards=guards)
                return
            except TxnGuardFailed:
                attempt += 1
                time.sleep(min(0.05, 0.001 * attempt) * _random.random())

    def available_slots(self) -> int:
        return sum(int(v) for _, v in self.store.scan(SLOTS))

    def total_available(self) -> int:
        """Free slots fleet-wide (cluster.ClusterState surface — the
        utilization numerator in cluster_sample/autoscale_signal)."""
        return self.available_slots()
