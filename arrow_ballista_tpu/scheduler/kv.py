"""Pluggable cluster state: a keyspace'd KV store with transactions + locks.

Parity: the reference makes scheduler state pluggable over a
``KeyValueStore`` trait (get/scan/put/apply_txn/lock/watch/delete,
reference ballista/scheduler/src/cluster/storage/mod.rs:30-147) with sled
(embedded) and etcd drivers (cluster/storage/sled.rs:34-395,
etcd.rs:37-346), and implements ClusterState/JobState over it
(cluster/kv.rs:63-110).  That's what makes the scheduler HA: two
schedulers share executor slots atomically and adopt each other's jobs.

Here the trait is ``KeyValueStore`` with two embedded drivers:

- ``MemoryKv`` — in-process (tests, standalone mode; sled's try_new_temporary
  analog);
- ``SqliteKv`` — file-backed, **multi-process safe**: transactions run as
  ``BEGIN IMMEDIATE`` so concurrent schedulers on a shared filesystem get
  real atomicity (the embedded-store role sled plays for the reference).

``KvJobStateBackend`` (job checkpoints + ownership locks) and
``KvClusterState`` (executors, heartbeats, atomic slot reservations) build
on the trait, so every backend gains HA semantics through one conformance
suite (tests/test_kv.py; reference cluster/test/mod.rs:218-446).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import serde
from .execution_graph import ExecutionGraph


# --------------------------------------------------------------------------
# the trait
# --------------------------------------------------------------------------


class TxnGuardFailed(Exception):
    """A transaction's compare guard did not hold; nothing was applied."""


class KeyValueStore:
    """Keyspace'd KV with atomic transactions and owner locks.

    Keys are (keyspace, key) string pairs.  ``txn`` applies a batch of
    put/delete ops atomically, optionally guarded by compare conditions
    (key must currently equal an expected value, None = absent)."""

    def get(self, space: str, key: str) -> Optional[str]:
        raise NotImplementedError

    def scan(self, space: str) -> List[Tuple[str, str]]:
        raise NotImplementedError

    def put(self, space: str, key: str, value: str) -> None:
        self.txn([("put", space, key, value)])

    def delete(self, space: str, key: str) -> None:
        self.txn([("del", space, key, None)])

    def txn(self, ops: List[Tuple[str, str, str, Optional[str]]],
            guards: Optional[List[Tuple[str, str, Optional[str]]]] = None) -> None:
        """ops: ('put'|'del', space, key, value).  guards: (space, key,
        expected_value_or_None).  Raises TxnGuardFailed when a guard fails."""
        raise NotImplementedError

    def lock(self, space: str, key: str, owner: str, ttl_s: float) -> bool:
        """Acquire an owner lock with a TTL lease.  Re-acquire by the same
        owner refreshes the lease.  Expired locks are taken over atomically
        (exactly one contender wins)."""
        now = time.time()
        val = self.get(space, key)
        holder = json.loads(val) if val else None
        if holder is not None and holder.get("owner") != owner \
                and now - holder.get("ts", 0) <= ttl_s:
            return False
        new = json.dumps({"owner": owner, "ts": now})
        try:
            self.txn([("put", space, key, new)], guards=[(space, key, val)])
            return True
        except TxnGuardFailed:
            return False

    def unlock(self, space: str, key: str, owner: str) -> None:
        val = self.get(space, key)
        if val and json.loads(val).get("owner") == owner:
            try:
                self.txn([("del", space, key, None)], guards=[(space, key, val)])
            except TxnGuardFailed:
                pass

    def watch(self, space: str, poll_interval_s: float = 0.2) -> "Watch":
        """Subscribe to changes in a keyspace (reference KeyValueStore::watch,
        storage/mod.rs:30-147 — etcd watch streams; sled subscriber).  The
        base implementation polls scan() and diffs snapshots, which works for
        ANY driver including multi-process sqlite; push-capable drivers
        (MemoryKv, RemoteKv) override with real event streams."""
        return _PollingWatch(self, space, poll_interval_s)

    def close(self) -> None:
        pass


class WatchEvent:
    __slots__ = ("op", "space", "key", "value")

    def __init__(self, op: str, space: str, key: str, value: Optional[str]):
        # 'put' | 'del' | 'resync' ('resync' = the stream lost history:
        # consumers mirroring the keyspace must clear their mirror; a full
        # snapshot follows as puts)
        self.op = op
        self.space = space
        self.key = key
        self.value = value

    def __repr__(self):
        return f"WatchEvent({self.op}, {self.space}/{self.key})"


class Watch:
    """Event stream handle.  ``get(timeout)`` returns the next WatchEvent or
    None on timeout; iterate for a blocking stream; ``close()`` releases."""

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __iter__(self):
        while True:
            ev = self.get(timeout=None)
            if ev is None:
                return
            yield ev


class _QueueWatch(Watch):
    def __init__(self, on_close=None):
        import queue

        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self._closed = False
        self._close_started = False
        self._on_close = on_close

    def _push(self, ev: Optional[WatchEvent]) -> None:
        self._q.put(ev)

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        import queue

        if self._closed:
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if ev is None:
            self._closed = True
        return ev

    def close(self) -> None:
        if not self._close_started:
            self._close_started = True
            # sentinel (get() flips _closed when it sees None): a consumer
            # blocked in get(timeout=None) / `for ev in watch` must wake up
            # and terminate; queued events before the sentinel still drain
            self._q.put(None)
            if self._on_close is not None:
                self._on_close(self)


class _PollingWatch(_QueueWatch):
    """Snapshot-diff poller: the watch fallback that works across processes
    (sqlite on a shared filesystem has no push channel)."""

    def __init__(self, store: KeyValueStore, space: str, interval_s: float):
        super().__init__()
        self._stop = threading.Event()
        self._snapshot = dict(store.scan(space))

        def run():
            while not self._stop.wait(interval_s):
                try:
                    now = dict(store.scan(space))
                # routine on shutdown: the store closes under the watcher
                # ballista: allow=recovery-path-logging — watcher exits here
                except Exception:  # noqa: BLE001 — store closing
                    break
                for k, v in now.items():
                    old = self._snapshot.get(k)
                    if old is None or old != v:
                        self._push(WatchEvent("put", space, k, v))
                for k in self._snapshot:
                    if k not in now:
                        self._push(WatchEvent("del", space, k, None))
                self._snapshot = now

        self._thread = threading.Thread(target=run, name=f"kv-watch-{space}",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        super().close()


class MemoryKv(KeyValueStore):
    def __init__(self):
        self._data: Dict[Tuple[str, str], str] = {}
        self._lock = threading.RLock()
        self._watchers: Dict[str, List[_QueueWatch]] = {}

    def get(self, space, key):
        with self._lock:
            return self._data.get((space, key))

    def scan(self, space):
        with self._lock:
            return sorted((k, v) for (s, k), v in self._data.items() if s == space)

    def txn(self, ops, guards=None):
        with self._lock:
            for space, key, expected in guards or []:
                if self._data.get((space, key)) != expected:
                    raise TxnGuardFailed(f"{space}/{key}")
            for op, space, key, value in ops:
                if op == "put":
                    self._data[(space, key)] = value
                else:
                    self._data.pop((space, key), None)
                # deliver under the lock: queue puts never block, and
                # delivering outside would let two racing txns enqueue their
                # events in the opposite order of their commits (a watcher
                # mirroring state would diverge permanently)
                for w in self._watchers.get(space, ()):
                    w._push(WatchEvent(
                        "put" if op == "put" else "del", space, key, value))

    def watch(self, space, poll_interval_s: float = 0.2):
        def on_close(w):
            with self._lock:
                lst = self._watchers.get(space, [])
                if w in lst:
                    lst.remove(w)

        w = _QueueWatch(on_close)
        with self._lock:
            self._watchers.setdefault(space, []).append(w)
        return w


class SqliteKv(KeyValueStore):
    """File-backed store safe across processes (WAL + BEGIN IMMEDIATE)."""

    def __init__(self, path: str):
        import sqlite3

        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self.path = path
        self._local = threading.local()
        self._sqlite3 = sqlite3
        conn = self._conn()
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("CREATE TABLE IF NOT EXISTS kv ("
                     "space TEXT NOT NULL, key TEXT NOT NULL, value TEXT, "
                     "PRIMARY KEY (space, key))")
        conn.commit()

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._sqlite3.connect(self.path, timeout=30.0,
                                         isolation_level=None)
            conn.execute("PRAGMA busy_timeout=30000")
            self._local.conn = conn
        return conn

    def get(self, space, key):
        cur = self._conn().execute(
            "SELECT value FROM kv WHERE space=? AND key=?", (space, key))
        row = cur.fetchone()
        return row[0] if row else None

    def scan(self, space):
        cur = self._conn().execute(
            "SELECT key, value FROM kv WHERE space=? ORDER BY key", (space,))
        return list(cur.fetchall())

    def txn(self, ops, guards=None):
        conn = self._conn()
        conn.execute("BEGIN IMMEDIATE")  # write lock: guards+ops are atomic
        try:
            for space, key, expected in guards or []:
                cur = conn.execute(
                    "SELECT value FROM kv WHERE space=? AND key=?", (space, key))
                row = cur.fetchone()
                current = row[0] if row else None
                if current != expected:
                    raise TxnGuardFailed(f"{space}/{key}")
            for op, space, key, value in ops:
                if op == "put":
                    conn.execute(
                        "INSERT INTO kv (space, key, value) VALUES (?,?,?) "
                        "ON CONFLICT (space, key) DO UPDATE SET value=excluded.value",
                        (space, key, value))
                else:
                    conn.execute("DELETE FROM kv WHERE space=? AND key=?",
                                 (space, key))
            conn.execute("COMMIT")
        except BaseException:
            conn.execute("ROLLBACK")
            raise

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def open_store(url: str) -> KeyValueStore:
    """Backend factory (reference BallistaCluster::new_from_config,
    cluster/mod.rs:76-192): 'memory://', 'sqlite:///path/state.db', or a
    bare filesystem path (sqlite)."""
    if url == "memory://" or url == "memory":
        return MemoryKv()
    if url.startswith("sqlite://"):
        return SqliteKv(url[len("sqlite://"):].lstrip("/") if url.startswith("sqlite:///")
                        else url[len("sqlite://"):])
    return SqliteKv(url)


# --------------------------------------------------------------------------
# JobState over the trait
# --------------------------------------------------------------------------

JOBS = "jobs"
JOB_LOCKS = "job_locks"
EXECUTORS = "executors"
HEARTBEATS = "heartbeats"
SLOTS = "slots"
SESSIONS = "sessions"


class KvJobStateBackend:
    """Drop-in for FileJobStateBackend over any KeyValueStore (reference
    KeyValueState's JobState half, cluster/kv.rs save_job/get_job +
    try_acquire_job, cluster/mod.rs:347-350)."""

    def __init__(self, store: KeyValueStore):
        self.store = store

    def save_job(self, graph: ExecutionGraph) -> None:
        self.store.put(JOBS, graph.job_id,
                       json.dumps(serde.graph_to_obj(graph),
                                  separators=(",", ":")))

    def load_job(self, job_id: str) -> Optional[ExecutionGraph]:
        val = self.store.get(JOBS, job_id)
        return serde.graph_from_obj(json.loads(val)) if val else None

    def list_jobs(self) -> List[str]:
        return [k for k, _ in self.store.scan(JOBS)]

    def remove_job(self, job_id: str) -> None:
        self.store.txn([("del", JOBS, job_id, None),
                        ("del", JOB_LOCKS, job_id, None)])

    def try_acquire_job(self, job_id: str, owner: str,
                        stale_after_s: float = 60.0) -> bool:
        return self.store.lock(JOB_LOCKS, job_id, owner, stale_after_s)

    def renew_lock(self, job_id: str, owner: str) -> None:
        self.store.lock(JOB_LOCKS, job_id, owner, ttl_s=0x7FFFFFFF)


# --------------------------------------------------------------------------
# ClusterState over the trait (multi-scheduler slot sharing)
# --------------------------------------------------------------------------


class KvClusterState:
    """Executor pool + atomic slot accounting over a shared KV store, so
    N schedulers see one cluster (reference KeyValueState's ClusterState
    half: Keyspace::{Slots, Executors, Heartbeats}, cluster/kv.rs:63-110;
    reservation atomicity stressed by test_fuzz_reservations,
    cluster/test/mod.rs:218-313).

    Matches the in-memory ClusterState surface used by SchedulerServer
    (scheduler/cluster.py)."""

    def __init__(self, store: KeyValueStore, task_distribution: str = "bias"):
        from .cluster import ExecutorHeartbeat, ExecutorMetadata  # noqa: F401

        self.store = store
        self.task_distribution = task_distribution

    # --- executors -------------------------------------------------------
    def register_executor(self, meta) -> None:
        from ..serde import executor_metadata_to_obj

        self.store.txn([
            ("put", EXECUTORS, meta.executor_id,
             json.dumps(executor_metadata_to_obj(meta), separators=(",", ":"))),
            ("put", SLOTS, meta.executor_id, str(meta.task_slots)),
            ("put", HEARTBEATS, meta.executor_id,
             json.dumps({"ts": time.time(), "status": "active"})),
        ])

    def remove_executor(self, executor_id: str) -> None:
        self.store.txn([
            ("del", EXECUTORS, executor_id, None),
            ("del", SLOTS, executor_id, None),
            ("put", HEARTBEATS, executor_id,
             json.dumps({"ts": time.time(), "status": "dead"})),
        ])

    def save_heartbeat(self, hb) -> None:
        self.store.put(HEARTBEATS, hb.executor_id,
                       json.dumps({"ts": hb.timestamp, "status": hb.status}))

    def touch_heartbeat(self, executor_id: str) -> None:
        """Timestamp-only refresh preserving the status (see
        cluster.ClusterState.touch_heartbeat)."""
        val = self.store.get(HEARTBEATS, executor_id)
        status = json.loads(val)["status"] if val else "active"
        self.store.put(HEARTBEATS, executor_id,
                       json.dumps({"ts": time.time(), "status": status}))

    def executors(self):
        from ..serde import executor_metadata_from_obj

        return [executor_metadata_from_obj(json.loads(v))
                for _, v in self.store.scan(EXECUTORS)]

    def total_slots(self) -> int:
        """Registered capacity (free + occupied) — the slot-share
        denominator (see cluster.ClusterState.total_slots)."""
        return sum(m.task_slots for m in self.executors())

    def get_executor(self, executor_id: str):
        from ..serde import executor_metadata_from_obj

        val = self.store.get(EXECUTORS, executor_id)
        return executor_metadata_from_obj(json.loads(val)) if val else None

    def alive_executors(self, timeout_s: float = 60.0) -> List[str]:
        now = time.time()
        known = {k for k, _ in self.store.scan(EXECUTORS)}
        out = []
        for eid, v in self.store.scan(HEARTBEATS):
            hb = json.loads(v)
            if eid in known and hb["status"] == "active" \
                    and now - hb["ts"] <= timeout_s:
                out.append(eid)
        return out

    def expired_executors(self, timeout_s: float = 180.0) -> List[str]:
        now = time.time()
        known = {k for k, _ in self.store.scan(EXECUTORS)}
        out = []
        for eid, v in self.store.scan(HEARTBEATS):
            hb = json.loads(v)
            if eid in known and (hb["status"] == "dead"
                                 or now - hb["ts"] > timeout_s):
                out.append(eid)
        return out

    # --- slots -----------------------------------------------------------
    def reserve_slots(self, n: int, executors: Optional[List[str]] = None):
        """Atomic multi-executor slot grab: read free counts, then commit
        the decrements guarded on every read value — a concurrent reserver
        forces a retry, so no slot is ever double-booked (reference
        reserve_slots txn, cluster/kv.rs + storage/mod.rs apply_txn)."""
        from .types import ExecutorReservation

        for _ in range(16):  # optimistic retries under contention
            snapshot = {k: v for k, v in self.store.scan(SLOTS)}
            if executors is not None:
                snapshot = {k: v for k, v in snapshot.items() if k in executors}
            order = sorted(snapshot, key=lambda k: -int(snapshot[k])) \
                if self.task_distribution == "bias" else sorted(snapshot)
            picks: List[str] = []
            remaining = n
            if self.task_distribution == "bias":
                for eid in order:
                    take = min(int(snapshot[eid]), remaining)
                    picks.extend([eid] * take)
                    remaining -= take
                    if remaining == 0:
                        break
            else:  # round robin
                free = {k: int(v) for k, v in snapshot.items()}
                while remaining > 0:
                    progressed = False
                    for eid in order:
                        if remaining == 0:
                            break
                        if free.get(eid, 0) > 0:
                            free[eid] -= 1
                            picks.append(eid)
                            remaining -= 1
                            progressed = True
                    if not progressed:
                        break
            if not picks:
                return []
            taken: Dict[str, int] = {}
            for eid in picks:
                taken[eid] = taken.get(eid, 0) + 1
            try:
                self.store.txn(
                    [("put", SLOTS, eid, str(int(snapshot[eid]) - c))
                     for eid, c in taken.items()],
                    guards=[(SLOTS, eid, snapshot[eid]) for eid in taken],
                )
                return [ExecutorReservation(eid) for eid in picks]
            except TxnGuardFailed:
                continue  # raced another scheduler; re-read and retry
        return []

    def cancel_reservations(self, reservations) -> None:
        counts: Dict[str, int] = {}
        for r in reservations:
            counts[r.executor_id] = counts.get(r.executor_id, 0) + 1
        self.free_slots_many(counts)

    def free_slots(self, executor_id: str, n: int) -> None:
        if n > 0:
            self.free_slots_many({executor_id: n})

    def free_slots_many(self, counts: Dict[str, int]) -> None:
        # must NOT give up: an abandoned free leaks slots forever (observed
        # under RPC-latency contention with a bounded retry count).  Guard
        # failures are transient by construction — some other reserver/freer
        # committed first — so retry with jitter until it lands.
        import random as _random

        attempt = 0
        while True:
            guards, ops = [], []
            for eid, c in counts.items():
                cur = self.store.get(SLOTS, eid)
                if cur is None:
                    continue  # executor gone
                meta = self.get_executor(eid)
                cap = meta.task_slots if meta else int(cur) + c
                guards.append((SLOTS, eid, cur))
                ops.append(("put", SLOTS, eid, str(min(int(cur) + c, cap))))
            if not ops:
                return
            try:
                self.store.txn(ops, guards=guards)
                return
            except TxnGuardFailed:
                attempt += 1
                time.sleep(min(0.05, 0.001 * attempt) * _random.random())

    def available_slots(self) -> int:
        return sum(int(v) for _, v in self.store.scan(SLOTS))
