"""Scheduler web UI: one static page over the REST API.

Parity: the reference ships a React app (Summary, ExecutorsList,
QueriesList with progress bars, JobStagesMetrics — reference
ballista/ui/src/components/*.tsx) talking to the same /api endpoints.
This is the dependency-free rendition: vanilla JS polling /api/state,
/api/executors, /api/jobs and /api/job/<id>/stages, served by RestApi at
``/`` — no build step, works from any daemon.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>Ballista-TPU Scheduler</title>
<style>
  :root { color-scheme: light; }
  body { font: 14px/1.45 system-ui, sans-serif; margin: 0; background:#f5f6f8; color:#1b1f24; }
  header { background:#1b3a5c; color:#fff; padding:14px 22px; display:flex; align-items:baseline; gap:14px; }
  header h1 { font-size:17px; margin:0; font-weight:600; }
  header .sub { opacity:.75; font-size:12px; }
  main { max-width:1060px; margin:18px auto; padding:0 16px; }
  .cards { display:flex; gap:12px; flex-wrap:wrap; margin-bottom:18px; }
  .card { background:#fff; border:1px solid #dde1e6; border-radius:8px; padding:12px 18px; min-width:130px; }
  .card .v { font-size:24px; font-weight:650; }
  .card .k { color:#57606a; font-size:12px; }
  section { background:#fff; border:1px solid #dde1e6; border-radius:8px; margin-bottom:18px; overflow:hidden; }
  section h2 { font-size:13px; letter-spacing:.04em; text-transform:uppercase; color:#57606a;
               margin:0; padding:10px 16px; border-bottom:1px solid #eceff2; }
  table { width:100%; border-collapse:collapse; }
  th, td { text-align:left; padding:7px 16px; border-bottom:1px solid #f0f2f4; font-size:13px; }
  th { color:#57606a; font-weight:600; background:#fafbfc; }
  tr:last-child td { border-bottom:none; }
  .bar { background:#e8ebee; border-radius:4px; height:8px; width:140px; display:inline-block; vertical-align:middle; }
  .bar i { display:block; height:100%; border-radius:4px; background:#2da44e; }
  .state { padding:1px 8px; border-radius:10px; font-size:12px; }
  .state.running { background:#dbeafe; color:#1d4ed8; }
  .state.successful { background:#dcfce7; color:#15803d; }
  .state.failed { background:#fee2e2; color:#b91c1c; }
  .state.cancelled { background:#f3f4f6; color:#4b5563; }
  .state.active { background:#dcfce7; color:#15803d; }
  .state.terminating, .state.unknown { background:#fef3c7; color:#92400e; }
  tr.job { cursor:pointer; }
  pre { margin:4px 0 10px; padding:8px 12px; background:#f6f8fa; border-radius:6px;
        font-size:11px; overflow-x:auto; }
  td.stages-cell { background:#fbfcfd; }
  .err { color:#b91c1c; font-size:12px; }
</style>
</head>
<body>
<header><h1>Ballista-TPU Scheduler</h1><span class="sub" id="refreshed"></span></header>
<main>
  <div class="cards" id="cards"></div>
  <section><h2>Executors</h2>
    <table><thead><tr><th>ID</th><th>Host</th><th>Data port</th><th>Slots</th>
      <th>Status</th><th>Last seen</th></tr></thead><tbody id="executors"></tbody></table>
  </section>
  <section><h2>Jobs</h2>
    <table><thead><tr><th>Job</th><th>State</th><th>Stages</th><th>Progress</th>
      <th>Tasks</th><th>Error</th></tr></thead><tbody id="jobs"></tbody></table>
  </section>
</main>
<script>
const open_ = new Set();
async function j(url) { const r = await fetch(url); return r.json(); }
function esc(s) { return String(s ?? "").replace(/[&<>]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c])); }
async function refresh() {
  try {
    const [state, execs, jobs] = await Promise.all([
      j("/api/state"), j("/api/executors"), j("/api/jobs")]);
    document.getElementById("cards").innerHTML = [
      ["Executors alive", state.alive_executors + " / " + state.executors],
      ["Free task slots", state.available_task_slots],
      ["Pending tasks", state.pending_tasks],
      ["Jobs", jobs.length],
    ].map(([k, v]) => `<div class="card"><div class="v">${esc(v)}</div>` +
                      `<div class="k">${esc(k)}</div></div>`).join("");
    document.getElementById("executors").innerHTML = execs.map(e =>
      `<tr><td>${esc(e.executor_id)}</td><td>${esc(e.host)}</td>` +
      `<td>${esc(e.port)}</td><td>${esc(e.task_slots)}</td>` +
      `<td><span class="state ${esc(e.status)}">${esc(e.status)}</span></td>` +
      `<td>${e.last_seen_s_ago == null ? "-" : esc(e.last_seen_s_ago) + "s ago"}</td></tr>`
    ).join("") || `<tr><td colspan="6">none registered</td></tr>`;
    const rows = [];
    for (const job of jobs) {
      const total = job.tasks_total || 0, done = job.tasks_completed || 0;
      const pct = total ? Math.round(100 * done / total) : 0;
      rows.push(
        `<tr class="job" onclick="toggle('${esc(job.job_id)}')">` +
        `<td>${esc(job.job_id)}</td>` +
        `<td><span class="state ${esc(job.state)}">${esc(job.state)}</span></td>` +
        `<td>${esc(job.stages ?? "-")}</td>` +
        `<td><span class="bar"><i style="width:${pct}%"></i></span> ${pct}%</td>` +
        `<td>${done} / ${total}</td>` +
        `<td class="err">${esc(job.error || "")}</td></tr>`);
      if (open_.has(job.job_id)) {
        const stages = await j(`/api/job/${job.job_id}/stages`);
        rows.push(`<tr><td colspan="6" class="stages-cell">` + stages.map(s =>
          `<div><b>stage ${s.stage_id}</b> ` +
          `<span class="state ${esc(s.state)}">${esc(s.state)}</span> ` +
          `${s.completed}/${s.partitions} tasks, attempt ${s.attempt}` +
          `<pre>${esc(s.plan)}</pre></div>`).join("") + `</td></tr>`);
      }
    }
    document.getElementById("jobs").innerHTML =
      rows.join("") || `<tr><td colspan="6">no jobs yet</td></tr>`;
    document.getElementById("refreshed").textContent =
      "refreshed " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("refreshed").textContent = "refresh failed: " + e;
  }
}
function toggle(id) { open_.has(id) ? open_.delete(id) : open_.add(id); refresh(); }
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
