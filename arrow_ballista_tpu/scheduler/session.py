"""Per-session isolation on a shared scheduler.

Parity: the reference creates/updates a DataFusion ``SessionContext`` per
client with its own validated ``BallistaConfig`` (shuffle partitions,
batch size) and persists sessions in the cluster state
(reference ballista/scheduler/src/state/session_manager.rs:27-57,
session_registry.rs:23-66; Flight SQL opens one per handshake,
flight_sql.rs:83-170).  Two clients with different
``ballista.shuffle.partitions`` must not see each other's settings —
or each other's temporary tables.

``OverlayCatalog`` gives each session a private table namespace that
falls back to the scheduler-level shared catalog (external tables
registered by operators are visible to everyone; a session's registered
tables are its own)."""
from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, Optional

from ..catalog import SchemaCatalog, TableProvider
from ..utils.config import BallistaConfig
from ..utils.errors import PlanningError


class OverlayCatalog(SchemaCatalog):
    def __init__(self, parent: SchemaCatalog):
        super().__init__()
        self.parent = parent

    def table_schema(self, name: str):
        p = self.tables.get(name)
        if p is not None:
            return p.schema
        return self.parent.table_schema(name)

    def table_names(self):
        return sorted(set(self.parent.table_names()) | set(self.tables))

    def provider(self, name: str) -> TableProvider:
        p = self.tables.get(name)
        if p is not None:
            return p
        return self.parent.provider(name)


class Session:
    def __init__(self, session_id: str, config: BallistaConfig,
                 catalog: OverlayCatalog):
        self.id = session_id
        self.config = config
        self.catalog = catalog
        self.created = time.time()
        self.last_used = self.created
        # prepared statements: id -> (sql, result schema)
        self.prepared: Dict[str, tuple] = {}

    def touch(self):
        self.last_used = time.time()

    @property
    def tenant(self) -> str:
        """Admission-control identity: ``ballista.admission.tenant`` when
        set (several sessions can share one quota pool), else the session
        id — each session is its own tenant."""
        from ..utils.config import ADMISSION_TENANT

        return self.config.get(ADMISSION_TENANT) or self.id

    def admission_request(self, config: Optional[BallistaConfig] = None):
        """Build the AdmissionRequest for a submission from this session;
        ``config`` overrides (session settings + per-request overlays)
        default to the session config."""
        from ..admission import AdmissionRequest

        return AdmissionRequest.from_config(config or self.config,
                                            default_tenant=self.tenant)


class SessionManager:
    """Create/update/expire sessions (reference session_manager.rs:27-57).
    Sessions idle beyond ``ttl_s`` are evicted lazily."""

    def __init__(self, default_config: BallistaConfig,
                 shared_catalog: SchemaCatalog, ttl_s: float = 3600.0):
        self.default_config = default_config
        self.shared_catalog = shared_catalog
        self.ttl_s = ttl_s
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.Lock()

    def create_session(self, settings: Optional[Dict[str, str]] = None) -> Session:
        sid = f"sess-{uuid.uuid4().hex[:12]}"
        config = BallistaConfig({**self.default_config._settings,
                                 **(settings or {})})
        session = Session(sid, config, OverlayCatalog(self.shared_catalog))
        with self._lock:
            self._evict_expired_locked()
            self._sessions[sid] = session
        return session

    def update_session(self, session_id: str,
                       settings: Dict[str, str]) -> Session:
        s = self.get(session_id)
        s.config = BallistaConfig({**s.config._settings, **settings})
        return s

    def get(self, session_id: Optional[str]) -> Optional[Session]:
        if session_id is None:
            return None
        with self._lock:
            s = self._sessions.get(session_id)
        if s is None:
            raise PlanningError(f"unknown or expired session {session_id!r}")
        s.touch()
        return s

    def remove_session(self, session_id: str) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)

    def _evict_expired_locked(self) -> None:
        # caller holds self._lock (repo convention: *_locked suffix)
        now = time.time()
        for sid in [sid for sid, s in self._sessions.items()
                    if now - s.last_used > self.ttl_s]:
            del self._sessions[sid]

    def count(self) -> int:
        with self._lock:
            return len(self._sessions)
