"""Arrow Flight front door on the scheduler, speaking enough Flight SQL
for JDBC-class clients.

Parity: the reference exposes Arrow Flight SQL on the scheduler
(reference ballista/scheduler/src/flight_sql.rs:83-911 — handshake,
CommandStatementQuery/getFlightInfo, prepared statements, do_get with
TicketStatementQuery; it powers the Arrow Flight SQL JDBC driver) and an
Arrow Flight data plane on executors (flight_service.rs:82-120).  Here one
`pyarrow.flight.FlightServerBase` fronts the scheduler's existing
session/prepare/execute/fetch machinery:

- a STOCK ``pyarrow.flight`` client can run SQL end-to-end:
  ``get_flight_info(FlightDescriptor.for_command(b"select ..."))`` then
  ``do_get(endpoint.ticket)``;
- Flight SQL's simple-query and prepared-statement flows are understood at
  the wire level: ``google.protobuf.Any``-wrapped ``CommandStatementQuery``
  / ``TicketStatementQuery`` / ``ActionCreatePreparedStatementRequest`` /
  ``CommandPreparedStatementQuery`` messages are parsed/emitted with a
  minimal protobuf codec (every field involved is length-delimited), so no
  protobuf toolchain is needed.

Results stream as plain (non-dictionary) arrow arrays: one stable stream
schema regardless of per-batch dictionaries.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

_SQL_NS = "type.googleapis.com/arrow.flight.protocol.sql."


def like_pattern(pattern: str):
    """SQL LIKE filter pattern -> compiled regex (Flight SQL
    CommandGetTables): ``%`` -> ``.*``, ``_`` -> ``.``, and a backslash
    escapes the next character (``\\%`` / ``\\_`` match literal ``%`` /
    ``_`` — re.escape alone would turn ``\\%`` into an escaped backslash
    followed by a live wildcard)."""
    import re as _re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\" and i + 1 < len(pattern):
            out.append(_re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(_re.escape(c))
        i += 1
    return _re.compile("^" + "".join(out) + "$", _re.IGNORECASE)


# --------------------------------------------------------------------------
# minimal protobuf (length-delimited fields only)
# --------------------------------------------------------------------------


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = data[i]
        out |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return out, i
        shift += 7


def _write_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


def pb_decode(data: bytes) -> Dict[int, List]:
    """field number -> list of values: raw bytes for length-delimited
    fields, int for varint fields (bools like include_schema arrive as
    wire-type 0 — skipping them loses real driver flags).  64/32-bit
    fixed fields are skipped (none of the messages we speak use them)."""
    out: Dict[int, List] = {}
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 2:  # length-delimited
            n, i = _read_varint(data, i)
            out.setdefault(field, []).append(data[i:i + n])
            i += n
        elif wire == 0:  # varint
            v, i = _read_varint(data, i)
            out.setdefault(field, []).append(v)
        elif wire == 1:  # 64-bit — skip
            i += 8
        elif wire == 5:  # 32-bit — skip
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
    return out


def pb_field(field: int, payload: bytes) -> bytes:
    return _write_varint(field << 3 | 2) + _write_varint(len(payload)) + payload


def any_wrap(type_name: str, value: bytes) -> bytes:
    return pb_field(1, (_SQL_NS + type_name).encode()) + pb_field(2, value)


def any_unwrap(data: bytes) -> Tuple[str, bytes]:
    """(short type name, value) from a google.protobuf.Any; raises
    ValueError when the bytes aren't an Any we understand."""
    fields = pb_decode(data)
    if 1 not in fields:
        raise ValueError("not a protobuf Any")
    url = fields[1][0].decode("utf-8", "strict")
    if "/" not in url:
        raise ValueError(f"unexpected Any type url {url!r}")
    value = fields[2][0] if 2 in fields else b""
    return url.rsplit(".", 1)[1], value


# --------------------------------------------------------------------------
# schema mapping
# --------------------------------------------------------------------------


def logical_arrow_schema(schema):
    """Our Schema -> the (stable) pyarrow schema Flight streams use:
    strings as plain utf8 (not per-batch dictionaries), decimals as
    decimal128(38, scale) — matching ColumnBatch.to_arrow after the
    dictionary cast.  One mapping for the whole engine
    (Schema.to_arrow_schema)."""
    return schema.to_arrow_schema()


# --------------------------------------------------------------------------
# the server
# --------------------------------------------------------------------------


class BallistaFlightServer:
    """Flight (SQL) service over a SchedulerNetService.  Lazily imports
    pyarrow.flight so deployments without the Flight door never pay for
    grpc."""

    def __init__(self, svc, host: str = "127.0.0.1", port: int = 0):
        import pyarrow.flight as fl

        self.svc = svc
        outer = self

        class _Server(fl.FlightServerBase):
            def __init__(self):
                super().__init__(location=f"grpc://{host}:{port}")

            def get_flight_info(self, context, descriptor):
                return outer._get_flight_info(descriptor)

            def get_schema(self, context, descriptor):
                kind, payload = outer._command_kind(bytes(descriptor.command))
                if kind == "meta":
                    return fl.SchemaResult(outer._meta_table(*payload).schema)
                return fl.SchemaResult(outer._plan_schema(payload))

            def do_get(self, context, ticket):
                return outer._do_get(bytes(ticket.ticket))

            def do_action(self, context, action):
                return outer._do_action(action.type, bytes(action.body))

            def list_actions(self, context):
                return [("CreatePreparedStatement",
                         "Flight SQL prepared statement"),
                        ("ClosePreparedStatement",
                         "drop a prepared statement handle")]

        self._fl = fl
        self._server = _Server()
        self.host = host
        self.port = self._server.port
        self._prepared: Dict[bytes, str] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve,
                                        name=f"flight-{self.port}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        try:
            self._server.shutdown()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            log.debug("flight server shutdown", exc_info=True)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # --- metadata commands (the JDBC/ADBC connect sequence) --------------
    # Every Flight SQL driver issues these on connect, before any query
    # (reference flight_sql.rs get_flight_info_sql_info/_catalogs/
    # _schemas/_tables/_table_types); the standard result schemas are
    # fixed by the Flight SQL spec.
    _META_COMMANDS = ("CommandGetSqlInfo", "CommandGetCatalogs",
                      "CommandGetDbSchemas", "CommandGetTables",
                      "CommandGetTableTypes")
    CATALOG_NAME = "ballista"
    DB_SCHEMA_NAME = "public"

    def _meta_table(self, name: str, value: bytes):
        import pyarrow as pa

        if name == "CommandGetCatalogs":
            return pa.table({"catalog_name": pa.array([self.CATALOG_NAME],
                                                      type=pa.string())})
        if name == "CommandGetDbSchemas":
            return pa.table({
                "catalog_name": pa.array([self.CATALOG_NAME], type=pa.string()),
                "db_schema_name": pa.array([self.DB_SCHEMA_NAME],
                                           type=pa.string())})
        if name == "CommandGetTableTypes":
            return pa.table({"table_type": pa.array(["TABLE"],
                                                    type=pa.string())})
        if name == "CommandGetTables":
            # FlightSql.proto CommandGetTables: catalog=1,
            # db_schema_filter_pattern=2, table_name_filter_pattern=3,
            # table_types=4 (repeated string), include_schema=5 (bool)
            f = pb_decode(value)
            _like = like_pattern
            names = sorted(self.svc.catalog.table_names())
            catalog = f[1][0].decode("utf-8") if 1 in f else None
            if catalog not in (None, "", self.CATALOG_NAME):
                names = []
            if 2 in f and not _like(f[2][0].decode("utf-8")).match(
                    self.DB_SCHEMA_NAME):
                names = []
            if 3 in f:
                rx = _like(f[3][0].decode("utf-8"))
                names = [n for n in names if rx.match(n)]
            if 4 in f:  # repeated table-type filter
                types = {t.decode("utf-8").upper() for t in f[4]}
                if "TABLE" not in types:
                    names = []
            include_schema = bool(f[5][0]) if 5 in f else False
            cols = {
                "catalog_name": pa.array([self.CATALOG_NAME] * len(names),
                                         type=pa.string()),
                "db_schema_name": pa.array([self.DB_SCHEMA_NAME] * len(names),
                                           type=pa.string()),
                "table_name": pa.array(names, type=pa.string()),
                "table_type": pa.array(["TABLE"] * len(names),
                                       type=pa.string()),
            }
            if include_schema:
                blobs = []
                for n in names:
                    sch = logical_arrow_schema(
                        self.svc.catalog.provider(n).schema)
                    blobs.append(sch.serialize().to_pybytes())
                cols["table_schema"] = pa.array(blobs, type=pa.binary())
            return pa.table(cols)
        if name == "CommandGetSqlInfo":
            # spec schema: info_name uint32, value dense_union of
            # (string, bool, int64, int32, list<utf8>, map<int32,list<int32>>)
            from .. import __version__ as _ver

            info = {
                0: "arrow-ballista-tpu",          # FLIGHT_SQL_SERVER_NAME
                1: str(_ver),                     # FLIGHT_SQL_SERVER_VERSION
                2: pa.__version__,                # FLIGHT_SQL_SERVER_ARROW_VERSION
            }
            f = pb_decode(value)
            # requested info ids: packed (one LEN payload of varints) or
            # unpacked repeated uint32 (ints straight from the decoder)
            wanted = None
            if 1 in f:
                wanted = set()
                for payload in f[1]:
                    if isinstance(payload, int):
                        wanted.add(payload)
                        continue
                    i = 0
                    while i < len(payload):
                        v, i = _read_varint(payload, i)
                        wanted.add(v)
            rows = [(k, v) for k, v in sorted(info.items())
                    if wanted is None or k in wanted]
            union_type = pa.dense_union([
                pa.field("string_value", pa.string()),
                pa.field("bool_value", pa.bool_()),
                pa.field("bigint_value", pa.int64()),
                pa.field("int32_bitmask", pa.int32()),
                pa.field("string_list", pa.list_(pa.string())),
                pa.field("int32_to_int32_list_map",
                         pa.map_(pa.int32(), pa.list_(pa.int32()))),
            ])
            types = pa.array([0] * len(rows), type=pa.int8())
            offsets = pa.array(range(len(rows)), type=pa.int32())
            strings = pa.array([v for _, v in rows], type=pa.string())
            empty = [pa.array([], type=t.type) for t in list(union_type)[1:]]
            union = pa.UnionArray.from_dense(types, offsets,
                                             [strings, *empty],
                                             [t.name for t in union_type])
            return pa.table({
                "info_name": pa.array([k for k, _ in rows], type=pa.uint32()),
                "value": union})
        raise self._fl.FlightServerError(f"unsupported metadata command {name}")

    # --- command parsing -------------------------------------------------
    def _command_kind(self, cmd: bytes):
        """(kind, payload): ('meta', (name, value)) for metadata commands,
        ('sql', text) for query commands."""
        try:
            name, value = any_unwrap(cmd)
        # ballista: allow=recovery-path-logging — expected dual-format parse
        except Exception:  # noqa: BLE001 — not protobuf: plain SQL bytes
            return "sql", cmd.decode("utf-8")
        if name in self._META_COMMANDS:
            return "meta", (name, value)
        if name == "CommandStatementQuery":
            return "sql", pb_decode(value)[1][0].decode("utf-8")
        if name == "CommandPreparedStatementQuery":
            handle = pb_decode(value)[1][0]
            with self._lock:
                sql = self._prepared.get(handle)
            if sql is None:
                raise self._fl.FlightServerError(
                    f"unknown prepared statement handle {handle!r}")
            return "sql", sql
        raise self._fl.FlightServerError(
            f"unsupported Flight SQL command {name}")

    def _sql_of_command(self, cmd: bytes) -> str:
        """SQL text from a descriptor command: an Any-wrapped Flight SQL
        message, or raw SQL bytes (the stock-pyarrow-client path)."""
        kind, payload = self._command_kind(cmd)
        if kind != "sql":
            raise self._fl.FlightServerError(
                f"metadata command {payload[0]} carries no SQL")
        return payload

    def _sql_of_ticket(self, raw: bytes) -> str:
        try:
            name, value = any_unwrap(raw)
        # ballista: allow=recovery-path-logging — expected dual-format parse
        except Exception:  # noqa: BLE001 — plain SQL ticket
            return raw.decode("utf-8")
        if name == "TicketStatementQuery":
            # statement_handle carries the SQL we stamped in get_flight_info
            return pb_decode(value)[1][0].decode("utf-8")
        # tickets for prepared statements carry the command itself
        return self._sql_of_command(raw)

    # --- planning / execution -------------------------------------------
    _DDL_TYPES = ("CreateExternalTable", "SetVariable", "ShowTables",
                  "ShowSettings", "ShowColumns", "Explain")

    def _parse(self, sql: str):
        """Parse once; returns (stmt, is_ddl) where is_ddl marks the
        utility statements (CREATE EXTERNAL TABLE / SET / SHOW / DESCRIBE
        / EXPLAIN) the Flight door executes directly — JDBC clients issue
        them like any statement (same set the CLI/client dispatch covers,
        context.py:255-283)."""
        from ..sql.parser import parse_sql

        stmt = parse_sql(sql)
        return stmt, type(stmt).__name__ in self._DDL_TYPES

    def _run_ddl(self, stmt):
        """Execute a DDL/utility statement; returns the result pa.Table."""
        import pyarrow as pa

        from ..sql import ast as sqlast

        if isinstance(stmt, sqlast.CreateExternalTable):
            from ..models.schema import Field as EField, Schema as ESchema
            from ..sql.planner import parse_type_name

            from .. import serde

            payload = {"name": stmt.name, "format": stmt.file_format,
                       "path": stmt.location, "has_header": stmt.has_header,
                       "delimiter": stmt.delimiter}
            if stmt.columns:  # declared column types win over inference
                payload["schema"] = serde.schema_to_obj(ESchema(
                    EField(n, parse_type_name(t)) for n, t in stmt.columns))
            self.svc._register_external_table(payload, b"")
            return pa.table({"result": pa.array([], type=pa.string())})
        if isinstance(stmt, sqlast.SetVariable):
            # sessionless Flight SET mutates the shared default config
            self.svc.config.set(stmt.key, stmt.value)
            return pa.table({"result": pa.array([], type=pa.string())})
        if isinstance(stmt, sqlast.ShowSettings):
            settings = self.svc.config.to_dict()
            if stmt.key:
                self.svc.config.get(stmt.key)  # unknown key -> error
                settings = {stmt.key: settings[stmt.key]}
            rows = sorted(settings.items())
            return pa.table({
                "name": pa.array([k for k, _ in rows], type=pa.string()),
                "value": pa.array([str(v) for _, v in rows], type=pa.string())})
        if isinstance(stmt, sqlast.ShowColumns):
            schema = self.svc.catalog.provider(stmt.table).schema
            return pa.table({
                "column_name": pa.array([f.name for f in schema],
                                        type=pa.string()),
                "data_type": pa.array([str(f.dtype) for f in schema],
                                      type=pa.string())})
        if isinstance(stmt, sqlast.Explain):
            from .physical_planner import explain_rows

            rows = explain_rows(self.svc.catalog, self.svc.config,
                                stmt.statement, stmt.verbose)
            return pa.table({
                "plan_type": pa.array([r["plan_type"] for r in rows],
                                      type=pa.string()),
                "plan": pa.array([r["plan"] for r in rows],
                                 type=pa.string())})
        # ShowTables
        names = sorted(self.svc.catalog.table_names())
        return pa.table({"table_name": pa.array(names, type=pa.string())})

    def _plan_schema(self, sql: str):
        stmt, is_ddl = self._parse(sql)
        if is_ddl:
            return self._run_ddl(stmt).schema
        # plan directly (the _prepare RPC would store a statement in the
        # sessionless prepared holder — leaking one entry per Flight
        # schema probe and evicting real RPC-prepared statements)
        from ..sql.optimizer import optimize
        from ..sql.planner import SqlToRel

        logical = optimize(SqlToRel(self.svc.catalog).plan(stmt))
        return logical_arrow_schema(logical.schema)

    def _get_flight_info(self, descriptor):
        fl = self._fl
        cmd = bytes(descriptor.command)
        kind, payload = self._command_kind(cmd)
        if kind == "meta":
            # metadata flows: the ticket is the command itself, round-tripped
            # verbatim (exactly how the JDBC driver replays it to do_get)
            schema = self._meta_table(*payload).schema
            ticket = fl.Ticket(cmd)
        else:
            sql = payload
            schema = self._plan_schema(sql)
            # the ticket round-trips through the client verbatim (JDBC sends
            # it back as-is): Any(TicketStatementQuery{statement_handle=sql})
            ticket = fl.Ticket(any_wrap(
                "TicketStatementQuery", pb_field(1, sql.encode())))
        endpoint = fl.FlightEndpoint(ticket, [
            fl.Location.for_grpc_tcp(self.host, self.port)])
        return fl.FlightInfo(schema, descriptor, [endpoint], -1, -1)

    def _do_get(self, raw_ticket: bytes):
        fl = self._fl
        try:
            name, value = any_unwrap(raw_ticket)
        # ballista: allow=recovery-path-logging — expected dual-format parse
        except Exception:  # noqa: BLE001
            name = value = None
        if name in self._META_COMMANDS:
            return fl.RecordBatchStream(self._meta_table(name, value))
        sql = self._sql_of_ticket(raw_ticket)
        table = self._execute_to_table(sql)
        return fl.RecordBatchStream(table)

    def _execute_to_table(self, sql: str):
        import pyarrow as pa

        stmt, is_ddl = self._parse(sql)
        if is_ddl:
            return self._run_ddl(stmt)

        from .. import serde
        from ..models.batch import ColumnBatch
        from ..models.ipc import read_ipc_files
        from ..net.dataplane import fetch_partition_batches
        from ..utils.errors import ExecutionError

        payload, _ = self.svc._execute_query({"sql": sql}, b"")
        job_id = payload["job_id"]
        status = self.svc.server.wait_for_job(
            job_id, float(self.svc.config.job_timeout_s))
        if status.state != "successful":
            raise ExecutionError(f"job {job_id} {status.state}: {status.error}")
        with self.svc._lock:
            schema = self.svc._final_schemas.get(job_id)
        if schema is None:  # LRU-evicted under heavy concurrent load
            raise ExecutionError(
                f"result schema for job {job_id} no longer cached; re-run "
                f"the query")
        target = logical_arrow_schema(schema)
        batches: List[ColumnBatch] = []
        for part in sorted(status.locations):
            for loc in status.locations[part]:
                if not loc.num_rows:
                    continue
                if os.path.exists(loc.path):
                    batches.extend(read_ipc_files([loc.path], schema))
                else:
                    batches.extend(fetch_partition_batches(
                        loc.host, loc.port, loc.path, schema,
                        self.svc.config.batch_size))
        tables = [b.to_arrow().cast(target) for b in batches]
        return pa.concat_tables(tables) if tables \
            else target.empty_table()

    # --- actions (prepared statements) ----------------------------------
    def _do_action(self, action_type: str, body: bytes):
        fl = self._fl
        if action_type == "CreatePreparedStatement":
            try:
                _name, value = any_unwrap(body)
            # ballista: allow=recovery-path-logging — expected dual-format parse
            except Exception:  # noqa: BLE001 — raw request body
                value = body
            sql = pb_decode(value)[1][0].decode("utf-8")
            schema = self._plan_schema(sql)
            handle = os.urandom(12)
            with self._lock:
                self._prepared[handle] = sql
                while len(self._prepared) > 256:
                    self._prepared.pop(next(iter(self._prepared)))
            result = (pb_field(1, handle)
                      + pb_field(2, schema.serialize().to_pybytes()))
            return [any_wrap("ActionCreatePreparedStatementResult", result)]
        if action_type == "ClosePreparedStatement":
            try:
                _name, value = any_unwrap(body)
            # ballista: allow=recovery-path-logging — expected dual-format parse
            except Exception:  # noqa: BLE001
                value = body
            handle = pb_decode(value)[1][0]
            with self._lock:
                self._prepared.pop(handle, None)
            return []
        raise self._fl.FlightServerError(f"unknown action {action_type!r}")
