"""REST API for the scheduler: cluster state, jobs, stages, dot, metrics.

Parity: reference ballista/scheduler/src/api/ (warp routes under /api,
api/mod.rs:85-137 + handlers.rs):

    GET  /api/state            cluster summary
    GET  /api/executors        executor metadata + heartbeats
    GET  /api/jobs             job list with status + progress
    GET  /api/job/<id>         job detail incl. per-task attempt history
    GET  /api/job/<id>/stages  per-stage task progress
    GET  /api/job/<id>/dot     graphviz of the execution graph
    PATCH /api/job/<id>        cancel (body ignored)
    GET  /api/metrics          prometheus text exposition

Beyond the reference surface:

    GET  /api/admission        admission-control queue state per tenant
    GET  /api/quarantine       quarantined/probation executors + counters
    GET  /api/job/<id>/profile per-stage -> per-task -> per-operator profile
    GET  /api/job/<id>/trace   Chrome trace-event JSON (Perfetto-loadable)
    GET  /api/job/<id>/stats   EXPLAIN ANALYZE report: per-stage skew /
                               histograms / duration quantiles + annotated
                               operator tree (obs/stats.py)
    GET  /api/job/<id>/advise  stage-fusion advisor: operator chains ranked
                               by estimated fusion savings (obs/advisor.py)
    GET  /api/cluster/history  ring-buffer time series of cluster samples
                               (utilization, queue depths, event-loop lag),
                               fleet-aware: per-shard breakdown + rollup
                               via the shared-KV shard registry
    GET  /api/job/<id>/forensics  self-contained postmortem bundle: flight-
                               recorder timeline + stage stats + device
                               stats + spans + metrics (obs/doctor.py)
    GET  /api/job/<id>/doctor  automated pathology diagnosis over the
                               forensics bundle: ranked findings with
                               cited metric evidence + config remedies
    GET  /api/plan-cache       prepared-plan cache: hit/miss/eviction
                               counters, budgets, recent templates
    GET  /api/result-cache     result/subplan cache counters + budgets
    GET  /api/autoscale        KEDA-style fleet scaling signal: pending
                               tasks / utilization / queue depths summed
                               across shards via the shared-KV registry
    GET  /api/slo              latency SLO snapshot: policy, fast/slow
                               window counts and burn rates, fleet-merged
                               across shards via the shared-KV registry
    GET  /api/job/<id>/watch   live chunked-NDJSON stream: journal events
                               + progress frames + one terminal frame
                               (docs/user-guide/live.md for the schema)
    GET  /api/cluster/watch    live chunked-NDJSON stream of every journal
                               event on this shard (no terminal frame;
                               close the connection to stop)
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..obs import journal
from ..obs.advisor import advise_graph
from ..obs.doctor import assemble_forensics, diagnose
from ..obs.progress import job_progress, monotonic_fraction
from ..obs.stats import explain_analyze_report
from ..utils.config import (
    BallistaConfig,
    LIVE_WATCH_POLL_S,
    LIVE_WATCH_QUEUE_EVENTS,
)
from .graph_dot import graph_to_dot
from .scheduler import SchedulerServer

#: job states that end a watch stream
_TERMINAL = ("successful", "failed", "cancelled")


class RestApi:
    def __init__(self, server: SchedulerServer, host: str = "127.0.0.1",
                 port: int = 0):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: str, ctype="application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    outer._route_get(self)
                # the error is returned to the HTTP client as the 500 body;
                # logging every probe of a bad route lets clients spam the log
                # ballista: allow=recovery-path-logging — surfaced in the 500
                except Exception as e:  # noqa: BLE001
                    self._send(500, json.dumps({"error": str(e)}))

            def do_PATCH(self):
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[0] == "api" and parts[1] == "job":
                    outer.server.cancel_job(parts[2])
                    self._send(200, json.dumps({"cancelled": parts[2]}))
                else:
                    self._send(404, json.dumps({"error": "not found"}))

        self.server = server
        # watch streams poll this so stop() does not hang on a client that
        # keeps its NDJSON connection open  ballista: guarded-by=none
        self._stopping = False
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"rest-{self.port}", daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stopping = True
        if self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()

    # --- routing ---------------------------------------------------------
    def _route_get(self, h) -> None:
        parts = h.path.strip("/").split("/")
        if parts in ([""], ["ui"], ["index.html"]):
            # the web dashboard (reference ships a React app over the same
            # /api surface, ui/src/components/*.tsx)
            from .webui import INDEX_HTML

            h._send(200, INDEX_HTML, ctype="text/html; charset=utf-8")
            return
        if parts[:1] != ["api"]:
            h._send(404, json.dumps({"error": "not found"}))
            return
        rest = parts[1:]
        if rest == ["state"]:
            h._send(200, json.dumps(self._state()))
        elif rest == ["executors"]:
            h._send(200, json.dumps(self._executors()))
        elif rest == ["jobs"]:
            h._send(200, json.dumps(self._jobs()))
        elif len(rest) == 2 and rest[0] == "job":
            job = self._job_detail(rest[1])
            if job is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, json.dumps(job))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "stages":
            h._send(200, json.dumps(self._stages(rest[1])))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "watch":
            if self.server.jobs.get_status(rest[1]) is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                self._stream_watch(h, rest[1])
        elif rest == ["cluster", "watch"]:
            self._stream_watch(h, None)
        elif rest == ["slo"]:
            h._send(200, json.dumps(self.server.slo_report()))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "profile":
            prof = self.server.obs.get_profile(
                rest[1], self.server.jobs.get_graph(rest[1]),
                self.server.jobs.get_status(rest[1]))
            if prof is None:
                h._send(404, json.dumps({"error": "no profile for job"}))
            else:
                h._send(200, json.dumps(prof))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "trace":
            trace = self.server.obs.get_trace(
                rest[1], self.server.jobs.get_graph(rest[1]))
            if trace is None:
                h._send(404, json.dumps({"error": "no trace for job"}))
            else:
                h._send(200, json.dumps(trace))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "stats":
            graph = self.server.jobs.get_graph(rest[1])
            if graph is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, json.dumps(explain_analyze_report(graph)))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "advise":
            graph = self.server.jobs.get_graph(rest[1])
            if graph is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, json.dumps(advise_graph(graph)))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "forensics":
            bundle = assemble_forensics(self.server, rest[1])
            if bundle is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, json.dumps(bundle, default=str))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "doctor":
            bundle = assemble_forensics(self.server, rest[1])
            if bundle is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, json.dumps(diagnose(bundle), default=str))
        elif rest == ["cluster", "history"]:
            h._send(200, json.dumps(self.server.cluster_history()))
        elif len(rest) == 3 and rest[0] == "job" and rest[2] == "dot":
            graph = self.server.jobs.get_graph(rest[1])
            if graph is None:
                h._send(404, json.dumps({"error": "no such job"}))
            else:
                h._send(200, graph_to_dot(graph), ctype="text/vnd.graphviz")
        elif rest == ["metrics"]:
            # fold the latest journal counter deltas in before exposition
            # (the history sampler also does this on its own cadence)
            self.server.sync_journal_metrics()
            h._send(200, self.server.metrics.gather(), ctype="text/plain")
        elif rest == ["admission"]:
            h._send(200, json.dumps(self.server.admission.snapshot()))
        elif rest == ["plan-cache"]:
            h._send(200, json.dumps(self.server.plan_cache.snapshot()))
        elif rest == ["result-cache"]:
            h._send(200, json.dumps(self.server.result_cache.snapshot()))
        elif rest == ["quarantine"]:
            h._send(200, json.dumps(self.server.quarantine.snapshot()))
        elif rest == ["scaler"]:
            # KEDA-scaler-shaped endpoint (reference external_scaler.rs:14-60
            # reports inflight_tasks = pending task count); consumed by a
            # metrics-api trigger (deploy/helm templates/hpa.yaml)
            h._send(200, json.dumps(
                {"inflight_tasks": self.server.pending_task_count()}))
        elif rest == ["autoscale"]:
            # fleet-wide scaling signal: /api/scaler's successor — pending
            # work, queue depths and utilization summed over every live
            # shard via the shared-KV shard registry (docs/user-guide/
            # metrics.md), plus a desired_executors suggestion
            h._send(200, json.dumps(self.server.autoscale_signal()))
        else:
            h._send(404, json.dumps({"error": "not found"}))

    # --- watch streams ---------------------------------------------------
    def _stream_watch(self, h, job_id: Optional[str]) -> None:
        """Chunk NDJSON frames at the client until the job ends (job watch)
        or the connection drops (cluster watch).  Frames are one JSON
        object per line, tagged ``{"t": "event"|"progress"|"end"}``; no
        Content-Length — the stream is close-delimited.  The journal
        subscription is bounded and never blocks ``emit()``: a slow
        reader sees a ``watch.gap`` event instead of backpressure."""
        defaults = BallistaConfig()
        poll_s = float(defaults.get(LIVE_WATCH_POLL_S))
        capacity = int(defaults.get(LIVE_WATCH_QUEUE_EVENTS))
        h.send_response(200)
        h.send_header("Content-Type", "application/x-ndjson")
        h.send_header("Cache-Control", "no-cache")
        h.end_headers()

        def frame(obj: dict) -> bool:
            try:
                h.wfile.write((json.dumps(obj) + "\n").encode())
                h.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        floor = 0.0
        with journal.subscribe(job_id=job_id, capacity=capacity) as sub:
            # subscribe BEFORE snapshotting the retained timeline, then
            # dedup on (actor, seq): no event emitted during the handoff
            # is lost, none is shown twice
            replayed = set()
            if job_id is not None:
                for ev in journal.job_timeline(job_id):
                    replayed.add((ev.get("actor"), ev.get("seq")))
                    if not frame({"t": "event", "event": ev}):
                        return
            while not self._stopping:
                for ev in sub.poll(timeout=poll_s):
                    key = (ev.get("actor"), ev.get("seq"))
                    # watch.gap markers carry seq=0 and must never dedup
                    if ev.get("kind") != "watch.gap" and key in replayed:
                        continue
                    if not frame({"t": "event", "event": ev}):
                        return
                if replayed:
                    replayed.clear()  # only the handoff window needs it
                if job_id is None:
                    continue
                st = self.server.jobs.get_status(job_id)
                graph = self.server.jobs.get_graph(job_id)
                if graph is not None:
                    prog = job_progress(graph)
                    floor = monotonic_fraction(prog, floor)
                    prog["fraction"] = floor
                    if not frame({"t": "progress", "progress": prog,
                                  "state": st.state if st else None}):
                        return
                if st is not None and st.state in _TERMINAL:
                    frame({"t": "end", "state": st.state,
                           "error": st.error})
                    return

    # --- payloads --------------------------------------------------------
    def _state(self) -> dict:
        cluster = self.server.cluster
        return {
            "executors": len(cluster.executors()),
            "alive_executors": len(cluster.alive_executors(
                self.server.config.executor_timeout_s)),
            "quarantined_executors": self.server.quarantine.count(),
            "available_task_slots": cluster.total_available(),
            "pending_tasks": self.server.pending_task_count(),
            "started_at": getattr(self.server, "_started_at", 0),
        }

    def _executors(self) -> list:
        cluster = self.server.cluster
        out = []
        for meta in cluster.executors():
            hb = cluster._heartbeats.get(meta.executor_id)
            out.append({
                "executor_id": meta.executor_id, "host": meta.host,
                "port": meta.port, "grpc_port": meta.grpc_port,
                "task_slots": meta.task_slots,
                "last_seen_s_ago": round(time.time() - hb.timestamp, 1) if hb else None,
                "status": hb.status if hb else "unknown",
                "quarantined": self.server.quarantine.is_quarantined(
                    meta.executor_id),
            })
        return out

    def _jobs(self) -> list:
        out = []
        with self.server.jobs._lock:
            statuses = dict(self.server.jobs._status)
        for job_id, st in statuses.items():
            entry = {"job_id": job_id, "state": st.state, "error": st.error}
            graph = self.server.jobs.get_graph(job_id)
            if graph is not None:
                # one computation for every surface: REST, watch frames and
                # EXPLAIN ANALYZE all report obs/progress.py's fraction
                prog = job_progress(graph)
                entry["stages"] = len(graph.stages)
                entry["tasks_completed"] = prog["tasks_completed"]
                entry["tasks_total"] = prog["tasks_total"]
                entry["progress"] = prog["fraction"]
                entry["eta_s"] = prog["eta_s"]
            out.append(entry)
        return out

    def _job_detail(self, job_id: str) -> Optional[dict]:
        """Job status + the full per-task attempt history: every launch
        (original, retry, or speculative duplicate) with its executor,
        terminal state and duration — the audit trail for straggler
        mitigation ("did speculation fire, and who won?")."""
        st = self.server.jobs.get_status(job_id)
        if st is None:
            return None
        out = {"job_id": job_id, "state": st.state, "error": st.error}
        graph = self.server.jobs.get_graph(job_id)
        if graph is None:
            return out
        out["progress"] = job_progress(graph)
        stages = {}
        for sid in sorted(graph.stages):
            s = graph.stages[sid]
            stages[str(sid)] = {
                "state": s.state,
                "stage_attempt": s.stage_attempt,
                "attempts": [
                    {"partition": e["partition"], "attempt": e["attempt"],
                     "stage_attempt": e["stage_attempt"],
                     "executor_id": e["executor_id"],
                     "speculative": e["speculative"], "state": e["state"],
                     "duration_s": (round(e["duration_s"], 3)
                                    if e["duration_s"] is not None else None)}
                    for e in s.attempt_log],
            }
        out["stages"] = stages
        return out

    def _stages(self, job_id: str) -> list:
        graph = self.server.jobs.get_graph(job_id)
        if graph is None:
            return []
        # per-stage fractions come from the same obs/progress.py fold the
        # job-level surfaces use, so the numbers always agree
        prog = {s["stage_id"]: s for s in job_progress(graph)["stages"]}
        out = []
        for sid in sorted(graph.stages):
            s = graph.stages[sid]
            agg = {k: round(v, 3) for k, v in s.aggregate_metrics().items()}
            out.append({
                "stage_id": sid, "state": s.state,
                "partitions": s.partitions,
                "completed": prog[sid]["tasks_completed"],
                "fraction": prog[sid]["fraction"],
                "attempt": s.stage_attempt,
                "producers": s.producer_ids,
                "consumers": s.output_links,
                "plan": (s.resolved_plan or s.plan).display(),
                "metrics": agg,
            })
        return out
