"""Executor quarantine: stop offering work to repeatedly-failing executors.

A bad host (full disk, broken accelerator, flaky NIC) fails every task it
touches; with round-robin offers it keeps draining retry budgets until a
job dies.  The scheduler counts *consecutive retryable* task failures per
executor (``FailedReason.retryable`` — IOError/ExecutorLost/ResultLost;
fetch failures blame the producer and fatal ExecutionErrors fail the job
outright, so neither counts here).  At ``threshold`` consecutive failures
the executor is quarantined: it stays registered and heartbeating but
``_offer``/poll stop handing it tasks.  After ``probation_s`` it is
re-admitted *on probation* — a single failure re-quarantines immediately,
a success clears its record.

Observable via ``executor_quarantined_total`` / ``quarantined_executors``
metrics and REST ``/api/quarantine``.  ``threshold <= 0`` disables.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Set


class ExecutorQuarantine:
    def __init__(self, threshold: int = 5, probation_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = int(threshold)
        self.probation_s = float(probation_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive: Dict[str, int] = {}
        self._quarantined_at: Dict[str, float] = {}
        self._on_probation: Set[str] = set()
        self.total_quarantined = 0

    # --- recording --------------------------------------------------------
    def record_success(self, executor_id: str) -> None:
        with self._lock:
            self._consecutive.pop(executor_id, None)
            self._quarantined_at.pop(executor_id, None)
            self._on_probation.discard(executor_id)

    def record_failure(self, executor_id: str) -> bool:
        """Count one retryable failure; True when this failure *newly*
        quarantines the executor (first crossing, or a probation strike)."""
        if self.threshold <= 0:
            return False
        with self._lock:
            if executor_id in self._on_probation:
                self._on_probation.discard(executor_id)
                self._consecutive[executor_id] = self.threshold
                self._quarantined_at[executor_id] = self._clock()
                self.total_quarantined += 1
                return True
            n = self._consecutive.get(executor_id, 0) + 1
            self._consecutive[executor_id] = n
            if n >= self.threshold and executor_id not in self._quarantined_at:
                self._quarantined_at[executor_id] = self._clock()
                self.total_quarantined += 1
                return True
            return False

    def remove(self, executor_id: str) -> None:
        """Executor deregistered/lost: forget its record entirely."""
        with self._lock:
            self._consecutive.pop(executor_id, None)
            self._quarantined_at.pop(executor_id, None)
            self._on_probation.discard(executor_id)

    # --- queries ----------------------------------------------------------
    def is_quarantined(self, executor_id: str) -> bool:
        """Also performs the lazy probation transition: a quarantine older
        than ``probation_s`` flips to probation and the executor becomes
        schedulable again (with zero failure allowance)."""
        if self.threshold <= 0:
            return False
        with self._lock:
            at = self._quarantined_at.get(executor_id)
            if at is None:
                return False
            if self._clock() - at >= self.probation_s:
                del self._quarantined_at[executor_id]
                self._consecutive.pop(executor_id, None)
                self._on_probation.add(executor_id)
                return False
            return True

    def filter(self, executor_ids: Iterable[str]) -> List[str]:
        return [e for e in executor_ids if not self.is_quarantined(e)]

    def count(self) -> int:
        with self._lock:
            now = self._clock()
            return sum(1 for at in self._quarantined_at.values()
                       if now - at < self.probation_s)

    def snapshot(self) -> dict:
        """REST/debug view: who is out, for how much longer, who is on
        probation, and the lifetime counter."""
        with self._lock:
            now = self._clock()
            return {
                "threshold": self.threshold,
                "probation_s": self.probation_s,
                "quarantined": {
                    e: round(max(0.0, self.probation_s - (now - at)), 1)
                    for e, at in self._quarantined_at.items()
                    if now - at < self.probation_s},
                "probation": sorted(self._on_probation),
                "total_quarantined": self.total_quarantined,
            }
