"""Graphviz export of an ExecutionGraph.

Parity: reference ballista/scheduler/src/state/execution_graph_dot.rs —
stages as clusters with per-operator nodes, shuffle edges between stages,
stage state/task-progress in the cluster label.
"""
from __future__ import annotations

from typing import List

from ..ops.shuffle import ShuffleReaderExec, UnresolvedShuffleExec
from .execution_graph import ExecutionGraph


def _esc(s: str) -> str:
    return s.replace('"', '\\"').replace("\n", "\\n")


def _metric_label(mm) -> str:
    """Fold one operator's metric dict into a short 'rows · time' line so
    the DAG doubles as a flame view (rows from output_rows, time as the
    sum of the operator's *_time timers, which are seconds)."""
    parts = []
    rows = mm.get("output_rows")
    if rows:
        parts.append(f"{int(rows):,} rows")
    t = sum(v for k, v in mm.items() if k.endswith("_time"))
    if t:
        parts.append(f"{t * 1000.0:.1f} ms")
    return " · ".join(parts)


def graph_to_dot(graph: ExecutionGraph) -> str:
    lines: List[str] = [
        "digraph G {",
        '  rankdir=BT;',
        '  node [shape=box, fontname="monospace", fontsize=10];',
        f'  label="job {graph.job_id} [{graph.status}]";',
    ]
    # operator nodes per stage cluster
    for sid in sorted(graph.stages):
        stage = graph.stages[sid]
        done = sum(1 for t in stage.task_infos if t and t.state == "success")
        # attempt history summary: total launches and how many were
        # speculative duplicates (straggler mitigation audit trail)
        launches = len(stage.attempt_log)
        spec = sum(1 for e in stage.attempt_log if e["speculative"])
        extra = f" {launches} launches" if launches > stage.partitions else ""
        if spec:
            extra += f" ({spec} speculative)"
        # folded runtime summary (obs/stats.py): rows/bytes shuffled and the
        # partition skew coefficient, once the stage has completed tasks
        summary = graph.stats.stage(sid) if hasattr(graph, "stats") else None
        if summary is not None and summary["output_rows"]:
            extra += (f" · {summary['output_rows']:,} rows"
                      f" · {summary['output_bytes'] / 1048576.0:.1f} MB"
                      f" · skew {summary['skew']:.2f}")
        # adaptive rewrites applied to this stage, with before/after
        # partition counts (scheduler/aqe.py)
        for r in getattr(stage, "aqe_rewrites", ()):
            kinds = "+".join(r.get("kinds", ())) or "rewrite"
            if "partitions_before" in r:
                extra += (f" · aqe {kinds} {r['partitions_before']}->"
                          f"{r['partitions_after']}")
            else:
                extra += f" · aqe {kinds}"
        # whole-stage compilation decisions (compile/fuse.py): chains the
        # compiler replaced with one jitted kernel
        for r in getattr(stage, "fusion_rewrites", ()):
            if r.get("fused"):
                for run in r.get("fused_ops", ()):
                    extra += " · fused " + "+".join(run)
        lines.append(f"  subgraph cluster_{sid} {{")
        lines.append(f'    label="stage {sid} [{stage.state}] '
                     f'{done}/{stage.partitions} tasks '
                     f'attempt {stage.stage_attempt}{extra}";')
        plan = stage.resolved_plan or stage.plan
        counter = [0]
        # per-operator metrics keyed by the executor-side walk's path key
        # ("0.1:HashAggregateExec", execution_engine.collect_plan_metrics)
        op_metrics = stage.operator_metrics()

        def walk(node, parent_id=None, path="0", sid=sid, counter=counter,
                 out=lines):
            nid = f"s{sid}_n{counter[0]}"
            counter[0] += 1
            label = node._label()
            extra = _metric_label(
                op_metrics.get(f"{path}:{type(node).__name__}", {}))
            if extra:
                label += "\n" + extra
            out.append(f'    {nid} [label="{_esc(label)}"];')
            if parent_id is not None:
                out.append(f"    {nid} -> {parent_id};")
            if not isinstance(node, (ShuffleReaderExec, UnresolvedShuffleExec)):
                for i, c in enumerate(node.children()):
                    walk(c, nid, f"{path}.{i}")
            return nid

        walk(plan)
        lines.append("  }")
    # shuffle edges between stages
    for sid in sorted(graph.stages):
        for pid in graph.stages[sid].producer_ids:
            lines.append(f"  cluster_edge_{pid}_{sid} [style=invis, width=0, "
                         f"label=\"\"];")
            lines.append(f'  s{pid}_n0 -> s{sid}_n0 [style=dashed, '
                         f'label="shuffle"];')
    lines.append("}")
    return "\n".join(lines)
