"""Scheduler network service: the SchedulerGrpc surface over the wire.

Parity: reference ballista/scheduler/src/scheduler_server/grpc.rs — the 10
RPC handlers (execute_query, get_job_status, register_executor,
heart_beat_from_executor, update_task_status, executor_stopped, cancel_job,
clean_job_data, …) — plus table registration (the reference client ships
CREATE EXTERNAL TABLE inside the logical plan, context.rs:358-530; here the
scheduler owns the catalog and clients register tables by RPC).

Launching goes through ``NetTaskLauncher`` -> executor launch_multi_task,
i.e. push scheduling (TaskSchedulingPolicy::PushStaged).
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from .. import faults, serde
from ..catalog import CsvTable, MemoryTable, ParquetTable, SchemaCatalog
from ..models.schema import Field, Schema
from ..net.rpc import RpcServer
from ..net import wire
from ..net.retry import RetryPolicy, call_with_retry
from ..utils.config import BallistaConfig
from ..utils.errors import PlanningError
from .scheduler import SchedulerConfig, SchedulerServer, TaskLauncher, random_job_id
from .types import ExecutorHeartbeat, ExecutorMetadata, TaskDescription

log = logging.getLogger(__name__)

# guards plan encoding (see serialize_tasks_or_fail)
_ENCODE_LOCK = threading.Lock()


def serialize_tasks_or_fail(scheduler, executor_id: str,
                            tasks: List[TaskDescription]) -> List[dict]:
    """Serialize tasks PER TASK; a task whose plan cannot serialize fails
    identically on every executor, so report it as a fatal task failure
    (fails its job fast) instead of letting launch retry forever —
    WITHOUT killing unrelated jobs' tasks sharing the batch.  Shared by
    the push launcher and the pull poll_work response.

    Same-stage tasks share one plan instance, so the (expensive) plan
    encoding runs once per stage per batch and is reused across its tasks
    (reference: MultiTaskDefinition's stage plan is encoded once,
    task_manager.rs:583-650)."""
    objs: List[dict] = []
    failed = []
    plan_cache: dict = {}
    for t in tasks:
        try:
            plan_obj = plan_cache.get(id(t.plan))
            if plan_obj is None:
                # ONE encode at a time process-wide: two launch-pool
                # threads serializing the same plan concurrently segfaulted
                # inside pyarrow's IPC writer (same MemoryScanExec table
                # from two threads); encoding is cheap host work, so the
                # lock costs nothing measurable
                with _ENCODE_LOCK:
                    plan_obj = serde.plan_to_obj(t.plan)
                plan_cache[id(t.plan)] = plan_obj
            objs.append(serde.task_to_obj(t, plan_obj=plan_obj))
        except Exception as e:  # noqa: BLE001 — deterministic plan defect
            from .types import EXECUTION_ERROR, FailedReason, TaskStatus

            log.exception("task %s failed to serialize", t.task)
            failed.append(TaskStatus(t.task, executor_id, "failed",
                                     failure=FailedReason(
                                         EXECUTION_ERROR,
                                         f"plan serialization failed: {e}")))
    if failed:
        scheduler.update_task_status(executor_id, failed)
    return objs


def group_tasks_by_plan(objs: List[dict]) -> List[dict]:
    """Flat task objects -> MultiTaskDefinition groups (one plan dict + N
    task envelopes).  Same-stage tasks share the plan OBJECT, so identity
    grouping is exact and the plan is JSON-encoded onto the wire once."""
    groups: dict = {}
    for o in objs:
        g = groups.setdefault(id(o["plan"]), {"plan": o["plan"], "tasks": []})
        g["tasks"].append({"task": o["task"],
                           "internal_id": o["internal_id"],
                           "scalars": o["scalars"],
                           "trace": o.get("trace", {})})
    return list(groups.values())


def ungroup_tasks(payload: dict) -> List[dict]:
    """Inverse of group_tasks_by_plan; accepts the legacy flat shape too."""
    if "stages" not in payload:
        return list(payload.get("tasks", []))
    out = []
    for st in payload["stages"]:
        for env in st["tasks"]:
            out.append({"task": env["task"], "plan": st["plan"],
                        "internal_id": env.get("internal_id", 0),
                        "scalars": env.get("scalars", {}),
                        "trace": env.get("trace", {})})
    return out


class NetTaskLauncher(TaskLauncher):
    """Pushes tasks to executors over the wire (reference
    DefaultTaskLauncher -> ExecutorGrpc.LaunchMultiTask,
    state/task_manager.rs:69-119)."""

    def __init__(self, policy: Optional[RetryPolicy] = None):
        self.scheduler: Optional[SchedulerServer] = None
        # deadline + bounded-backoff policy for every scheduler->executor
        # call; a launch that exhausts the give-up deadline raises a
        # ConnectionError subclass, which _launch turns into ExecutorLost —
        # the retryable path that re-runs the tasks elsewhere without
        # charging task retry budgets
        self.policy = policy or RetryPolicy()
        # (host, port) this scheduler serves RPC on; rides in every launch
        # payload so multi-registered executors report task statuses back
        # to the shard that LAUNCHED the task (fleet mode: a status
        # broadcast to every shard would double-free shared slot accounting)
        self.endpoint: Optional[tuple] = None

    def _addr(self, executor_id: str):
        meta = self.scheduler.cluster.get_executor(executor_id)
        if meta is None:
            raise PlanningError(f"unknown executor {executor_id}")
        return meta.host, meta.grpc_port or meta.port

    def launch_tasks(self, executor_id: str, tasks: List[TaskDescription]) -> None:
        objs = serialize_tasks_or_fail(self.scheduler, executor_id, tasks)
        if not objs:
            return
        # MultiTaskDefinition wire shape (reference ballista.proto:440-463 +
        # task_manager.rs:583-650): one encoded stage plan + N task
        # envelopes, so the plan crosses the wire once per stage, not once
        # per task
        host, port = self._addr(executor_id)
        payload = {"stages": group_tasks_by_plan(objs)}
        if self.endpoint is not None:
            payload["scheduler"] = {"host": self.endpoint[0],
                                    "port": self.endpoint[1]}
        try:
            call_with_retry(host, port, "launch_multi_task", payload,
                            policy=self.policy)
        except wire.RemoteError as e:
            if "'tasks'" not in str(e):
                raise
            # mixed-version rollout: an executor predating the grouped
            # shape KeyErrors on payload['tasks'] — resend flat once
            log.info("executor %s speaks the legacy launch shape", executor_id)
            call_with_retry(host, port, "launch_multi_task", {"tasks": objs},
                            policy=self.policy)

    def cancel_tasks(self, executor_id: str, job_id: str) -> None:
        if faults.dropped("scheduler.cancel.fanout",
                          executor_id=executor_id, job_id=job_id):
            # chaos: simulate the lost cancel RPC this method otherwise
            # swallows below — heartbeat zombie reconciliation must reap
            return
        try:
            host, port = self._addr(executor_id)
            call_with_retry(host, port, "cancel_tasks", {"job_id": job_id},
                            policy=self.policy)
        except Exception:  # noqa: BLE001 — best effort: delivery failures
            # are logged and swallowed; the executor's heartbeat `running`
            # set lets the scheduler re-issue the kill (zombie reaping)
            log.warning("cancel_tasks on %s failed", executor_id, exc_info=True)

    def cancel_task(self, executor_id: str, task) -> None:
        if faults.dropped("scheduler.cancel.fanout",
                          executor_id=executor_id, job_id=task.job_id):
            return
        try:
            host, port = self._addr(executor_id)
            call_with_retry(host, port, "cancel_task",
                            {"task": serde.taskid_to_obj(task)},
                            policy=self.policy)
        except Exception:  # noqa: BLE001 — best effort (the loser's late
            # result is discarded by the graph's attempt bookkeeping anyway)
            log.warning("cancel_task on %s failed", executor_id, exc_info=True)

    def clean_job_data(self, executor_id: str, job_id: str) -> None:
        host, port = self._addr(executor_id)
        call_with_retry(host, port, "remove_job_data", {"job_id": job_id},
                        policy=self.policy)


class SchedulerNetService:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 config: Optional[BallistaConfig] = None,
                 scheduler_config: Optional[SchedulerConfig] = None,
                 rest_port: Optional[int] = None,
                 state_dir: Optional[str] = None,
                 cluster_url: Optional[str] = None,
                 flight_port: Optional[int] = None):
        self.config = config or BallistaConfig()
        # arm the failpoint plan (no-op unless ballista.faults.plan or
        # BALLISTA_FAULTS_PLAN is set) before any instrumented site runs
        faults.configure(self.config)
        # flight recorder: honour the session config here — SchedulerServer
        # itself only sees process defaults/env.  Enable-only (a journal a
        # test already turned on stays on), and before SchedulerServer is
        # built so its init names the actor.
        from ..utils.config import (JOURNAL_CAPACITY, JOURNAL_ENABLED,
                                    JOURNAL_SPILL_PATH)

        if bool(self.config.get(JOURNAL_ENABLED)):
            from ..obs import journal

            journal.set_enabled(True)
            journal.configure(
                capacity=int(self.config.get(JOURNAL_CAPACITY)),
                spill_path=str(self.config.get(JOURNAL_SPILL_PATH)))
        if scheduler_config is None:
            # honour the session config's cluster keys when the caller did
            # not hand us an explicit SchedulerConfig — one timeout key
            # (ballista.cluster.executor_timeout_s) governs offers, the
            # reaper, and the REST summary alike
            from ..utils.config import (
                CLUSTER_EXECUTOR_TIMEOUT_S,
                FLEET_ADOPT_INTERVAL_S,
                FLEET_LEASE_RENEW_S,
                FLEET_LEASE_TTL_S,
                FLEET_REGISTRY_STALE_S,
                LIVE_DOCTOR_INTERVAL_S,
                LIVE_ENABLED,
                POISON_DISTINCT_EXECUTORS,
                QUARANTINE_FAILURES,
                QUARANTINE_PROBATION_S,
                QUERY_DEADLINE_S,
                SLO_P99_TARGET_MS,
                SLO_WINDOW_S,
                SPECULATION_ENABLED,
                SPECULATION_INTERVAL_S,
                SPECULATION_MAX_CONCURRENT,
                SPECULATION_MIN_RUNTIME_S,
                SPECULATION_MULTIPLIER,
                SPECULATION_QUANTILE,
            )

            scheduler_config = SchedulerConfig(
                executor_timeout_s=float(
                    self.config.get(CLUSTER_EXECUTOR_TIMEOUT_S)),
                fleet_lease_ttl_s=float(
                    self.config.get(FLEET_LEASE_TTL_S)),
                fleet_lease_renew_s=float(
                    self.config.get(FLEET_LEASE_RENEW_S)),
                fleet_adopt_interval_s=float(
                    self.config.get(FLEET_ADOPT_INTERVAL_S)),
                fleet_registry_stale_s=float(
                    self.config.get(FLEET_REGISTRY_STALE_S)),
                quarantine_failures=int(
                    self.config.get(QUARANTINE_FAILURES)),
                quarantine_probation_s=float(
                    self.config.get(QUARANTINE_PROBATION_S)),
                speculation_enabled=bool(
                    self.config.get(SPECULATION_ENABLED)),
                speculation_quantile=float(
                    self.config.get(SPECULATION_QUANTILE)),
                speculation_multiplier=float(
                    self.config.get(SPECULATION_MULTIPLIER)),
                speculation_min_runtime_s=float(
                    self.config.get(SPECULATION_MIN_RUNTIME_S)),
                speculation_max_concurrent=int(
                    self.config.get(SPECULATION_MAX_CONCURRENT)),
                speculation_interval_s=float(
                    self.config.get(SPECULATION_INTERVAL_S)),
                live_enabled=bool(self.config.get(LIVE_ENABLED)),
                live_doctor_interval_s=float(
                    self.config.get(LIVE_DOCTOR_INTERVAL_S)),
                slo_p99_target_ms=float(self.config.get(SLO_P99_TARGET_MS)),
                slo_window_s=float(self.config.get(SLO_WINDOW_S)),
                query_deadline_s=float(self.config.get(QUERY_DEADLINE_S)),
                poison_distinct_executors=int(
                    self.config.get(POISON_DISTINCT_EXECUTORS)))
        self.catalog = SchemaCatalog()
        launcher = NetTaskLauncher(RetryPolicy.from_config(self.config))
        job_backend = None
        cluster_state = None
        if cluster_url:
            # shared KV backend: job checkpoints AND slot accounting go
            # through one store so sibling schedulers cooperate (kv.py)
            from .kv import KvClusterState, KvJobStateBackend
            from .kv_remote import open_remote_or_local

            sc = scheduler_config or SchedulerConfig()
            # kv://host:port -> networked KV service (multi-host HA);
            # memory:// / sqlite:/// -> embedded
            store = open_remote_or_local(cluster_url)
            job_backend = KvJobStateBackend(store,
                                            lease_ttl_s=sc.fleet_lease_ttl_s)
            cluster_state = KvClusterState(store, sc.task_distribution)
        elif state_dir:
            from .persistence import FileJobStateBackend

            job_backend = FileJobStateBackend(state_dir)
        from ..obs import JobObservability

        self.server = SchedulerServer(
            launcher, scheduler_config,
            job_backend=job_backend,
            cluster_state=cluster_state,
            observability=JobObservability.from_config(self.config))
        launcher.scheduler = self.server
        self.rpc = RpcServer(host, port)
        self.host, self.port = self.rpc.host, self.rpc.port
        # published to the shard registry + job leases so a surviving shard
        # (and redirected clients) can name where this scheduler serves;
        # launch payloads carry it so executors route statuses back here
        self.server.client_endpoint = f"{self.host}:{self.port}"
        launcher.endpoint = (self.host, self.port)
        # job -> result schema, LRU-bounded: clients fetch results right
        # after completion, so old entries are dead weight in a long-running
        # daemon
        from collections import OrderedDict

        self._final_schemas: "OrderedDict[str, Schema]" = OrderedDict()
        self._max_schemas = 1024
        self._lock = threading.Lock()
        self._default_prepared: Dict[str, tuple] = {}
        # result-cache hits parked for one fetch_result round-trip: the
        # execute_query reply stays a tiny job handle either way, and the
        # client pulls the bytes exactly once (entries are popped)
        self._cached_results: "OrderedDict[str, dict]" = OrderedDict()
        self._max_cached_results = 64

        # per-session isolation (reference session_manager.rs:27-57; the
        # Flight-SQL-analog surface below opens one session per client)
        from .session import SessionManager

        self.sessions = SessionManager(self.config, self.catalog)

        r = self.rpc.register
        r("create_session", self._create_session)
        r("update_session", self._update_session)
        r("remove_session", self._remove_session)
        r("prepare", self._prepare)
        r("explain", self._explain)
        r("execute_query", self._execute_query)
        r("get_job_status", self._get_job_status)
        r("watch_job", self._watch_job)
        r("fetch_result", self._fetch_result)
        r("cancel_job", self._cancel_job)
        r("register_executor", self._register_executor)
        r("heartbeat", self._heartbeat)
        r("update_task_status", self._update_task_status)
        r("poll_work", self._poll_work)
        r("executor_stopped", self._executor_stopped)
        r("register_table", self._register_table)
        r("register_external_table", self._register_external_table)
        r("get_file_metadata", self._get_file_metadata)
        r("list_tables", self._list_tables)
        r("table_schema", self._table_schema)
        r("deregister_table", self._deregister_table)
        r("ping", lambda p, b: ({}, b""))

        self.rest = None
        if rest_port is not None:
            from .rest import RestApi

            self.rest = RestApi(self.server, host, rest_port)

        # Arrow Flight (SQL) front door (reference flight_sql.rs:83-911)
        self.flight = None
        if flight_port is not None:
            from .flight_service import BallistaFlightServer

            self.flight = BallistaFlightServer(self, host, flight_port)

    def start(self) -> None:
        import time as _time

        self.server._started_at = int(_time.time())
        self.server.init()
        self.rpc.start()
        if self.rest is not None:
            self.rest.start()
        if self.flight is not None:
            self.flight.start()
        if self.server.job_backend is not None:
            self.server.recover_jobs()

    def stop(self) -> None:
        self.server.shutdown()
        self.rpc.stop()
        if self.rest is not None:
            self.rest.stop()
        if self.flight is not None:
            self.flight.stop()

    def kill(self) -> None:
        """Crash-simulate this shard inside one process (chaos harness):
        tear the RPC listener and background threads down WITHOUT the
        goodbyes a clean stop performs — no registry withdrawal, no lease
        release.  Held job leases simply stop renewing, exactly like
        kill -9, so a sibling shard must adopt them through lease expiry
        (the registry entry ages out at the stale cutoff the same way)."""
        self.server.shutdown(withdraw=False)
        self.rpc.stop()
        if self.rest is not None:
            self.rest.stop()
        if self.flight is not None:
            self.flight.stop()

    # --- sessions (the Flight SQL handshake analog) -----------------------
    def _session_ctx(self, payload: dict):
        """Resolve (catalog, config) for a request: its session's when a
        session_id is given, the shared defaults otherwise; per-request
        config overrides apply on top either way."""
        session = self.sessions.get(payload.get("session_id"))
        base_catalog = session.catalog if session else self.catalog
        base_settings = (session.config if session else self.config)._settings
        overrides = payload.get("config", {})
        config = BallistaConfig({**base_settings, **overrides}) \
            if overrides or session else self.config
        return session, base_catalog, config

    def _create_session(self, payload: dict, _bin: bytes):
        s = self.sessions.create_session(payload.get("settings"))
        return {"session_id": s.id,
                "settings": dict(s.config._settings)}, b""

    def _update_session(self, payload: dict, _bin: bytes):
        s = self.sessions.update_session(payload["session_id"],
                                         payload.get("settings", {}))
        return {"settings": dict(s.config._settings)}, b""

    def _remove_session(self, payload: dict, _bin: bytes):
        self.sessions.remove_session(payload["session_id"])
        return {}, b""

    def _prepare(self, payload: dict, _bin: bytes):
        """Prepared statement: validate + plan once, return the result
        schema (reference FlightSqlServiceImpl prepared statements,
        flight_sql.rs:483-560).  Execute later via execute_query with
        {"statement_id": ...}."""
        import uuid as uuidmod

        from ..sql.optimizer import optimize
        from ..sql.parser import parse_sql
        from ..sql.planner import SqlToRel

        session, catalog, _config = self._session_ctx(payload)
        sql = payload["sql"]
        logical = optimize(SqlToRel(catalog).plan(parse_sql(sql)))
        stmt_id = f"stmt-{uuidmod.uuid4().hex[:12]}"
        holder = session.prepared if session else self._default_prepared
        holder[stmt_id] = (sql, logical.schema)
        while len(holder) > 256:
            holder.pop(next(iter(holder)))
        return {"statement_id": stmt_id,
                "schema": serde.schema_to_obj(logical.schema)}, b""

    def _explain(self, payload: dict, _bin: bytes):
        """EXPLAIN over the wire: the scheduler owns the catalog in remote
        deployments, so planning happens here; clients get plan rows."""
        from ..scheduler.physical_planner import explain_rows
        from ..sql import ast as sqlast
        from ..sql.parser import parse_sql

        _session, catalog, config = self._session_ctx(payload)
        stmt = parse_sql(payload["sql"])
        verbose = False
        if isinstance(stmt, sqlast.Explain):
            if stmt.analyze:
                raise PlanningError(
                    "EXPLAIN ANALYZE is not supported over the wire: run "
                    "the query, then read GET /api/job/<id>/stats on the "
                    "scheduler's REST API for the same report")
            verbose = stmt.verbose
            stmt = stmt.statement
        return {"rows": explain_rows(catalog, config, stmt, verbose)}, b""

    # --- query handling --------------------------------------------------
    def _execute_query(self, payload: dict, _bin: bytes):
        session, catalog, session_config = self._session_ctx(payload)
        if "statement_id" in payload:
            holder = session.prepared if session else self._default_prepared
            entry = holder.get(payload["statement_id"])
            if entry is None:
                raise PlanningError(
                    f"unknown prepared statement {payload['statement_id']!r}")
            sql = entry[0]
        else:
            sql = payload["sql"]
        job_id = random_job_id()

        from .serving import prepare_sql_submission

        def schema_cb(schema):
            with self._lock:
                self._final_schemas[job_id] = schema
                while len(self._final_schemas) > self._max_schemas:
                    self._final_schemas.popitem(last=False)

        # subplan_ok=False: spooled stage files are served by filesystem
        # path (port-0 locations), which networked executors cannot reach
        cached, plan_fn, serving = prepare_sql_submission(
            self.server, sql, catalog, session_config, job_id,
            subplan_ok=False, schema_cb=schema_cb)
        if cached is not None:
            with self._lock:
                self._cached_results[job_id] = cached
                while len(self._cached_results) > self._max_cached_results:
                    self._cached_results.popitem(last=False)
            return {"job_id": job_id, "cached": True}, b""

        # tenant identity + quotas ride on the session config (plus any
        # per-request overrides already merged into session_config)
        if session is not None:
            request = session.admission_request(session_config)
        else:
            from ..admission import AdmissionRequest

            request = AdmissionRequest.from_config(session_config)
        self.server.submit_job(job_id, plan_fn, admission=request,
                               trace=payload.get("trace"),
                               config=session_config, serving=serving)
        return {"job_id": job_id}, b""

    def _get_job_status(self, payload: dict, _bin: bytes):
        job_id = payload["job_id"]
        with self._lock:
            cached = self._cached_results.get(job_id)
        if cached is not None:
            return {"state": "successful", "cached": True,
                    "schema": serde.schema_to_obj(cached["schema"])}, b""
        status = self.server.get_job_status(job_id)
        if status is None:
            return self._resolve_foreign_status(job_id), b""
        out = {"state": status.state, "error": status.error,
               "retriable": status.retriable}
        if status.state == "successful":
            out["locations"] = {
                str(part): [serde.location_to_obj(l) for l in locs]
                for part, locs in status.locations.items()}
            with self._lock:
                schema = self._final_schemas.get(job_id)
            if schema is None:
                # adopted job: the submit-time schema cache lives on the
                # shard that PLANNED it — re-derive from the final stage
                graph = self.server.jobs.get_graph(job_id)
                if graph is not None:
                    final = graph.stages[graph.final_stage_id]
                    schema = (final.resolved_plan or final.plan).schema
            if schema is not None:
                out["schema"] = serde.schema_to_obj(schema)
        return out, b""

    def _watch_job(self, payload: dict, _bin: bytes):
        """One long-poll watch frame: the job's journal events past
        ``cursor`` plus a live progress snapshot and the current state.
        The client's ``ctx.watch()`` stitches frames into a single stream
        and follows lease adoption (PR 11): when the answering shard
        changes it resets the cursor to 0 — the adopted shard re-seeded
        its timeline from the checkpoint, so indices restart — and dedups
        replayed events on (actor, seq).  Blocking here is fine: the RPC
        server is one thread per connection."""
        import time as _time

        from ..obs import journal
        from ..obs.progress import job_progress

        job_id = payload["job_id"]
        cursor = max(0, int(payload.get("cursor", 0)))
        timeout_s = min(max(float(payload.get("timeout_s", 0.25)), 0.0), 5.0)
        deadline = _time.monotonic() + timeout_s
        while True:
            with self._lock:
                cached = job_id in self._cached_results
            if cached:
                return {"state": "successful", "cached": True,
                        "scheduler_id": self.server.scheduler_id,
                        "cursor": cursor, "events": [],
                        "progress": None}, b""
            status = self.server.get_job_status(job_id)
            if status is None:
                # foreign job: same redirect shape as get_job_status —
                # the reply names the owning shard's endpoint
                return self._resolve_foreign_status(job_id), b""
            timeline = journal.job_timeline(job_id)
            if cursor > len(timeline):
                cursor = 0  # timeline restarted (adoption re-seed)
            events = timeline[cursor:]
            terminal = status.state in ("successful", "failed", "cancelled")
            if events or terminal or _time.monotonic() >= deadline:
                graph = self.server.jobs.get_graph(job_id)
                progress = job_progress(graph) if graph is not None else None
                return {"state": status.state, "error": status.error,
                        "scheduler_id": self.server.scheduler_id,
                        "cursor": cursor + len(events),
                        "events": events, "progress": progress}, b""
            _time.sleep(0.05)

    def _resolve_foreign_status(self, job_id: str) -> dict:
        """A job this shard is not driving: consult the shared KV so
        clients polling the wrong shard after a failover either get
        redirected (lease held by a sibling — the reply names the owner's
        endpoint) or served directly (the job finished and its lease was
        released: the checkpointed graph is the source of truth, and the
        result schema is re-derived from the final stage's plan because
        ``_final_schemas`` is shard-local)."""
        backend = self.server.job_backend
        if backend is None or not hasattr(backend, "get_lease"):
            return {"state": "not_found"}
        try:
            lease = backend.get_lease(job_id)
            if lease is not None and lease.owner != self.server.scheduler_id:
                return {"state": "not_found", "owner": lease.owner,
                        "endpoint": lease.endpoint}
            graph = backend.load_job(job_id)
        except Exception:  # noqa: BLE001 — KV blip: look lost, not failed
            log.exception("foreign-status resolution failed for %s", job_id)
            return {"state": "not_found"}
        if graph is None or graph.status not in ("successful", "failed"):
            return {"state": "not_found"}
        if graph.status == "failed":
            return {"state": "failed", "error": graph.error,
                    "retriable": False}
        graph.addr_resolver = self.server._resolve_addr
        final = graph.stages[graph.final_stage_id]
        locations = final.output_locations(graph.addr_resolver)
        return {"state": "successful", "error": "", "retriable": False,
                "locations": {
                    str(part): [serde.location_to_obj(l) for l in locs]
                    for part, locs in locations.items()},
                "schema": serde.schema_to_obj(
                    (final.resolved_plan or final.plan).schema)}

    def _fetch_result(self, payload: dict, _bin: bytes):
        """One-shot pull of a parked result-cache hit: the reply payload
        lists ``[partition, [blob_len, ...]]`` per partition and the binary
        channel carries the Arrow IPC file blobs concatenated in that
        order (same bytes the executors wrote, so decode is bit-identical
        to the uncached fetch path)."""
        job_id = payload["job_id"]
        with self._lock:
            cached = self._cached_results.pop(job_id, None)
        if cached is None:
            raise PlanningError(f"no cached result parked for job {job_id}")
        parts = []
        blob = bytearray()
        for part, blobs in cached["partitions"]:
            parts.append([part, [len(b) for b in blobs]])
            for b in blobs:
                blob.extend(b)
        return {"partitions": parts,
                "schema": serde.schema_to_obj(cached["schema"])}, bytes(blob)

    def _cancel_job(self, payload: dict, _bin: bytes):
        self.server.cancel_job(payload["job_id"])
        return {}, b""

    # --- executor control ------------------------------------------------
    def _register_executor(self, payload: dict, _bin: bytes):
        self.server.register_executor(
            serde.executor_metadata_from_obj(payload["meta"]))
        return {}, b""

    def _heartbeat(self, payload: dict, _bin: bytes):
        # failpoint: the heartbeat reached the scheduler but is discarded
        # before it touches cluster state — the executor ages toward the
        # offer cutoff / reaper timeout exactly as if the packet was lost
        if faults.dropped("scheduler.heartbeat.receive",
                          executor_id=payload.get("executor_id")):
            return {}, b""
        meta = payload.get("meta")
        self.server.heartbeat(ExecutorHeartbeat(
            payload["executor_id"], status=payload.get("status", "active"),
            metadata=serde.executor_metadata_from_obj(meta) if meta else None,
            memory_pressure=float(payload.get("memory_pressure", 0.0)),
            running=[tuple(t) for t in payload.get("running", [])]))
        return {}, b""

    def _update_task_status(self, payload: dict, _bin: bytes):
        if faults.dropped("scheduler.status.receive",
                          executor_id=payload.get("executor_id"),
                          count=len(payload.get("statuses", []))):
            # swallow the report: the executor's reporter loop keeps the
            # statuses pending and must redeem them on a later attempt
            raise ConnectionError(
                "failpoint scheduler.status.receive dropped the report")
        statuses = [serde.status_from_obj(s) for s in payload["statuses"]]
        # a status report is proof of life: refresh the heartbeat timestamp
        # (without clobbering status) so a busy executor whose heartbeat
        # thread is starved is not reaped while actively reporting work
        self.server.cluster.touch_heartbeat(payload["executor_id"])
        self.server.update_task_status(payload["executor_id"], statuses)
        return {}, b""

    def _poll_work(self, payload: dict, _bin: bytes):
        statuses = [serde.status_from_obj(s) for s in payload.get("statuses", [])]
        executor_id = payload["executor_id"]
        tasks = self.server.poll_work(executor_id,
                                      payload.get("num_free_slots", 0), statuses)
        # per-task guard: an unserializable plan must fail its job, not
        # strand already-popped tasks as running forever.  Grouped shape:
        # the stage plan is wire-encoded once, not once per task.
        objs = serialize_tasks_or_fail(self.server, executor_id, tasks)
        return {"stages": group_tasks_by_plan(objs)}, b""

    def _executor_stopped(self, payload: dict, _bin: bytes):
        self.server.executor_stopped(payload["executor_id"],
                                     payload.get("reason", ""))
        return {}, b""

    # --- catalog (session-scoped when a session_id is supplied) -----------
    def _register_table(self, payload: dict, binary: bytes):
        import io

        import pyarrow.ipc as ipc

        _session, catalog, _ = self._session_ctx(payload)
        table = ipc.open_stream(io.BytesIO(binary)).read_all()
        catalog.register(MemoryTable(payload["name"], table))
        return {}, b""

    def _register_external_table(self, payload: dict, _bin: bytes):
        _session, catalog, _ = self._session_ctx(payload)
        name, fmt, path = payload["name"], payload["format"], payload["path"]
        schema = serde.schema_from_obj(payload["schema"]) if payload.get("schema") else None
        if fmt == "parquet":
            catalog.register(ParquetTable(name, path, schema))
        elif fmt == "csv":
            catalog.register(CsvTable(
                name, path, schema, payload.get("delimiter", ","),
                payload.get("has_header", True)))
        elif fmt == "json":
            from ..catalog import JsonTable

            catalog.register(JsonTable(name, path, schema))
        elif fmt == "avro":
            from ..catalog import AvroTable

            catalog.register(AvroTable(name, path, schema))
        else:
            raise PlanningError(f"unsupported format {fmt!r}")
        return {}, b""

    def _get_file_metadata(self, payload: dict, _bin: bytes):
        """Schema inference for a file path (reference
        SchedulerGrpc.get_file_metadata, grpc.rs:271-325)."""
        from ..catalog import AvroTable, CsvTable, JsonTable, ParquetTable

        path = payload["path"]
        fmt = payload.get("format") or (
            "parquet" if path.endswith(".parquet") else
            "avro" if path.endswith(".avro") else
            "json" if path.endswith((".json", ".jsonl", ".ndjson")) else "csv")
        provider = {"parquet": ParquetTable, "csv": CsvTable,
                    "json": JsonTable, "avro": AvroTable}[fmt]
        schema = provider("__meta", path).schema
        return {"format": fmt, "schema": serde.schema_to_obj(schema)}, b""

    def _list_tables(self, payload: dict, _bin: bytes):
        _session, catalog, _ = self._session_ctx(payload)
        return {"tables": catalog.table_names()}, b""

    def _table_schema(self, payload: dict, _bin: bytes):
        _session, catalog, _ = self._session_ctx(payload)
        schema = catalog.table_schema(payload["name"])
        return {"schema": serde.schema_to_obj(schema)}, b""

    def _deregister_table(self, payload: dict, _bin: bytes):
        _session, catalog, _ = self._session_ctx(payload)
        catalog.deregister(payload["name"])
        return {}, b""
