"""Scheduler network service: the SchedulerGrpc surface over the wire.

Parity: reference ballista/scheduler/src/scheduler_server/grpc.rs — the 10
RPC handlers (execute_query, get_job_status, register_executor,
heart_beat_from_executor, update_task_status, executor_stopped, cancel_job,
clean_job_data, …) — plus table registration (the reference client ships
CREATE EXTERNAL TABLE inside the logical plan, context.rs:358-530; here the
scheduler owns the catalog and clients register tables by RPC).

Launching goes through ``NetTaskLauncher`` -> executor launch_multi_task,
i.e. push scheduling (TaskSchedulingPolicy::PushStaged).
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from .. import serde
from ..catalog import CsvTable, MemoryTable, ParquetTable, SchemaCatalog
from ..models.schema import Field, Schema
from ..net.rpc import RpcServer
from ..net import wire
from ..utils.config import BallistaConfig
from ..utils.errors import PlanningError
from .scheduler import SchedulerConfig, SchedulerServer, TaskLauncher, random_job_id
from .types import ExecutorHeartbeat, ExecutorMetadata, TaskDescription

log = logging.getLogger(__name__)


class NetTaskLauncher(TaskLauncher):
    """Pushes tasks to executors over the wire (reference
    DefaultTaskLauncher -> ExecutorGrpc.LaunchMultiTask,
    state/task_manager.rs:69-119)."""

    def __init__(self):
        self.scheduler: Optional[SchedulerServer] = None

    def _addr(self, executor_id: str):
        meta = self.scheduler.cluster.get_executor(executor_id)
        if meta is None:
            raise PlanningError(f"unknown executor {executor_id}")
        return meta.host, meta.grpc_port or meta.port

    def launch_tasks(self, executor_id: str, tasks: List[TaskDescription]) -> None:
        host, port = self._addr(executor_id)
        wire.call(host, port, "launch_multi_task",
                  {"tasks": [serde.task_to_obj(t) for t in tasks]})

    def cancel_tasks(self, executor_id: str, job_id: str) -> None:
        try:
            host, port = self._addr(executor_id)
            wire.call(host, port, "cancel_tasks", {"job_id": job_id})
        except Exception:  # noqa: BLE001 — best effort
            log.warning("cancel_tasks on %s failed", executor_id, exc_info=True)


class SchedulerNetService:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 config: Optional[BallistaConfig] = None,
                 scheduler_config: Optional[SchedulerConfig] = None,
                 rest_port: Optional[int] = None,
                 state_dir: Optional[str] = None):
        self.config = config or BallistaConfig()
        self.catalog = SchemaCatalog()
        launcher = NetTaskLauncher()
        job_backend = None
        if state_dir:
            from .persistence import FileJobStateBackend

            job_backend = FileJobStateBackend(state_dir)
        self.server = SchedulerServer(launcher, scheduler_config,
                                      job_backend=job_backend)
        launcher.scheduler = self.server
        self.rpc = RpcServer(host, port)
        self.host, self.port = self.rpc.host, self.rpc.port
        # job -> result schema, LRU-bounded: clients fetch results right
        # after completion, so old entries are dead weight in a long-running
        # daemon
        from collections import OrderedDict

        self._final_schemas: "OrderedDict[str, Schema]" = OrderedDict()
        self._max_schemas = 1024
        self._lock = threading.Lock()

        r = self.rpc.register
        r("execute_query", self._execute_query)
        r("get_job_status", self._get_job_status)
        r("cancel_job", self._cancel_job)
        r("register_executor", self._register_executor)
        r("heartbeat", self._heartbeat)
        r("update_task_status", self._update_task_status)
        r("poll_work", self._poll_work)
        r("executor_stopped", self._executor_stopped)
        r("register_table", self._register_table)
        r("register_external_table", self._register_external_table)
        r("list_tables", self._list_tables)
        r("table_schema", self._table_schema)
        r("deregister_table", self._deregister_table)
        r("ping", lambda p, b: ({}, b""))

        self.rest = None
        if rest_port is not None:
            from .rest import RestApi

            self.rest = RestApi(self.server, host, rest_port)

    def start(self) -> None:
        import time as _time

        self.server._started_at = int(_time.time())
        self.server.init()
        self.rpc.start()
        if self.rest is not None:
            self.rest.start()
        if self.server.job_backend is not None:
            self.server.recover_jobs()

    def stop(self) -> None:
        self.server.shutdown()
        self.rpc.stop()
        if self.rest is not None:
            self.rest.stop()

    # --- query handling --------------------------------------------------
    def _execute_query(self, payload: dict, _bin: bytes):
        sql = payload["sql"]
        session_config = BallistaConfig({**self.config._settings,
                                         **payload.get("config", {})})
        job_id = random_job_id()

        def plan_fn():
            from ..client.context import extract_scalar
            from ..ops.physical import TaskContext
            from ..sql.optimizer import optimize
            from ..sql.parser import parse_sql
            from ..sql.planner import SqlToRel
            from .physical_planner import PhysicalPlanner

            logical = optimize(SqlToRel(self.catalog).plan(parse_sql(sql)))
            planned = PhysicalPlanner(self.catalog, session_config).plan_query(logical)
            ctx = TaskContext(config=session_config, job_id=f"{job_id}-scalars")
            scalars: Dict[str, object] = {}
            for sid, splan in planned.scalars:
                ctx.scalars = scalars
                scalars[sid] = extract_scalar(splan, ctx)
            with self._lock:
                self._final_schemas[job_id] = planned.plan.schema
                while len(self._final_schemas) > self._max_schemas:
                    self._final_schemas.popitem(last=False)
            return planned.plan, scalars

        self.server.submit_job(job_id, plan_fn)
        return {"job_id": job_id}, b""

    def _get_job_status(self, payload: dict, _bin: bytes):
        job_id = payload["job_id"]
        status = self.server.get_job_status(job_id)
        if status is None:
            return {"state": "not_found"}, b""
        out = {"state": status.state, "error": status.error}
        if status.state == "successful":
            out["locations"] = {
                str(part): [serde.location_to_obj(l) for l in locs]
                for part, locs in status.locations.items()}
            with self._lock:
                schema = self._final_schemas.get(job_id)
            if schema is not None:
                out["schema"] = serde.schema_to_obj(schema)
        return out, b""

    def _cancel_job(self, payload: dict, _bin: bytes):
        self.server.cancel_job(payload["job_id"])
        return {}, b""

    # --- executor control ------------------------------------------------
    def _register_executor(self, payload: dict, _bin: bytes):
        self.server.register_executor(ExecutorMetadata(**payload["meta"]))
        return {}, b""

    def _heartbeat(self, payload: dict, _bin: bytes):
        self.server.heartbeat(ExecutorHeartbeat(
            payload["executor_id"], status=payload.get("status", "active")))
        return {}, b""

    def _update_task_status(self, payload: dict, _bin: bytes):
        statuses = [serde.status_from_obj(s) for s in payload["statuses"]]
        self.server.update_task_status(payload["executor_id"], statuses)
        return {}, b""

    def _poll_work(self, payload: dict, _bin: bytes):
        statuses = [serde.status_from_obj(s) for s in payload.get("statuses", [])]
        tasks = self.server.poll_work(payload["executor_id"],
                                      payload.get("num_free_slots", 0), statuses)
        return {"tasks": [serde.task_to_obj(t) for t in tasks]}, b""

    def _executor_stopped(self, payload: dict, _bin: bytes):
        self.server.executor_stopped(payload["executor_id"],
                                     payload.get("reason", ""))
        return {}, b""

    # --- catalog ---------------------------------------------------------
    def _register_table(self, payload: dict, binary: bytes):
        import io

        import pyarrow.ipc as ipc

        table = ipc.open_stream(io.BytesIO(binary)).read_all()
        self.catalog.register(MemoryTable(payload["name"], table))
        return {}, b""

    def _register_external_table(self, payload: dict, _bin: bytes):
        name, fmt, path = payload["name"], payload["format"], payload["path"]
        schema = serde.schema_from_obj(payload["schema"]) if payload.get("schema") else None
        if fmt == "parquet":
            self.catalog.register(ParquetTable(name, path, schema))
        elif fmt == "csv":
            self.catalog.register(CsvTable(
                name, path, schema, payload.get("delimiter", ","),
                payload.get("has_header", True)))
        else:
            raise PlanningError(f"unsupported format {fmt!r}")
        return {}, b""

    def _list_tables(self, payload: dict, _bin: bytes):
        return {"tables": self.catalog.table_names()}, b""

    def _table_schema(self, payload: dict, _bin: bytes):
        schema = self.catalog.table_schema(payload["name"])
        return {"schema": serde.schema_to_obj(schema)}, b""

    def _deregister_table(self, payload: dict, _bin: bytes):
        self.catalog.deregister(payload["name"])
        return {}, b""
