"""File-backed job state: crash-safe checkpoints + multi-scheduler adoption.

Parity: the reference's KV-backed JobState (sled embedded store,
reference ballista/scheduler/src/cluster/kv.rs save_job +
cluster/storage/sled.rs) and ``try_acquire_job`` ownership takeover
(cluster/mod.rs:347-350): graphs are persisted on every transition; a
restarted or sibling scheduler lists persisted jobs, acquires a lock, and
resumes from the last checkpoint (shuffle files on executors are the data
checkpoints).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .. import serde
from .execution_graph import ExecutionGraph


class FileJobStateBackend:
    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self._lock = threading.Lock()

    def _job_path(self, job_id: str) -> str:
        return os.path.join(self.state_dir, f"{job_id}.json")

    def save_job(self, graph: ExecutionGraph) -> None:
        """Atomic write (tmp + rename), called on every graph transition."""
        obj = serde.graph_to_obj(graph)
        path = self._job_path(graph.job_id)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                json.dump(obj, f, separators=(",", ":"))
            os.replace(tmp, path)

    def load_job(self, job_id: str) -> Optional[ExecutionGraph]:
        path = self._job_path(job_id)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return serde.graph_from_obj(json.load(f))

    def list_jobs(self) -> List[str]:
        return sorted(p[:-5] for p in os.listdir(self.state_dir)
                      if p.endswith(".json"))

    def remove_job(self, job_id: str) -> None:
        with self._lock:
            for suffix in (".json", ".lock"):
                try:
                    os.remove(os.path.join(self.state_dir, job_id + suffix))
                except FileNotFoundError:
                    pass

    # --- ownership (reference try_acquire_job) ---------------------------
    def try_acquire_job(self, job_id: str, owner: str,
                        stale_after_s: float = 60.0) -> bool:
        """Exclusive claim via O_EXCL lockfile; stale locks (dead owner,
        no heartbeat) are broken after ``stale_after_s``."""
        lock = os.path.join(self.state_dir, f"{job_id}.lock")
        payload = json.dumps({"owner": owner, "ts": time.time()}).encode()
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, payload)
            os.close(fd)
            return True
        except FileExistsError:
            try:
                with open(lock) as f:
                    holder = json.load(f)
                if holder.get("owner") == owner:
                    return True
                if time.time() - holder.get("ts", 0) > stale_after_s:
                    return self._break_stale_lock(lock, owner, stale_after_s)
            except (OSError, ValueError):
                pass
            return False

    def _break_stale_lock(self, lock: str, owner: str,
                          stale_after_s: float) -> bool:
        """Atomic stale-lock takeover: an O_EXCL ``.takeover`` sentinel
        elects exactly one winner; the winner re-verifies staleness inside
        the critical section (a racer that slipped in between the caller's
        check and here would have refreshed the lock) and atomically
        replaces the lock via tmp+rename.  Losers return False and retry
        on a later cycle."""
        takeover = lock + ".takeover"
        try:
            st = os.stat(takeover)
            if time.time() - st.st_mtime > stale_after_s:
                # takeover sentinel itself abandoned (winner died mid-swap)
                try:
                    os.remove(takeover)
                except OSError:
                    pass
            return False  # someone is (or was) mid-takeover; try next cycle
        except FileNotFoundError:
            pass
        try:
            fd = os.open(takeover, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        try:
            # critical section: re-verify the lock is still stale
            try:
                with open(lock) as f:
                    holder = json.load(f)
                if holder.get("owner") != owner and \
                        time.time() - holder.get("ts", 0) <= stale_after_s:
                    return False  # refreshed by a racer before we won
            except (OSError, ValueError):
                pass
            tmp = lock + ".new"
            with open(tmp, "w") as f:
                json.dump({"owner": owner, "ts": time.time()}, f)
            os.replace(tmp, lock)
            return True
        finally:
            try:
                os.remove(takeover)
            except OSError:
                pass

    def renew_lock(self, job_id: str, owner: str) -> None:
        lock = os.path.join(self.state_dir, f"{job_id}.lock")
        try:
            with open(lock, "w") as f:
                json.dump({"owner": owner, "ts": time.time()}, f)
        except OSError:
            pass
