"""Control-plane message types.

Python mirrors of the reference's protobuf contract
(reference ballista/core/proto/ballista.proto): task identity/status with
the full failure taxonomy (ballista.proto:360-431), executor metadata and
heartbeats (284-358), and task definitions (440-463).  These are plain
dataclasses — the wire encoding for remote mode lives in
``arrow_ballista_tpu/net/wire.py`` and serializes exactly these shapes.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional

from ..ops.shuffle import PartitionLocation, ShuffleWritePartition

# failure taxonomy (ballista.proto:391-431 FailedTask oneof)
EXECUTION_ERROR = "ExecutionError"      # fatal: fails the job
FETCH_PARTITION_ERROR = "FetchPartitionError"  # re-run producer stage
IO_ERROR = "IOError"                    # retryable on another executor
EXECUTOR_LOST = "ExecutorLost"          # retryable
RESULT_LOST = "ResultLost"              # retryable, outputs discarded
TASK_KILLED = "TaskKilled"              # cancellation
# memory-governor denial that could not degrade to spill: retryable
# back-pressure (ideally on a less-loaded executor) and NEVER a
# quarantine strike — an executor protecting itself from OOM is healthy
RESOURCE_EXHAUSTED = "ResourceExhausted"

# distinct terminal error markers (JobStatus.error prefix; the state stays
# 'failed' so every terminal-tuple consumer keeps working unchanged)
DEADLINE_EXCEEDED = "DeadlineExceeded"   # server-side deadline enforcement
POISON_QUERY = "PoisonQuery"             # poison-task containment


@dataclasses.dataclass
class TaskId:
    job_id: str
    stage_id: int
    partition: int
    # monotonically increasing per (stage_attempt, partition): every launch
    # — retry or speculative duplicate — gets a fresh attempt id, so the
    # scheduler can tell a winner's status from a loser's (reference
    # execution_graph.rs task-attempt bookkeeping)
    task_attempt: int = 0
    stage_attempt: int = 0
    # True for a speculative duplicate launched against a straggling
    # original attempt; first success wins either way
    speculative: bool = False


@dataclasses.dataclass
class TaskDescription:
    """A runnable task handed to an executor (parity: TaskDefinition,
    ballista.proto:440-452)."""

    task: TaskId
    plan: "object"  # ShuffleWriterExec root (encoded bytes in remote mode)
    task_internal_id: int = 0
    # job-level scalar-subquery values, shipped with every task (the
    # reference ships session props the same way, ballista.proto:446-449)
    scalars: Dict[str, object] = dataclasses.field(default_factory=dict)
    # trace propagation context ({"trace_id", "span_id"} of the job's
    # execution span); empty when tracing is disabled
    trace: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FailedReason:
    kind: str  # one of the taxonomy constants
    message: str = ""
    # FetchPartitionError details (ballista.proto:399-404)
    map_stage_id: int = -1
    map_partition_id: int = -1
    executor_id: str = ""

    @property
    def retryable(self) -> bool:
        return self.kind in (IO_ERROR, EXECUTOR_LOST, RESULT_LOST,
                             RESOURCE_EXHAUSTED)

    @property
    def count_to_failures(self) -> bool:
        # RESOURCE_EXHAUSTED counts toward task attempts (bounding retry
        # loops against a saturated cluster) but is exempted from
        # quarantine strikes (scheduler._record_quarantine_signals)
        return self.kind in (IO_ERROR, RESOURCE_EXHAUSTED)


@dataclasses.dataclass
class TaskStatus:
    """Executor -> scheduler task outcome (ballista.proto:360-390)."""

    task: TaskId
    executor_id: str
    state: str  # 'success' | 'failed' | 'killed'
    shuffle_writes: List[ShuffleWritePartition] = dataclasses.field(default_factory=list)
    failure: Optional[FailedReason] = None
    launch_time_ms: int = 0
    start_time_ms: int = 0
    end_time_ms: int = 0
    metrics: Dict[str, Dict[str, int]] = dataclasses.field(default_factory=dict)
    # identity of the executing PROCESS (not executor: in-proc standalone
    # executors share one process and thus one plan instance / MetricsSet;
    # stage metric aggregation must dedupe cumulative snapshots per process)
    process_id: str = ""
    # task span tree (obs.tracing.Span objects; serialized with the
    # status over the wire, empty when tracing is disabled)
    spans: List[object] = dataclasses.field(default_factory=list)
    # device-observatory fold for this task (obs/device.py task_scope):
    # jit compiles/retraces/cache hits, compile seconds, h2d/d2h
    # bytes+seconds, memory watermark peaks.  Empty dict when the
    # observatory is off — and then it serializes to NO wire key, so
    # disabled mode is byte-identical to the pre-observatory wire format
    device_stats: Dict[str, float] = dataclasses.field(default_factory=dict)
    # flight-recorder events captured during this task's run
    # (obs/journal.py task_scope; wire-ready dicts).  Same wire contract
    # as device_stats: empty list serializes to NO key, so journal-off is
    # byte-identical to the pre-journal wire format
    journal: List[Dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ExecutorMetadata:
    """ballista.proto:284-300."""

    executor_id: str
    host: str = "localhost"
    port: int = 0
    grpc_port: int = 0
    task_slots: int = 1


@dataclasses.dataclass
class ExecutorHeartbeat:
    executor_id: str
    timestamp: float = dataclasses.field(default_factory=time.time)
    status: str = "active"  # 'active' | 'dead' | 'terminating'
    # carried so a restarted scheduler can auto re-register unknown
    # heartbeaters (reference heart_beat_from_executor, grpc.rs:174-241)
    metadata: Optional[ExecutorMetadata] = None
    # memory governor pressure in [0, 1] (fraction of the most-loaded
    # budgeted pool in use): degrades this executor's offer ordering and,
    # past ballista.memory.pressure.shed.threshold, feeds admission shed.
    # 0.0 (the unbudgeted default) is omitted on the wire.
    memory_pressure: float = 0.0
    # in-flight (job_id, stage_id, partition, task_attempt) tuples on this
    # executor: the scheduler diffs them against graph truth and re-issues
    # kills for zombies whose cancel RPC was lost.  Empty (the idle
    # default) is omitted on the wire.
    running: List[tuple] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ExecutorReservation:
    """A reserved task slot, optionally job-affine (parity:
    reference scheduler state/executor_manager.rs:48-66)."""

    executor_id: str
    job_id: Optional[str] = None


# job status (ballista.proto:528-663 JobStatus oneof)
JOB_QUEUED = "queued"
JOB_RUNNING = "running"
JOB_SUCCESSFUL = "successful"
JOB_FAILED = "failed"
JOB_CANCELLED = "cancelled"


@dataclasses.dataclass
class JobStatus:
    job_id: str
    state: str
    error: str = ""
    # successful: per output-partition locations of the final stage
    locations: Dict[int, List[PartitionLocation]] = dataclasses.field(default_factory=dict)
    # failed + retriable: the failure is transient back-pressure (admission
    # queue full / timed out) — clients should back off and resubmit
    retriable: bool = False


@dataclasses.dataclass
class JobLease:
    """A scheduler shard's ownership claim on a job, stored in the shared
    KV (scheduler/kv.py JOB_LOCKS keyspace).  The epoch is the fencing
    token: it increments on every ownership change, and every fenced job
    write is guarded on (owner, epoch) — a partitioned ex-owner whose
    lease was adopted holds a stale epoch and cannot write job state
    (parity: the reference's etcd lease + sled lock in cluster/kv.rs
    try_acquire_job, hardened with epoch fencing)."""

    job_id: str
    owner: str = ""      # scheduler_id of the lease holder
    epoch: int = 0       # bumps on every ownership change, never on renewal
    ts: float = 0.0      # last acquire/renew time (unix seconds)
    endpoint: str = ""   # "host:port" the owner serves clients on
