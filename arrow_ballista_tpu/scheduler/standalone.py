"""Standalone mode: scheduler + executors in one process.

Parity: reference ballista/scheduler/src/standalone.rs +
ballista/executor/src/standalone.rs + BallistaContext::standalone
(client context.rs:142-212) — the full stage-DAG machinery, shuffle files,
and fault-tolerance paths run in-process with no RPC, which is also the
test configuration (SURVEY.md §4).
"""
from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..executor.executor import Executor
from ..models.batch import ColumnBatch
from ..models.ipc import read_ipc_files
from ..ops.physical import TaskContext
from ..utils.config import BallistaConfig
from ..utils.errors import ExecutionError
from .scheduler import (
    SchedulerConfig,
    SchedulerServer,
    TaskLauncher,
    random_job_id,
)
from .types import ExecutorHeartbeat, ExecutorMetadata, TaskDescription


class InProcessTaskLauncher(TaskLauncher):
    """Launch seam wired directly to in-proc Executor objects."""

    def __init__(self):
        self.executors: Dict[str, Executor] = {}
        self.scheduler: Optional[SchedulerServer] = None

    def launch_tasks(self, executor_id: str, tasks: List[TaskDescription]) -> None:
        executor = self.executors[executor_id]
        for task in tasks:
            executor.submit_task(
                task,
                lambda st: self.scheduler.update_task_status(executor_id, [st]))

    def cancel_tasks(self, executor_id: str, job_id: str) -> None:
        from .. import faults

        # same lost-cancel failpoint as NetTaskLauncher: the fanout is the
        # scheduler's to lose whatever the transport — heartbeat zombie
        # reconciliation must reap whatever this drop leaks
        if faults.dropped("scheduler.cancel.fanout",
                          executor_id=executor_id, job_id=job_id):
            return
        self.executors[executor_id].cancel_job_tasks(job_id)

    def cancel_task(self, executor_id: str, task) -> None:
        from .. import faults

        if faults.dropped("scheduler.cancel.fanout",
                          executor_id=executor_id, job_id=task.job_id):
            return
        ex = self.executors.get(executor_id)
        if ex is not None:
            ex.cancel_task(task)

    def clean_job_data(self, executor_id: str, job_id: str) -> None:
        from ..executor.executor import remove_job_data

        remove_job_data(self.executors[executor_id].work_dir, job_id)

    def stop(self) -> None:
        for ex in self.executors.values():
            ex.shutdown()


class StandaloneCluster:
    """In-proc scheduler + N executors sharing a work_dir tree."""

    def __init__(self, config: Optional[BallistaConfig] = None,
                 concurrent_tasks: int = 4, num_executors: int = 1,
                 work_dir: Optional[str] = None,
                 scheduler_config: Optional[SchedulerConfig] = None):
        self.config = config or BallistaConfig()
        # arm failpoints (no-op unless a plan is configured) — standalone
        # runs the same instrumented task/shuffle paths as remote mode
        from .. import faults

        faults.configure(self.config)
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-tpu-")
        self._owns_work_dir = work_dir is None
        from ..obs import JobObservability

        self.launcher = InProcessTaskLauncher()
        if scheduler_config is None:
            # honour the session's ballista.speculation.* and
            # ballista.live./slo.* keys (remote deployments do the same
            # via SchedulerNetService)
            from ..utils.config import (LIVE_DOCTOR_INTERVAL_S,
                                        LIVE_ENABLED,
                                        POISON_DISTINCT_EXECUTORS,
                                        QUERY_DEADLINE_S,
                                        SLO_P99_TARGET_MS,
                                        SLO_WINDOW_S,
                                        SPECULATION_ENABLED,
                                        SPECULATION_INTERVAL_S,
                                        SPECULATION_MAX_CONCURRENT,
                                        SPECULATION_MIN_RUNTIME_S,
                                        SPECULATION_MULTIPLIER,
                                        SPECULATION_QUANTILE)

            scheduler_config = SchedulerConfig(
                speculation_enabled=bool(self.config.get(SPECULATION_ENABLED)),
                speculation_quantile=float(self.config.get(SPECULATION_QUANTILE)),
                speculation_multiplier=float(self.config.get(SPECULATION_MULTIPLIER)),
                speculation_min_runtime_s=float(
                    self.config.get(SPECULATION_MIN_RUNTIME_S)),
                speculation_max_concurrent=int(
                    self.config.get(SPECULATION_MAX_CONCURRENT)),
                speculation_interval_s=float(
                    self.config.get(SPECULATION_INTERVAL_S)),
                live_enabled=bool(self.config.get(LIVE_ENABLED)),
                live_doctor_interval_s=float(
                    self.config.get(LIVE_DOCTOR_INTERVAL_S)),
                slo_p99_target_ms=float(self.config.get(SLO_P99_TARGET_MS)),
                slo_window_s=float(self.config.get(SLO_WINDOW_S)),
                query_deadline_s=float(self.config.get(QUERY_DEADLINE_S)),
                poison_distinct_executors=int(
                    self.config.get(POISON_DISTINCT_EXECUTORS)))
        self.scheduler = SchedulerServer(
            self.launcher, scheduler_config,
            observability=JobObservability.from_config(self.config))
        self.launcher.scheduler = self.scheduler
        self.scheduler.init()
        self.last_job_id: Optional[str] = None
        self.executors: List[Executor] = []
        for i in range(num_executors):
            meta = ExecutorMetadata(executor_id=f"executor-{i}",
                                    task_slots=concurrent_tasks)
            ex = Executor(meta, self.work_dir, self.config,
                          concurrent_tasks=concurrent_tasks)
            self.executors.append(ex)
            self.launcher.executors[meta.executor_id] = ex
            self.scheduler.register_executor(meta)
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="standalone-heartbeat",
                                           daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        # reference executors heartbeat every 60 s (executor_server.rs:465)
        while not self._hb_stop.wait(10.0):
            for ex in self.executors:
                self.scheduler.heartbeat(ExecutorHeartbeat(
                    ex.metadata.executor_id,
                    memory_pressure=ex.governor.pressure(),
                    running=ex.running_task_ids()))

    # --- query execution -------------------------------------------------
    def execute_sql(self, sql_text: str, catalog,
                    config: Optional[BallistaConfig] = None,
                    statement=None) -> List[ColumnBatch]:
        """Serving path: SQL text in, batches out, through the scheduler's
        prepared-plan / result / subplan caches (scheduler/serving.py).  A
        result-cache hit returns decoded bytes without planning or running
        anything; ``execute`` below stays cache-free for pre-planned
        queries (EXPLAIN ANALYZE, chaos/fault harnesses)."""
        from ..models.ipc import read_ipc_buffers
        from .serving import prepare_sql_submission

        config = config or self.config
        job_id = random_job_id()
        cached, plan_fn, serving = prepare_sql_submission(
            self.scheduler, sql_text, catalog, config, job_id,
            subplan_ok=True, work_dir=self.work_dir, statement=statement)
        if cached is not None:
            batches: List[ColumnBatch] = []
            for _part, blobs in cached["partitions"]:
                batches.extend(read_ipc_buffers(blobs, cached["schema"],
                                                capacity=config.batch_size))
            return batches
        self.last_job_id = job_id
        from ..admission import AdmissionRequest
        from ..obs import new_trace_context

        self.scheduler.submit_job(
            job_id, plan_fn,
            admission=AdmissionRequest.from_config(config),
            trace=new_trace_context(), config=config, serving=serving)
        status = self.scheduler.wait_for_job(
            job_id, timeout=float(config.job_timeout_s))
        if status.state == "failed":
            if status.retriable:
                from ..utils.errors import ResourceExhausted

                raise ResourceExhausted(f"job {job_id} shed: {status.error}")
            raise ExecutionError(f"job {job_id} failed: {status.error}")
        if status.state != "successful":
            raise ExecutionError(f"job {job_id} ended as {status.state}")
        batches = []
        for part in sorted(status.locations):
            paths = [loc.path for loc in status.locations[part] if loc.num_rows]
            batches.extend(read_ipc_files(paths, serving.schema,
                                          capacity=config.batch_size))
        return batches

    def execute(self, planned) -> List[ColumnBatch]:
        """Run a PlannedQuery through the distributed machinery and fetch
        the final-stage output files (the client side of
        DistributedQueryExec, reference distributed_query.rs:226-329)."""
        from ..client.context import extract_scalar

        # scalar subqueries run first, host-side (they are tiny by
        # construction: single-row reductions)
        scalar_ctx = TaskContext(config=self.config, work_dir=self.work_dir,
                                 job_id="scalars")
        scalars: Dict[str, object] = {}
        for sid, splan in planned.scalars:
            scalar_ctx.scalars = scalars
            scalars[sid] = extract_scalar(splan, scalar_ctx)

        job_id = random_job_id()
        # remembered so explain_analyze can find the job's retained graph
        # (and its RuntimeStatsStore) after execute() returns
        self.last_job_id = job_id
        from ..admission import AdmissionRequest
        from ..obs import new_trace_context

        self.scheduler.submit_job(job_id, lambda: (planned.plan, scalars),
                                  admission=AdmissionRequest.from_config(self.config),
                                  trace=new_trace_context(),
                                  config=self.config)
        # deadline is config-driven (round-2 failure mode: a slow first-compile
        # TPU run blew through a hard-coded 300 s wait and "failed" a job that
        # would have finished)
        status = self.scheduler.wait_for_job(job_id,
                                             timeout=float(self.config.job_timeout_s))
        if status.state == "failed":
            if status.retriable:
                from ..utils.errors import ResourceExhausted

                raise ResourceExhausted(f"job {job_id} shed: {status.error}")
            raise ExecutionError(f"job {job_id} failed: {status.error}")
        if status.state != "successful":
            raise ExecutionError(f"job {job_id} ended as {status.state}")

        schema = planned.plan.schema
        batches: List[ColumnBatch] = []
        for part in sorted(status.locations):
            paths = [loc.path for loc in status.locations[part] if loc.num_rows]
            batches.extend(read_ipc_files(paths, schema,
                                          capacity=self.config.batch_size))
        return batches

    def shutdown(self) -> None:
        self._hb_stop.set()
        self._hb_thread.join(timeout=5.0)
        self.scheduler.shutdown()
        if self._owns_work_dir:
            shutil.rmtree(self.work_dir, ignore_errors=True)
