"""ExecutionGraph: per-job DAG of shuffle stages + fault tolerance.

Parity with the reference's scheduler core
(reference ballista/scheduler/src/state/execution_graph.rs:61-1211 and
execution_graph/execution_stage.rs): stages move through

    UNRESOLVED -> RESOLVED/RUNNING -> SUCCESSFUL
         ^                |
         └── rollback ────┘        (FetchPartitionError / executor lost)

``update_task_status`` implements the same lineage-aware recovery
(execution_graph.rs:270-657): a fetch failure rolls the consumer stage back
to UNRESOLVED and re-opens the producer's poisoned map partition; retryable
task errors reset the task; execution errors fail the job.  Retry budgets
mirror task_manager.rs:55-57 (TASK_MAX_FAILURES=4, STAGE_MAX_FAILURES=4).

Design deviation from the reference: consumer input locations are *derived*
from producer stage outputs at resolve time instead of being incrementally
pushed — a stage resolves only when every producer is SUCCESSFUL, at which
point producer outputs are final, so the derived view is equivalent and
removes a whole class of partial-update states.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

from ..ops.shuffle import (
    PartitionLocation,
    ShuffleReaderExec,
    ShuffleWritePartition,
    ShuffleWriterExec,
)
from ..obs import journal
from ..obs.stats import RuntimeStatsStore
from ..utils.errors import InternalError
from .aqe import AqePolicy, maybe_broadcast_switch, rewrite_resolved_stage
from .planner import (
    DistributedPlanner,
    QueryStage,
    collect_nodes,
    remove_unresolved_shuffles,
)
from ..ops.shuffle import UnresolvedShuffleExec
from .types import (
    EXECUTION_ERROR,
    FETCH_PARTITION_ERROR,
    TASK_KILLED,
    FailedReason,
    TaskDescription,
    TaskId,
    TaskStatus,
)

TASK_MAX_FAILURES = 4
STAGE_MAX_FAILURES = 4

UNRESOLVED = "unresolved"
RUNNING = "running"
SUCCESSFUL = "successful"
FAILED = "failed"


@dataclasses.dataclass
class TaskInfo:
    partition: int
    executor_id: str
    state: str  # 'running' | 'success'
    # last TaskStatus for observability: per-operator metrics + launch/end
    # timestamps survive absorption (reference keeps the full status stream
    # in ExecutionGraph for the UI's stage metrics)
    status: object = None
    # attempt id this info belongs to (matches TaskId.task_attempt), so a
    # status from a cancelled duplicate can be told apart from the winner's
    attempt: int = 0
    speculative: bool = False
    # monotonic launch time; age drives the speculation policy
    started_at: float = 0.0


class ExecutionStage:
    def __init__(self, stage_id: int, plan: ShuffleWriterExec):
        self.stage_id = stage_id
        self.plan = plan  # with UnresolvedShuffleExec leaves
        self.partitions = plan.output_partition_count()
        self.producer_ids = sorted(
            {u.stage_id for u in collect_nodes(plan, UnresolvedShuffleExec)})
        self.output_links: List[int] = []
        self.state = UNRESOLVED
        # stage_attempt is a monotonic *epoch*: it identifies which attempt a
        # task belongs to, so late statuses from rolled-back attempts can be
        # dropped.  failures is the *budget* counter checked against
        # STAGE_MAX_FAILURES — rollbacks that aren't the query's fault
        # (executor loss) bump the epoch but not the budget.
        self.stage_attempt = 0
        self.failures = 0
        self.resolved_plan: Optional[ShuffleWriterExec] = None
        self.task_infos: List[Optional[TaskInfo]] = [None] * self.partitions
        self.task_failures: List[int] = [0] * self.partitions
        # next attempt id per partition: every launch — retry duplicate or
        # speculative duplicate — draws a fresh id (keeps planned length,
        # like task_failures, across adaptive coalescing)
        self.task_attempts: List[int] = [0] * self.partitions
        # partition -> in-flight speculative duplicate of a straggling task
        self.speculative_tasks: Dict[int, TaskInfo] = {}
        # partition -> executors where it failed retryably (this stage
        # attempt): retry anti-affinity steers the next attempt to a FRESH
        # executor when one is alive, so a task-level fault either clears
        # (executor was sick) or accumulates the distinct-executor evidence
        # poison containment needs (query is sick)
        self.failed_on: Dict[int, set] = {}
        # completed-attempt durations (s), the speculation-policy baseline
        self.durations: List[float] = []
        # append-only per-attempt history for /api/job/<id> (survives
        # rollbacks: entries carry their stage_attempt epoch)
        self.attempt_log: List[dict] = []
        self._attempt_index: Dict[Tuple[int, int, int], dict] = {}
        # map partition -> (executor_id, [ShuffleWritePartition])
        self.outputs: Dict[int, Tuple[str, List[ShuffleWritePartition]]] = {}
        # AQE rewrite records applied to this stage (scheduler/aqe.py);
        # append-only, entries carry their stage_attempt epoch
        self.aqe_rewrites: List[dict] = []
        # whole-stage-fusion decisions for this stage (compile/fuse.py):
        # one record per detected chain — fused or rejected, with reasons;
        # append-only, entries carry their stage_attempt epoch
        self.fusion_rewrites: List[dict] = []

    # --- attempt bookkeeping ---------------------------------------------
    def new_attempt(self, partition: int, executor_id: str,
                    speculative: bool = False) -> TaskInfo:
        """Mint the next attempt id for ``partition`` and log it."""
        import time as _time

        attempt = self.task_attempts[partition]
        self.task_attempts[partition] = attempt + 1
        info = TaskInfo(partition, executor_id, "running", attempt=attempt,
                        speculative=speculative,
                        started_at=_time.monotonic())
        entry = {"partition": partition, "attempt": attempt,
                 "stage_attempt": self.stage_attempt,
                 "executor_id": executor_id, "speculative": speculative,
                 "state": "running", "duration_s": None}
        self.attempt_log.append(entry)
        self._attempt_index[(partition, attempt, self.stage_attempt)] = entry
        return info

    def close_attempt(self, st: TaskStatus, state: str) -> None:
        """Record an attempt's terminal state + duration in the log."""
        import time as _time

        entry = self._attempt_index.get(
            (st.task.partition, st.task.task_attempt, st.task.stage_attempt))
        if entry is None or entry["state"] != "running":
            return
        entry["state"] = state
        for info in (self.task_infos[st.task.partition],
                     self.speculative_tasks.get(st.task.partition)):
            if info is not None and info.attempt == st.task.task_attempt \
                    and info.started_at:
                entry["duration_s"] = round(_time.monotonic() - info.started_at, 3)
                break

    def operator_metrics(self) -> Dict[str, Dict[str, float]]:
        """Fold completed tasks' per-operator metrics into a
        per-operator dict keyed by the ``collect_plan_metrics`` path key
        (e.g. ``'0.1:HashAggregateExec'``) — the structured view behind
        the profile endpoint and the dot annotations
        (``aggregate_metrics`` flattens it for the legacy stage view).

        Same-stage tasks in one executor process share operator instances,
        so each task status snapshots the *cumulative* counters at its
        completion time — summing snapshots would overcount quadratically
        (observed: a 6M-row scan reported as 49M).  The stage total is the
        LAST snapshot per PLAN INSTANCE (statuses carry a
        process+instance id; counters are monotone per decoded plan
        object), summed across instances — correct across processes,
        in-proc multi-executor standalone mode, fetch-failure re-resolves
        and plan-cache evictions alike (id() reuse after GC could in
        principle alias two instances; metrics are observability, not
        correctness)."""
        per_exec: Dict[str, Dict[Tuple[str, str], float]] = {}
        for t in self.task_infos:
            st = getattr(t, "status", None)
            if st is None:
                continue
            # attempt-aware dedup: only the recorded winner's own status
            # counts — a terminal status absorbed from a cancelled
            # speculative loser carries a different task_attempt and must
            # not add its (cumulative) snapshot to the fold
            st_att = getattr(getattr(st, "task", None), "task_attempt", None)
            if st_att is not None and st_att != getattr(t, "attempt", st_att):
                continue
            dst = per_exec.setdefault(
                getattr(st, "process_id", "") or getattr(t, "executor_id", ""),
                {})
            for op, mm in (st.metrics or {}).items():
                for k, v in mm.items():
                    if v > dst.get((op, k), float("-inf")):
                        dst[(op, k)] = v
        agg: Dict[str, Dict[str, float]] = {}
        for mm in per_exec.values():
            for (op, k), v in mm.items():
                d = agg.setdefault(op, {})
                d[k] = d.get(k, 0.0) + v
        return agg

    def aggregate_metrics(self) -> Dict[str, float]:
        """Flattened '<op>.<metric>' -> total view of
        ``operator_metrics`` (the REST stage view and bench profiler)."""
        return {f"{op}.{k}": v
                for op, mm in self.operator_metrics().items()
                for k, v in mm.items()}

    # --- queries ---------------------------------------------------------
    @property
    def planned_partitions(self) -> int:
        """The partition count the planner asked for, regardless of
        adaptive coalescing (observability/tests read this)."""
        return getattr(self, "_orig_partitions", None) or self.partitions

    def pending_partitions(self) -> List[int]:
        if self.state != RUNNING:
            return []
        return [p for p in range(self.partitions) if self.task_infos[p] is None]

    def all_successful(self) -> bool:
        return all(t is not None and t.state == "success" for t in self.task_infos)

    def output_locations(self, addr_resolver=None) -> Dict[int, List[PartitionLocation]]:
        """output partition -> locations across all map tasks.
        ``addr_resolver(executor_id) -> (host, port[, grpc_port])`` stamps
        the data-plane address for remote fetch (None in purely local
        deployments); the optional third element is the executor's control
        port, where the chunked ``fetch_partition_stream`` protocol lives
        (0 = whole-file fetch only, e.g. a pre-upgrade resolver)."""
        locs: Dict[int, List[PartitionLocation]] = {}
        for map_part, (executor_id, writes) in sorted(self.outputs.items()):
            host, port, grpc_port = ("", 0, 0)
            if addr_resolver is not None:
                addr = addr_resolver(executor_id)
                host, port = addr[0], addr[1]
                grpc_port = addr[2] if len(addr) > 2 else 0
            for w in writes:
                locs.setdefault(w.output_partition, []).append(
                    PartitionLocation(executor_id, map_part, w.output_partition,
                                      w.path, w.num_rows, w.num_bytes,
                                      host, port, checksum=w.checksum,
                                      grpc_port=grpc_port,
                                      format="arrow_file"))
        return locs

    # --- adaptive exchange coalescing ------------------------------------
    # When the producers' REAL output is tiny, running the planned N reduce
    # tasks is pure overhead (q1: 46 final-agg tasks over 48 partial rows
    # cost ~1.9 s of launch/fetch/dispatch).  The scheduler knows the exact
    # shuffle sizes before launching the consumer — a static planner never
    # does — so the stage collapses to one task reading every bucket.
    # Correct for any hash exchange: the union of buckets is the full
    # input, and aggregates/joins re-group/re-match within the task.
    COALESCE_INPUT_ROWS = 8192

    def maybe_coalesce(self) -> None:
        if self.partitions <= 1 or self.resolved_plan is None:
            return
        leaves = []

        def walk(p):
            kids = p.children()
            if not kids:
                leaves.append(p)
            for c in kids:
                walk(c)

        walk(self.resolved_plan)
        readers = [p for p in leaves if isinstance(p, ShuffleReaderExec)]
        if len(readers) != len(leaves):
            return  # a scan leaf owns the partition count; leave it alone
        total = sum(loc.num_rows for r in readers
                    for locs in r.locations.values() for loc in locs)
        if total > self.COALESCE_INPUT_ROWS:
            return
        for r in readers:
            merged = [loc for q in sorted(r.locations)
                      for loc in r.locations[q]]
            r.locations = {0: merged}
            # remember the planned count: resolve mutates the plan tree in
            # place, and a rollback rebuilds UnresolvedShuffleExec from
            # this reader — it must restore the ORIGINAL partitioning
            r._orig_partition_count = r.partition_count
            r.partition_count = 1
        self._orig_partitions = self.partitions
        self.partitions = 1
        self.task_infos = [None]
        # task_failures/task_attempts keep their planned length: only
        # index 0 is touched while coalesced, and rollback restores the
        # full partition count with per-partition budgets intact

    # --- transitions -----------------------------------------------------
    def rollback(self, count_failure: bool = True) -> None:
        """RUNNING/RESOLVED -> UNRESOLVED (reference execution_stage.rs
        rollback arrows); outputs are discarded, tasks forgotten.

        ``remove_unresolved_shuffles`` resolves in place (each stage owns
        its subtree), so the inverse walk here restores the
        UnresolvedShuffleExec leaves — without it a re-resolve would keep
        the *previous* attempt's partition locations (dead paths)."""
        from .planner import rollback_resolved_shuffles

        self.plan = rollback_resolved_shuffles(self.plan)
        self.state = UNRESOLVED
        self.resolved_plan = None
        # undo adaptive coalescing: the fresh resolve re-decides from the
        # new attempt's real shuffle sizes
        if getattr(self, "_orig_partitions", None):
            self.partitions = self._orig_partitions
            self._orig_partitions = None
        self.task_infos = [None] * self.partitions
        self.speculative_tasks.clear()
        self.failed_on.clear()
        self.outputs.clear()
        self.stage_attempt += 1
        if count_failure:
            self.failures += 1

    def reopen_partitions(self, partitions: List[int], count_attempt: bool = True) -> None:
        """SUCCESSFUL/RUNNING -> RUNNING with the given map partitions
        pending again (reference SuccessfulStage::to_running).  Partitions
        already pending or re-running (reported lost twice, e.g. by two
        reducer tasks that both failed to fetch) are left alone."""
        reopened = False
        for p in partitions:
            info = self.task_infos[p]
            if p not in self.outputs and (info is None or info.state != "success"):
                continue  # already re-opened; a re-run may be in flight
            self.outputs.pop(p, None)
            self.task_infos[p] = None
            self.speculative_tasks.pop(p, None)
            reopened = True
        if reopened and self.state == SUCCESSFUL:
            self.state = RUNNING
            self.stage_attempt += 1  # new epoch either way
            if count_attempt:
                self.failures += 1

    def __repr__(self):
        done = sum(1 for t in self.task_infos if t and t.state == "success")
        return (f"Stage(id={self.stage_id}, {self.state}, "
                f"{done}/{self.partitions} tasks, attempt={self.stage_attempt})")


class ExecutionGraph:
    """Parity: reference state/execution_graph.rs ExecutionGraph."""

    def __init__(self, job_id: str, stages: List[QueryStage]):
        self.job_id = job_id
        self.stages: Dict[int, ExecutionStage] = {
            s.stage_id: ExecutionStage(s.stage_id, s.plan) for s in stages}
        # link producers -> consumers (reference ExecutionStageBuilder,
        # execution_graph.rs:1441-1543)
        for stage in self.stages.values():
            for pid in stage.producer_ids:
                if pid not in self.stages:
                    raise InternalError(f"stage {stage.stage_id} references "
                                        f"unknown producer {pid}")
                self.stages[pid].output_links.append(stage.stage_id)
        finals = [s for s in self.stages.values() if not s.output_links]
        if len(finals) != 1:
            raise InternalError(f"expected exactly one final stage, got {finals}")
        self.final_stage_id = finals[0].stage_id
        self.status = "running"
        self.error = ""
        self.scalars: Dict[str, object] = {}
        # server-side deadline (ballista.query.deadline.seconds): absolute
        # wall-clock expiry + the configured budget, stamped at planning
        # from the submitter's clock and checkpointed so an adopting shard
        # keeps enforcing the original deadline.  0.0 = no deadline.
        self.deadline_ts = 0.0
        self.deadline_s = 0.0
        # trace propagation context handed to every task of this job
        # ({"trace_id", "span_id"}; empty when tracing is off)
        self.trace: Dict[str, str] = {}
        # executor_id -> (host, port) of the data plane; None = local-only
        self.addr_resolver = None
        # live per-stage runtime summaries (skew, histograms, duration
        # quantiles) — refolded on every task success, read by EXPLAIN
        # ANALYZE, /api/job/<id>/stats, and future AQE.  Not checkpointed
        # (serde.graph_to_obj is field-explicit): a recovered graph starts
        # with an empty store and refills as its re-run stages complete.
        self.stats = RuntimeStatsStore(job_id)
        # adaptive query execution (scheduler/aqe.py): per-job policy (the
        # scheduler overwrites it from the session config right after
        # build), the flat rewrite log (bench/REST/serde), and the pending
        # metric events the scheduler drains into its collector
        self.aqe = AqePolicy()
        self.aqe_log: List[dict] = []
        self.aqe_events: List[Tuple[str, int]] = []
        # whole-stage compiler (compile/fuse.py): per-job policy installed
        # by the scheduler AFTER build (None = fusion off, so the leaf
        # stages resolved by the revive() below stay interpreted until the
        # scheduler decides), plus the flat decision log (REST/serde)
        self.compiler = None
        self.compile_log: List[dict] = []
        self._task_id_gen = itertools.count()
        self.revive()

    @staticmethod
    def build(job_id: str, plan) -> "ExecutionGraph":
        stages = DistributedPlanner().plan_query_stages(job_id, plan)
        return ExecutionGraph(job_id, stages)

    # --- scheduling ------------------------------------------------------
    def revive(self) -> bool:
        """Resolve every UNRESOLVED stage whose producers are all
        SUCCESSFUL (reference execution_graph.rs:242-266)."""
        changed = False
        for stage in self.stages.values():
            if stage.state != UNRESOLVED:
                continue
            if all(self.stages[p].state == SUCCESSFUL for p in stage.producer_ids):
                locations = {p: self.stages[p].output_locations(self.addr_resolver)
                             for p in stage.producer_ids}
                stage.resolved_plan = remove_unresolved_shuffles(stage.plan, locations) \
                    if stage.producer_ids else stage.plan
                if stage.producer_ids:
                    if self.aqe.enabled:
                        # dynamic coalescing + skew splitting off the
                        # observed shuffle sizes (subsumes the static
                        # heuristic below, which stays byte-identical for
                        # ballista.aqe.enabled=false)
                        rewrite_resolved_stage(self, stage, self.aqe)
                    else:
                        stage.maybe_coalesce()
                stage.state = RUNNING
                changed = True
                if self.compiler is not None and self.compiler.enabled:
                    # whole-stage fusion rides the resolve: applied to the
                    # freshly resolved plan (after AQE), before any task
                    # launches — so rollbacks re-resolve AND re-fuse, and
                    # speculative duplicates share the fused kernel
                    from ..compile.fuse import fuse_stage

                    fuse_stage(self, stage)
                if journal.enabled():
                    journal.emit("stage.resolved", job_id=self.job_id,
                                 stage_id=stage.stage_id,
                                 partitions=stage.partitions,
                                 producers=list(stage.producer_ids))
        return changed

    def preload_stage(self, stage_id: int,
                      outputs: Dict[int, Tuple[str, List["ShuffleWritePartition"]]]
                      ) -> bool:
        """Complete a stage from cached shuffle output without running any
        of its tasks (serving subplan cache, scheduler/serving_cache.py).
        Only a stage that is already resolved (RUNNING) and untouched is
        eligible — resolution must run normally so fetch-failure recovery
        keeps working on preloaded stages (reopen_partitions requires
        resolved_plan).  The final stage is never preloaded: its output is
        the result cache's domain."""
        stage = self.stages.get(stage_id)
        if stage is None or stage.state != RUNNING:
            return False
        if not stage.output_links:
            return False
        if any(t is not None for t in stage.task_infos):
            return False
        if sorted(outputs) != list(range(stage.partitions)):
            return False  # adaptive rewrites changed the task shape
        stage.outputs = dict(outputs)
        for p in range(stage.partitions):
            stage.task_infos[p] = TaskInfo(p, "subplan-cache", "success")
        stage.state = SUCCESSFUL
        self.revive()
        return True

    def available_task_count(self) -> int:
        if self.status != "running":
            return 0
        return sum(len(s.pending_partitions()) for s in self.stages.values())

    def pop_next_task(self, executor_id: str,
                      alive: Optional[set] = None) -> Optional[TaskDescription]:
        """Hand out one pending task (reference execution_graph.rs:834-935).

        ``alive``: the scheduler's current alive+healthy executor set,
        enabling retry anti-affinity — a partition that already failed
        retryably on ``executor_id`` is skipped HERE as long as some other
        alive executor could still take it (no deadlock: when every alive
        executor has failed it, anyone may retry it and the failure budget
        decides).  ``alive=None`` (tests, direct drivers) disables the
        steering."""
        if self.status != "running":
            return None
        for stage in sorted(self.stages.values(), key=lambda s: s.stage_id):
            for p in stage.pending_partitions():
                failed_on = stage.failed_on.get(p)
                if (failed_on and executor_id in failed_on
                        and alive is not None and (alive - failed_on)):
                    continue  # steer this retry toward a fresh executor
                info = stage.new_attempt(p, executor_id)
                stage.task_infos[p] = info
                return self._describe(stage, info)
        return None

    def _describe(self, stage: ExecutionStage, info: TaskInfo) -> TaskDescription:
        tid = TaskId(self.job_id, stage.stage_id, info.partition,
                     task_attempt=info.attempt,
                     stage_attempt=stage.stage_attempt,
                     speculative=info.speculative)
        if journal.enabled():
            # the single mint point for every launch (normal + speculative):
            # registers the causal key the scheduler's task.finish event
            # chains back to
            journal.emit("task.launch", job_id=self.job_id,
                         causal_key=("task", self.job_id, stage.stage_id,
                                     info.partition, info.attempt),
                         stage_id=stage.stage_id, partition=info.partition,
                         attempt=info.attempt,
                         executor_id=info.executor_id,
                         speculative=info.speculative)
        return TaskDescription(tid, stage.resolved_plan,
                               task_internal_id=next(self._task_id_gen),
                               scalars=self.scalars,
                               trace=dict(self.trace))

    def launch_speculative(self, stage_id: int, partition: int,
                           executor_id: str) -> Optional[TaskDescription]:
        """Mint a speculative duplicate attempt for a straggling running
        task, to be placed on ``executor_id`` (the caller guarantees it is
        a *different* executor than the original's).  Returns None when the
        partition is no longer a candidate (finished, rolled back, or
        already speculated) — the monitor races task completion by design."""
        if self.status != "running":
            return None
        stage = self.stages.get(stage_id)
        if stage is None or stage.state != RUNNING:
            return None
        if partition in stage.speculative_tasks:
            return None
        primary = stage.task_infos[partition]
        if primary is None or primary.state != "running" \
                or primary.executor_id == executor_id:
            return None
        info = stage.new_attempt(partition, executor_id, speculative=True)
        stage.speculative_tasks[partition] = info
        return self._describe(stage, info)

    # --- status intake ---------------------------------------------------
    def update_task_status(self, statuses: List[TaskStatus]) -> List[Tuple[str, object]]:
        """Absorb executor task outcomes; returns job-level events:
        ('job_successful', locations) | ('job_failed', message).
        Parity: reference execution_graph.rs:270-657."""
        events: List[Tuple[str, object]] = []
        if self.status != "running":
            # a terminal job still absorbs attempt BOOKKEEPING: a cancelled
            # speculative loser often reports "killed" after the job has
            # already succeeded, and without this its audit-log entry would
            # read "running" forever
            for st in statuses:
                stage = self.stages.get(st.task.stage_id)
                if stage is not None \
                        and st.task.stage_attempt == stage.stage_attempt:
                    stage.close_attempt(st, st.state)
            return events
        for st in statuses:
            stage = self.stages.get(st.task.stage_id)
            if stage is None:
                continue
            if st.task.stage_attempt != stage.stage_attempt:
                # late message from a rolled-back attempt — drop it
                # (reference handles these via attempt checks)
                continue
            if st.state == "success":
                self._on_task_success(stage, st, events)
            elif st.state == "failed":
                self._on_task_failed(stage, st, events)
            elif st.state == "killed":
                # job-level cancel, or a cancelled speculative loser: free
                # the duplicate's slot bookkeeping, nothing else to do
                stage.close_attempt(st, "killed")
                spec = stage.speculative_tasks.get(st.task.partition)
                if spec is not None and spec.attempt == st.task.task_attempt:
                    stage.speculative_tasks.pop(st.task.partition, None)
            if self.status != "running":
                break
        return events

    def _on_task_success(self, stage: ExecutionStage, st: TaskStatus,
                         events: List[Tuple[str, object]]) -> None:
        import time as _time

        p = st.task.partition
        info = stage.task_infos[p]
        spec = stage.speculative_tasks.get(p)
        att = st.task.task_attempt
        stage.close_attempt(st, "success")
        if info is not None and info.state == "success":
            # first-result-wins dedup: the loser of a speculative race (or
            # any duplicate report) finished after the winner — its outputs
            # are ignored, the recorded ones stay authoritative
            if spec is not None and spec.attempt == att:
                stage.speculative_tasks.pop(p, None)
            return
        # which in-flight attempt does this status belong to?
        winner: Optional[TaskInfo] = None
        if info is not None and info.state == "running" and info.attempt == att:
            winner = info
        elif spec is not None and spec.attempt == att:
            winner = spec
            events.append(("speculative_win", (stage.stage_id, p)))
        # cancel the losing duplicate (first success wins either way)
        loser = spec if winner is info else info
        if spec is not None and loser is not None and loser is not winner \
                and loser.state == "running":
            events.append(("cancel_task",
                           (loser.executor_id,
                            TaskId(self.job_id, stage.stage_id, p,
                                   task_attempt=loser.attempt,
                                   stage_attempt=stage.stage_attempt,
                                   speculative=loser.speculative))))
        stage.speculative_tasks.pop(p, None)
        started = winner.started_at if winner is not None else 0.0
        if started:
            stage.durations.append(_time.monotonic() - started)
        stage.task_infos[p] = TaskInfo(p, st.executor_id, "success", st,
                                       attempt=att,
                                       speculative=st.task.speculative,
                                       started_at=started)
        stage.outputs[p] = (st.executor_id, list(st.shuffle_writes))
        completed = stage.all_successful() and stage.state == RUNNING
        if completed:
            stage.state = SUCCESSFUL
        # refold AFTER the state transition (the final summary must record
        # the stage as successful) and BEFORE downstream stages resolve:
        # the AQE passes read the completed stage's folded stats
        self.stats.fold_stage(stage)
        if completed:
            if stage.stage_id == self.final_stage_id:
                self.status = "successful"
                events.append(("job_successful",
                               stage.output_locations(self.addr_resolver)))
            else:
                # broadcast-switch pass first: a flipped join changes what
                # revive() resolves (and may graft away an exchange whose
                # cancellations ride out on ``events``)
                maybe_broadcast_switch(self, stage, events, self.aqe)
                self.revive()

    def _on_task_failed(self, stage: ExecutionStage, st: TaskStatus,
                        events: List[Tuple[str, object]]) -> None:
        p = st.task.partition
        info = stage.task_infos[p]
        spec = stage.speculative_tasks.get(p)
        att = st.task.task_attempt
        reason = st.failure or FailedReason(EXECUTION_ERROR, "unknown failure")
        stage.close_attempt(st, "killed" if reason.kind == TASK_KILLED
                            else "failed")

        # a cancelled/crashed loser must never disturb a completed
        # partition: the winner's outputs are already recorded
        if info is not None and info.state == "success":
            if spec is not None and spec.attempt == att:
                stage.speculative_tasks.pop(p, None)
            return

        if reason.kind == EXECUTION_ERROR:
            self._fail_job(f"task {st.task.job_id}/{stage.stage_id}/{p}: "
                           f"{reason.message}", events)
            return

        if reason.kind == TASK_KILLED:
            if spec is not None and spec.attempt == att:
                stage.speculative_tasks.pop(p, None)
            return

        if reason.kind == FETCH_PARTITION_ERROR:
            self._on_fetch_failure(stage, reason, events)
            return

        # retryable (IOError / ExecutorLost / ResultLost)
        if spec is not None and spec.attempt == att:
            # the speculative duplicate died while the original is still
            # running: just drop the duplicate — no budget charge, no reset
            stage.speculative_tasks.pop(p, None)
            return
        if reason.count_to_failures:
            stage.task_failures[p] += 1
        if stage.task_failures[p] >= TASK_MAX_FAILURES:
            self._fail_job(
                f"task {st.task.job_id}/{stage.stage_id}/{p} failed "
                f"{TASK_MAX_FAILURES} times: {reason.message}", events)
            return
        # remember WHERE it failed so the retry steers to a fresh executor
        # (and poison containment can count distinct witnesses)
        eid = st.executor_id or (info.executor_id if info is not None else "")
        if eid:
            stage.failed_on.setdefault(p, set()).add(eid)
        if spec is not None:
            # the original died but a speculative duplicate is in flight:
            # promote it to primary instead of launching a third attempt
            stage.task_infos[p] = stage.speculative_tasks.pop(p)
        else:
            stage.task_infos[p] = None  # back to pending

    def _on_fetch_failure(self, stage: ExecutionStage, reason: FailedReason,
                          events: List[Tuple[str, object]]) -> None:
        """Shuffle-lineage retry (execution_graph.rs: fetch failures remove
        poisoned inputs, roll back the reducer, re-run the producer)."""
        producer = self.stages.get(reason.map_stage_id)
        if producer is None:
            self._fail_job(f"fetch failure names unknown stage "
                           f"{reason.map_stage_id}", events)
            return
        stage.rollback()
        if stage.failures >= STAGE_MAX_FAILURES:
            # keep the ORIGINAL transport cause in the job error: "budget
            # exhausted" alone is undebuggable once the executor is gone
            self._fail_job(
                f"stage {stage.stage_id} exceeded {STAGE_MAX_FAILURES} "
                f"attempts after fetch failures (last: {reason.message})",
                events)
            return
        producer.reopen_partitions([reason.map_partition_id])
        if producer.failures >= STAGE_MAX_FAILURES:
            self._fail_job(
                f"stage {producer.stage_id} exceeded {STAGE_MAX_FAILURES} "
                f"re-runs (last fetch failure: {reason.message})", events)
            return
        self.revive()

    # --- executor loss ---------------------------------------------------
    def executor_lost(self, executor_id: str) -> None:
        """Reset tasks and roll back stages whose outputs lived on the lost
        executor (reference execution_graph.rs:950-1095).  Does not count
        toward stage attempt budgets: losing a node is not the query's
        fault."""
        if self.status != "running":
            return
        # 1. forget running tasks on the executor (a surviving speculative
        #    duplicate is promoted to primary rather than relaunching)
        for stage in self.stages.values():
            if stage.state != RUNNING:
                continue
            for p, spec in list(stage.speculative_tasks.items()):
                if spec.executor_id == executor_id:
                    stage.speculative_tasks.pop(p, None)
            for p, info in enumerate(stage.task_infos):
                if info is not None and info.state == "running" \
                        and info.executor_id == executor_id:
                    spec = stage.speculative_tasks.pop(p, None)
                    stage.task_infos[p] = spec
        # 2. re-open map partitions whose outputs are gone
        poisoned: List[int] = []
        for stage in self.stages.values():
            lost = [p for p, (ex, _) in stage.outputs.items() if ex == executor_id]
            if lost:
                stage.reopen_partitions(lost, count_attempt=False)
                poisoned.append(stage.stage_id)
        # 3. roll back non-successful consumers of poisoned stages
        #    (they may hold resolved plans pointing at dead locations);
        #    consumers that are already SUCCESSFUL keep their outputs.
        #    No recursion needed: a consumer-of-a-consumer can only be
        #    RUNNING if its producer was SUCCESSFUL, whose lost outputs
        #    step 2 already handles directly.
        for sid in poisoned:
            for cid in self.stages[sid].output_links:
                consumer = self.stages[cid]
                if consumer.state == RUNNING:
                    consumer.rollback(count_failure=False)
        self.revive()

    # --- job level -------------------------------------------------------
    def _fail_job(self, message: str, events: List[Tuple[str, object]]) -> None:
        self.status = "failed"
        self.error = message
        events.append(("job_failed", message))

    def cancel(self) -> None:
        self.status = "cancelled"

    def running_tasks(self) -> List[Tuple[int, int, str]]:
        """(stage_id, partition, executor_id) of in-flight tasks,
        speculative duplicates included."""
        out = []
        for stage in self.stages.values():
            if stage.state != RUNNING:
                continue
            for info in stage.task_infos:
                if info is not None and info.state == "running":
                    out.append((stage.stage_id, info.partition, info.executor_id))
            for info in stage.speculative_tasks.values():
                out.append((stage.stage_id, info.partition, info.executor_id))
        return out

    def __repr__(self):
        lines = [f"ExecutionGraph(job={self.job_id}, status={self.status})"]
        for sid in sorted(self.stages):
            lines.append("  " + repr(self.stages[sid]))
        return "\n".join(lines)
