"""DistributedPlanner: split a physical plan into shuffle stages.

Parity with the reference's stage-splitting rules
(reference ballista/scheduler/src/planner.rs:80-165): walk the plan; every
exchange (``RepartitionExec`` — hash or single) becomes a stage boundary:
the subtree below it becomes a new ``QueryStage`` rooted at a
``ShuffleWriterExec`` with that partitioning, and the exchange node is
replaced by an ``UnresolvedShuffleExec`` leaf.  The root plan becomes the
final stage, a ``ShuffleWriterExec`` with ``partitioning=None``
(planner.rs:60-75).

``remove_unresolved_shuffles`` resolves placeholder leaves into
``ShuffleReaderExec`` with concrete partition locations once producer
stages complete (planner.rs:208-257); ``rollback_resolved_shuffles``
undoes that for stage re-runs after fetch failures (planner.rs:262-285).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List

from ..ops.physical import ExecutionPlan
from ..ops.shuffle import (
    PartitionLocation,
    RepartitionExec,
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
)
from ..utils.errors import InternalError


def map_children(plan: ExecutionPlan, fn) -> ExecutionPlan:
    """Rebuild ``plan``'s children via ``fn`` (mutating in place: every
    stage owns its subtree, the graph machinery never shares operator
    nodes across stages)."""
    if hasattr(plan, "input") and isinstance(plan.input, ExecutionPlan):
        plan.input = fn(plan.input)
    if hasattr(plan, "left") and isinstance(getattr(plan, "left"), ExecutionPlan):
        plan.left = fn(plan.left)
    if hasattr(plan, "right") and isinstance(getattr(plan, "right"), ExecutionPlan):
        plan.right = fn(plan.right)
    return plan


def collect_nodes(plan: ExecutionPlan, cls) -> List[ExecutionPlan]:
    found = []
    if isinstance(plan, cls):
        found.append(plan)
    for c in plan.children():
        found.extend(collect_nodes(c, cls))
    return found


@dataclasses.dataclass
class QueryStage:
    stage_id: int
    plan: ShuffleWriterExec  # every stage is rooted at a shuffle writer


class DistributedPlanner:
    """Stateless except for the per-job stage-id counter."""

    def __init__(self):
        self._next_stage_id = 1

    def _new_stage_id(self) -> int:
        sid = self._next_stage_id
        self._next_stage_id += 1
        return sid

    def plan_query_stages(self, job_id: str, plan: ExecutionPlan) -> List[QueryStage]:
        stages: List[QueryStage] = []
        root = self._split(plan, stages)
        final = ShuffleWriterExec(root, None, stage_id=self._new_stage_id())
        stages.append(QueryStage(final.stage_id, final))
        return stages

    def _split(self, plan: ExecutionPlan, stages: List[QueryStage]) -> ExecutionPlan:
        plan = map_children(plan, lambda c: self._split(c, stages))
        if isinstance(plan, RepartitionExec):
            sid = self._new_stage_id()
            writer = ShuffleWriterExec(plan.input, plan.partitioning, stage_id=sid)
            stages.append(QueryStage(sid, writer))
            return UnresolvedShuffleExec(sid, writer.schema, plan.partitioning.count)
        return plan


def remove_unresolved_shuffles(
    plan: ExecutionPlan,
    locations: Dict[int, Dict[int, List[PartitionLocation]]],
) -> ExecutionPlan:
    """Replace every UnresolvedShuffleExec with a ShuffleReaderExec.

    ``locations[producer_stage_id][output_partition] -> [PartitionLocation]``.
    """

    def walk(p: ExecutionPlan) -> ExecutionPlan:
        p = map_children(p, walk)
        if isinstance(p, UnresolvedShuffleExec):
            locs = locations.get(p.stage_id)
            if locs is None:
                raise InternalError(
                    f"no output locations for producer stage {p.stage_id}")
            return ShuffleReaderExec(p.stage_id, p.schema,
                                     p.output_partition_count(), dict(locs))
        return p

    return walk(plan)


def rollback_resolved_shuffles(plan: ExecutionPlan) -> ExecutionPlan:
    """Inverse of remove_unresolved_shuffles, for stage re-runs."""

    def walk(p: ExecutionPlan) -> ExecutionPlan:
        p = map_children(p, walk)
        if isinstance(p, ShuffleReaderExec):
            # adaptive coalescing may have collapsed the reader to one
            # partition; the re-run must restore the PLANNED partitioning
            count = getattr(p, "_orig_partition_count", None) or p.partition_count
            return UnresolvedShuffleExec(p.stage_id, p.schema, count)
        return p

    return walk(plan)
