"""Networked KV store: the etcd-role driver for multi-host HA.

Parity: the reference's etcd driver gives N schedulers on DIFFERENT hosts
one shared, transactional, watchable state store (reference
ballista/scheduler/src/cluster/storage/etcd.rs:37-346 — namespaced keys,
lease locks, watch streams).  The embedded drivers here (MemoryKv, SqliteKv)
need shared memory or a shared filesystem; this module removes that
constraint with a standalone KV service over the framework's own wire
protocol:

- :class:`KvServer` hosts any embedded ``KeyValueStore`` behind RPC,
  assigning every mutation a monotonically increasing sequence number and
  keeping a bounded replay log so watches survive short disconnects;
- :class:`RemoteKv` is a full ``KeyValueStore``: get/scan/txn proxy
  straight through (guards evaluate server-side, so CAS semantics are
  exactly the embedded ones), and ``watch`` long-polls the replay log.

Run the service with ``python -m arrow_ballista_tpu.scheduler.kv_remote
--port 50070 [--store sqlite:///path]`` next to (or replicated behind) the
schedulers, then point every scheduler at ``kv://host:port``.
"""
from __future__ import annotations

import argparse
import collections
import json
import logging
import threading
from typing import Dict, List, Optional, Tuple

from ..net import wire
from ..net.rpc import RpcServer
from .kv import (
    KeyValueStore,
    MemoryKv,
    TxnGuardFailed,
    Watch,
    WatchEvent,
    _QueueWatch,
    open_store,
)

log = logging.getLogger(__name__)


class KvServer:
    """RPC front for an embedded KeyValueStore + watch replay log."""

    REPLAY_CAP = 4096

    def __init__(self, store: Optional[KeyValueStore] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store or MemoryKv()
        self.rpc = RpcServer(host, port)
        self.host, self.port = self.rpc.host, self.rpc.port
        self._seq = 0
        self._log: "collections.deque[Tuple[int, str, str, str, Optional[str]]]" = \
            collections.deque(maxlen=self.REPLAY_CAP)
        self._log_lock = threading.Condition()
        self.rpc.register("kv_get", self._get)
        self.rpc.register("kv_scan", self._scan)
        self.rpc.register("kv_txn", self._txn)
        self.rpc.register("kv_poll", self._poll)

    def start(self) -> None:
        self.rpc.start()

    def stop(self) -> None:
        self.rpc.stop()
        with self._log_lock:
            self._log_lock.notify_all()
        self.store.close()

    # --- handlers --------------------------------------------------------
    def _get(self, p: dict, _b: bytes):
        return {"value": self.store.get(p["space"], p["key"])}, b""

    def _scan(self, p: dict, _b: bytes):
        return {"items": self.store.scan(p["space"])}, b""

    def _txn(self, p: dict, _b: bytes):
        ops = [tuple(op) for op in p["ops"]]
        guards = [tuple(g) for g in p.get("guards") or []]
        try:
            # single-writer section: the embedded store's txn is atomic; the
            # log append must observe the same order
            with self._log_lock:
                self.store.txn(ops, guards=guards or None)
                for op, space, key, value in ops:
                    self._seq += 1
                    self._log.append((self._seq, op, space, key,
                                      value if op == "put" else None))
                self._log_lock.notify_all()
                # capture under the lock: reading self._seq after the with
                # block could return a CONCURRENT txn's seq, and a client
                # using it as a watch cursor would skip the events between
                # its own txn and that later one
                head = self._seq
            return {"ok": True, "seq": head}, b""
        except TxnGuardFailed as e:
            return {"ok": False, "guard_failed": str(e)}, b""

    def _poll(self, p: dict, _b: bytes):
        """Long-poll events after ``since`` for one keyspace."""
        since = int(p.get("since", 0))
        space = p["space"]
        timeout = min(float(p.get("timeout", 10.0)), 30.0)
        with self._log_lock:
            if not self._log or self._log[-1][0] <= since:
                self._log_lock.wait(timeout)
            events = [
                {"seq": s, "op": op, "key": k, "value": v}
                for (s, op, sp, k, v) in self._log
                if s > since and sp == space
            ]
            head = self._seq
            oldest = self._log[0][0] if self._log else head
        # a client whose cursor predates the replay window must resync
        resync = since and oldest > since + 1
        return {"events": events, "head": head, "resync": bool(resync)}, b""


class RemoteKv(KeyValueStore):
    """KeyValueStore client for a KvServer (the 'etcd client' analog).

    Connections are persistent per thread: the scheduler's slot-reservation
    CAS loops issue many small get/txn calls, and a fresh TCP handshake per
    call would dominate their latency (RpcServer handlers loop on
    recv_frame, so one socket serves many frames)."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._local = threading.local()

    def _call(self, method: str, payload: dict) -> dict:
        last_err = None
        for attempt in range(2):  # one reconnect on a stale pooled socket
            sock = getattr(self._local, "sock", None)
            try:
                if sock is None:
                    sock = wire.connect(self.host, self.port)
                    sock.settimeout(60.0)
                    self._local.sock = sock
                wire.send_frame(sock, {"method": method,
                                       "payload": payload or {}})
                resp, _ = wire.recv_frame(sock)
                if not resp.get("ok"):
                    raise wire.RemoteError(resp.get("error", "remote error"),
                                           resp.get("error_kind", ""))
                return resp.get("payload", {})
            except wire.RemoteError:
                raise
            # the error is NOT swallowed: it re-raises as last_err below
            # ballista: allow=recovery-path-logging — bounded reconnect retry
            except Exception as e:  # noqa: BLE001 — socket died; reconnect
                last_err = e
                try:
                    if sock is not None:
                        sock.close()
                except OSError:
                    pass
                self._local.sock = None
        raise last_err

    def get(self, space, key):
        return self._call("kv_get", {"space": space, "key": key})["value"]

    def scan(self, space):
        return [tuple(kv) for kv in self._call("kv_scan", {"space": space})["items"]]

    def txn(self, ops, guards=None):
        out = self._call("kv_txn", {"ops": [list(o) for o in ops],
                                    "guards": [list(g) for g in guards]
                                    if guards else None})
        if not out.get("ok"):
            raise TxnGuardFailed(out.get("guard_failed", ""))

    def watch(self, space, poll_interval_s: float = 0.2) -> Watch:
        w = _RemoteWatch(self, space)
        return w

    def close(self):
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            self._local.sock = None


class _RemoteWatch(_QueueWatch):
    """Watch over KvServer's replay log, resilient to server restarts:

    - the constructor tolerates a down server (cursor acquisition moves
      into the loop; the watch comes up when the server does, primed with
      a resync + snapshot);
    - poll failures retry with doubling capped backoff instead of a fixed
      sleep, so a bounced server is re-attached to quickly without
      hammering a dead one;
    - a HEAD REGRESSION (``head < since``: the restarted server's sequence
      counter reset to 0) forces a client-side resync — the server-side
      ``resync`` marker cannot flag this case because the fresh server's
      replay log is empty."""

    BACKOFF_BASE_S = 0.05
    BACKOFF_CAP_S = 2.0

    def __init__(self, kv: RemoteKv, space: str):
        super().__init__()
        self._stop = threading.Event()
        # cursor starts at the server head so only NEW events stream; a
        # down server defers acquisition to the loop rather than failing
        # the caller
        try:
            head = int(kv._call("kv_poll", {"space": space, "since": 0,
                                            "timeout": 0.0})["head"])
        except Exception:  # noqa: BLE001 — server away; loop will attach
            log.debug("kv watch on %s deferred: server unreachable", space,
                      exc_info=True)
            head = None

        def _resync(since_hint):
            """Clear-and-snapshot: consumers drop their mirror (deletes
            during the gap produce no events), then the snapshot streams
            as puts.  Returns the new cursor, or None to retry."""
            self._push(WatchEvent("resync", space, "", None))
            try:
                snapshot = kv.scan(space)
            except Exception:  # noqa: BLE001 — bounced again mid-resync
                log.debug("kv watch resync scan on %s failed; retrying",
                          space, exc_info=True)
                return None
            for k, v in snapshot:
                self._push(WatchEvent("put", space, k, v))
            return since_hint

        def run():
            since = head
            backoff = self.BACKOFF_BASE_S
            while not self._stop.is_set():
                try:
                    if since is None:
                        # (re)acquire the cursor, then prime the consumer:
                        # anything that happened while detached is invisible
                        # to the replay cursor, so snapshot from scratch
                        cur = int(kv._call("kv_poll", {
                            "space": space, "since": 0,
                            "timeout": 0.0})["head"])
                        since = _resync(cur)
                        backoff = self.BACKOFF_BASE_S
                        continue
                    out = kv._call("kv_poll", {"space": space, "since": since,
                                               "timeout": 5.0})
                except Exception:  # noqa: BLE001 — server away; back off
                    log.debug("kv_poll on %s failed; retrying", space,
                              exc_info=True)
                    if self._stop.wait(backoff):
                        break
                    backoff = min(backoff * 2.0, self.BACKOFF_CAP_S)
                    continue
                backoff = self.BACKOFF_BASE_S
                hd = int(out.get("head", since))
                if out.get("resync") or hd < since:
                    since = _resync(hd)
                    continue
                for ev in out["events"]:
                    self._push(WatchEvent(ev["op"], space, ev["key"],
                                          ev["value"]))
                # head covers every logged event <= it (events and head are
                # read under one server lock), so advancing to head is safe
                # AND required: without it, traffic in OTHER keyspaces makes
                # the long-poll return immediately forever (busy loop)
                since = max(since, hd)

        self._thread = threading.Thread(target=run, name=f"kv-rwatch-{space}",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        # bounded: the poll loop re-checks _stop at most one long-poll
        # (5 s) later; don't hang a caller on a slow server
        self._thread.join(timeout=6.0)
        super().close()


def open_remote_or_local(url: str) -> KeyValueStore:
    """Extended factory: 'kv://host:port' -> RemoteKv, else open_store."""
    if url.startswith("kv://"):
        hostport = url[len("kv://"):]
        host, _, port = hostport.partition(":")
        return RemoteKv(host or "127.0.0.1", int(port))
    return open_store(url)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="standalone cluster-state KV service")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=50070)
    ap.add_argument("--store", default="memory://",
                    help="backing store: memory:// or sqlite:///path")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    srv = KvServer(open_store(args.store), args.host, args.port)
    srv.start()
    log.info("kv service on %s:%d (store %s)", srv.host, srv.port, args.store)
    import signal

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    srv.stop()


if __name__ == "__main__":
    main()
