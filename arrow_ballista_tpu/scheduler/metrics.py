"""Scheduler metrics: counters/histograms + prometheus text exposition.

Parity: reference ballista/scheduler/src/metrics/ — the
``SchedulerMetricsCollector`` trait (mod.rs:10-66) with its Prometheus
implementation (prometheus.rs:41-176: job_exec_time_seconds,
planning_time_seconds histograms; submitted/completed/failed/cancelled
counters; pending_task_queue_size gauge) and the Noop default.  Metric
names match docs/source/user-guide/metrics.md so reference dashboards
port over unchanged.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

_BUCKETS = [0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0]


class Histogram:
    def __init__(self, buckets: Optional[List[float]] = None):
        self.buckets = buckets or _BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.n += 1
        self.total += v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class SchedulerMetricsCollector:
    """Trait (reference metrics/mod.rs:10-66)."""

    def record_submitted(self, job_id: str, queued_at_ms: int, submitted_at_ms: int) -> None: ...
    def record_completed(self, job_id: str, queued_at_ms: int, completed_at_ms: int) -> None: ...
    def record_failed(self, job_id: str) -> None: ...
    def record_cancelled(self, job_id: str) -> None: ...
    def set_pending_tasks_queue_size(self, value: int) -> None: ...
    def gather(self) -> str:
        return ""


class NoopMetricsCollector(SchedulerMetricsCollector):
    pass


class InMemoryMetricsCollector(SchedulerMetricsCollector):
    """Collects + renders prometheus text exposition format."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        self.pending_tasks = 0
        self.planning_time = Histogram([0.01, 0.05, 0.1, 0.5, 1.0, 5.0])
        self.exec_time = Histogram()

    def record_submitted(self, job_id, queued_at_ms, submitted_at_ms):
        with self._lock:
            self.submitted += 1
            self.planning_time.observe(max(0.0, (submitted_at_ms - queued_at_ms) / 1e3))

    def record_completed(self, job_id, queued_at_ms, completed_at_ms):
        with self._lock:
            self.completed += 1
            self.exec_time.observe(max(0.0, (completed_at_ms - queued_at_ms) / 1e3))

    def record_failed(self, job_id):
        with self._lock:
            self.failed += 1

    def record_cancelled(self, job_id):
        with self._lock:
            self.cancelled += 1

    def set_pending_tasks_queue_size(self, value):
        with self._lock:
            self.pending_tasks = value

    def gather(self) -> str:
        with self._lock:
            lines = []

            def counter(name, v, help_):
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {v}")

            counter("job_submitted_total", self.submitted, "jobs submitted")
            counter("job_completed_total", self.completed, "jobs completed")
            counter("job_failed_total", self.failed, "jobs failed")
            counter("job_cancelled_total", self.cancelled, "jobs cancelled")
            lines.append("# HELP pending_task_queue_size pending tasks")
            lines.append("# TYPE pending_task_queue_size gauge")
            lines.append(f"pending_task_queue_size {self.pending_tasks}")
            for name, h, help_ in [
                ("planning_time_seconds", self.planning_time, "job planning time"),
                ("job_exec_time_seconds", self.exec_time, "job execution time"),
            ]:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} histogram")
                acc = 0
                for b, c in zip(h.buckets, h.counts):
                    acc += c
                    lines.append(f'{name}_bucket{{le="{b}"}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {h.n}')
                lines.append(f"{name}_sum {h.total}")
                lines.append(f"{name}_count {h.n}")
            return "\n".join(lines) + "\n"
