"""Scheduler metrics: counters/histograms + prometheus text exposition.

Parity: reference ballista/scheduler/src/metrics/ — the
``SchedulerMetricsCollector`` trait (mod.rs:10-66) with its Prometheus
implementation (prometheus.rs:41-176: job_exec_time_seconds,
planning_time_seconds histograms; submitted/completed/failed/cancelled
counters; pending_task_queue_size gauge) and the Noop default.  Metric
names match docs/source/user-guide/metrics.md so reference dashboards
port over unchanged.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

_BUCKETS = [0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0]


class Histogram:
    def __init__(self, buckets: Optional[List[float]] = None):
        self.buckets = buckets or _BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.n += 1
        self.total += v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class SchedulerMetricsCollector:
    """Trait (reference metrics/mod.rs:10-66)."""

    def record_submitted(self, job_id: str, queued_at_ms: int, submitted_at_ms: int) -> None: ...
    def record_completed(self, job_id: str, queued_at_ms: int, completed_at_ms: int) -> None: ...
    def record_failed(self, job_id: str) -> None: ...
    def record_cancelled(self, job_id: str) -> None: ...
    def set_pending_tasks_queue_size(self, value: int) -> None: ...
    # admission control (arrow_ballista_tpu/admission/)
    def record_admitted(self, job_id: str, queue_wait_s: float) -> None: ...
    def record_shed(self, job_id: str) -> None: ...
    def record_memory_shed(self, job_id: str) -> None: ...
    def set_admission_queue_depth(self, value: int) -> None: ...
    # executor quarantine (scheduler/quarantine.py)
    def record_quarantined(self, executor_id: str) -> None: ...
    def set_quarantined_executors(self, value: int) -> None: ...
    # speculative execution + shuffle integrity (scheduler/speculation.py,
    # net/dataplane.py checksum verification)
    def record_speculative_launched(self, job_id: str) -> None: ...
    def record_speculative_win(self, job_id: str) -> None: ...
    def record_integrity_failure(self, executor_id: str) -> None: ...
    # adaptive query execution (scheduler/aqe.py)
    def record_aqe_coalesce(self, partitions: int) -> None: ...
    def record_aqe_broadcast_switch(self, joins: int) -> None: ...
    def record_aqe_skew_split(self, partitions: int) -> None: ...
    # event-loop saturation (scheduler/event_loop.py, sampled by the
    # cluster-history thread)
    def set_event_queue_depth(self, value: int) -> None: ...
    def set_event_loop_lag(self, seconds: float) -> None: ...
    # device observatory (obs/device.py; shipped as
    # TaskStatus.device_stats and folded fleet-wide on status intake)
    def record_device_stats(self, device_stats: Dict[str, float]) -> None: ...
    # serving caches (scheduler/serving_cache.py)
    def record_plan_cache_hit(self) -> None: ...
    def record_plan_cache_miss(self) -> None: ...
    def record_result_cache_hit(self) -> None: ...
    def record_cache_eviction(self) -> None: ...
    # flight recorder (obs/journal.py): events accepted into / evicted
    # from the journal ring + per-job timelines
    def record_journal_events(self, n: int) -> None: ...
    def record_journal_dropped(self, n: int) -> None: ...
    # live observability plane (obs/live.py + obs/slo.py): standing
    # in-flight alerts and per-window SLO burn-rate gauges
    def set_alerts_active(self, value: int) -> None: ...
    def set_slo_burn_rate(self, window: str, value: float) -> None: ...
    # query lifecycle guardrails (server-side deadlines, poison-query
    # containment, zombie-task reconciliation)
    def record_deadline_exceeded(self, job_id: str) -> None: ...
    def record_poisoned(self, job_id: str) -> None: ...
    def record_zombies_reaped(self, n: int) -> None: ...
    def gather(self) -> str:
        return ""


class NoopMetricsCollector(SchedulerMetricsCollector):
    pass


class InMemoryMetricsCollector(SchedulerMetricsCollector):
    """Collects + renders prometheus text exposition format."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.cancelled = 0
        self.pending_tasks = 0
        self.planning_time = Histogram([0.01, 0.05, 0.1, 0.5, 1.0, 5.0])
        self.exec_time = Histogram()
        self.admitted = 0
        self.shed = 0
        self.memory_sheds = 0
        self.admission_queue_depth = 0
        self.admission_queue_depth_max = 0
        self.admission_wait = Histogram([0.001, 0.01, 0.1, 0.5, 1.0, 5.0,
                                         30.0, 120.0])
        self.quarantined_total = 0
        self.quarantined_executors = 0
        self.speculative_launched = 0
        self.speculative_wins = 0
        self.integrity_failures = 0
        self.aqe_coalesced = 0
        self.aqe_broadcast_switches = 0
        self.aqe_skew_splits = 0
        self.event_queue_depth = 0
        self.event_loop_lag_s = 0.0
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        self.result_cache_hits = 0
        self.cache_evictions = 0
        self.journal_events = 0
        self.journal_dropped = 0
        self.alerts_active = 0
        # burn window name ("fast"/"slow") -> most recent burn rate
        self.slo_burn_rate: Dict[str, float] = {}
        # query lifecycle guardrails: both deadline/poison verdicts ALSO
        # count in `failed` (they publish a failed terminal status); these
        # break the failure total down by cause
        self.deadline_exceeded = 0
        self.poisoned = 0
        self.zombies_reaped = 0
        # fleet-wide device-observatory fold (TaskStatus.device_stats
        # intake): counters sum across every task the fleet absorbed,
        # watermarks keep the max any single task reported
        self.device_jit_compiles = 0
        self.device_jit_retraces = 0
        self.device_compile_seconds = 0.0
        self.device_h2d_bytes = 0
        self.device_d2h_bytes = 0
        self.device_mem_peak = 0
        self.device_host_mem_peak = 0

    def record_submitted(self, job_id, queued_at_ms, submitted_at_ms):
        with self._lock:
            self.submitted += 1
            self.planning_time.observe(max(0.0, (submitted_at_ms - queued_at_ms) / 1e3))

    def record_completed(self, job_id, queued_at_ms, completed_at_ms):
        with self._lock:
            self.completed += 1
            self.exec_time.observe(max(0.0, (completed_at_ms - queued_at_ms) / 1e3))

    def record_failed(self, job_id):
        with self._lock:
            self.failed += 1

    def record_cancelled(self, job_id):
        with self._lock:
            self.cancelled += 1

    def set_pending_tasks_queue_size(self, value):
        with self._lock:
            self.pending_tasks = value

    def record_admitted(self, job_id, queue_wait_s):
        with self._lock:
            self.admitted += 1
            self.admission_wait.observe(max(0.0, queue_wait_s))

    def record_shed(self, job_id):
        with self._lock:
            self.shed += 1

    def record_memory_shed(self, job_id):
        with self._lock:
            self.memory_sheds += 1

    def set_admission_queue_depth(self, value):
        with self._lock:
            self.admission_queue_depth = value
            self.admission_queue_depth_max = max(
                self.admission_queue_depth_max, value)

    def record_quarantined(self, executor_id):
        with self._lock:
            self.quarantined_total += 1

    def set_quarantined_executors(self, value):
        with self._lock:
            self.quarantined_executors = value

    def record_speculative_launched(self, job_id):
        with self._lock:
            self.speculative_launched += 1

    def record_speculative_win(self, job_id):
        with self._lock:
            self.speculative_wins += 1

    def record_integrity_failure(self, executor_id):
        with self._lock:
            self.integrity_failures += 1

    def record_aqe_coalesce(self, partitions):
        with self._lock:
            self.aqe_coalesced += partitions

    def record_aqe_broadcast_switch(self, joins):
        with self._lock:
            self.aqe_broadcast_switches += joins

    def record_aqe_skew_split(self, partitions):
        with self._lock:
            self.aqe_skew_splits += partitions

    def set_event_queue_depth(self, value):
        with self._lock:
            self.event_queue_depth = value

    def set_event_loop_lag(self, seconds):
        with self._lock:
            self.event_loop_lag_s = seconds

    def record_device_stats(self, device_stats):
        with self._lock:
            self.device_jit_compiles += int(
                device_stats.get("jit_compiles", 0))
            self.device_jit_retraces += int(
                device_stats.get("jit_retraces", 0))
            self.device_compile_seconds += float(
                device_stats.get("jit_compile_time", 0.0))
            self.device_h2d_bytes += int(device_stats.get("h2d_bytes", 0))
            self.device_d2h_bytes += int(device_stats.get("d2h_bytes", 0))
            self.device_mem_peak = max(
                self.device_mem_peak,
                int(device_stats.get("device_mem_peak", 0)))
            self.device_host_mem_peak = max(
                self.device_host_mem_peak,
                int(device_stats.get("host_mem_peak", 0)))

    def record_plan_cache_hit(self):
        with self._lock:
            self.plan_cache_hits += 1

    def record_plan_cache_miss(self):
        with self._lock:
            self.plan_cache_misses += 1

    def record_result_cache_hit(self):
        with self._lock:
            self.result_cache_hits += 1

    def record_cache_eviction(self):
        with self._lock:
            self.cache_evictions += 1

    def record_journal_events(self, n):
        with self._lock:
            self.journal_events += n

    def record_journal_dropped(self, n):
        with self._lock:
            self.journal_dropped += n

    def set_alerts_active(self, value):
        with self._lock:
            self.alerts_active = int(value)

    def set_slo_burn_rate(self, window, value):
        with self._lock:
            self.slo_burn_rate[str(window)] = float(value)

    def record_deadline_exceeded(self, job_id):
        with self._lock:
            self.deadline_exceeded += 1

    def record_poisoned(self, job_id):
        with self._lock:
            self.poisoned += 1

    def record_zombies_reaped(self, n):
        with self._lock:
            self.zombies_reaped += n

    def counters_snapshot(self) -> Dict[str, float]:
        """Plain-dict view of the scalar counters/gauges (the forensics
        bundle embeds this so the doctor's cache/churn rules read metric
        values, not prometheus text)."""
        with self._lock:
            return {
                "job_submitted_total": self.submitted,
                "job_completed_total": self.completed,
                "job_failed_total": self.failed,
                "job_cancelled_total": self.cancelled,
                "plan_cache_hits": self.plan_cache_hits,
                "plan_cache_misses": self.plan_cache_misses,
                "result_cache_hits": self.result_cache_hits,
                "cache_evictions": self.cache_evictions,
                "speculative_launched": self.speculative_launched,
                "speculative_wins": self.speculative_wins,
                "memory_pressure_sheds_total": self.memory_sheds,
                "quarantined_total": self.quarantined_total,
                "quarantined_executors": self.quarantined_executors,
                "integrity_failures": self.integrity_failures,
                "aqe_coalesced": self.aqe_coalesced,
                "aqe_broadcast_switches": self.aqe_broadcast_switches,
                "aqe_skew_splits": self.aqe_skew_splits,
                "device_jit_compiles": self.device_jit_compiles,
                "device_jit_retraces": self.device_jit_retraces,
                "device_compile_seconds":
                    round(self.device_compile_seconds, 6),
                "event_loop_lag_s": self.event_loop_lag_s,
                "journal_events": self.journal_events,
                "journal_dropped": self.journal_dropped,
                "alerts_active": self.alerts_active,
                "jobs_deadline_exceeded_total": self.deadline_exceeded,
                "jobs_poisoned_total": self.poisoned,
                "zombie_tasks_reaped_total": self.zombies_reaped,
                **{f"slo_burn_rate_{w}": v
                   for w, v in sorted(self.slo_burn_rate.items())},
            }

    def gather(self) -> str:
        with self._lock:
            lines = []

            def counter(name, v, help_):
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {v}")

            counter("job_submitted_total", self.submitted, "jobs submitted")
            counter("job_completed_total", self.completed, "jobs completed")
            counter("job_failed_total", self.failed, "jobs failed")
            counter("job_cancelled_total", self.cancelled, "jobs cancelled")
            counter("job_admitted_total", self.admitted,
                    "jobs admitted by admission control")
            counter("job_shed_total", self.shed,
                    "jobs shed by admission control (queue full / timeout)")
            counter("memory_pressure_sheds_total", self.memory_sheds,
                    "jobs shed or deferred because every alive executor's "
                    "memory-governor pressure exceeded "
                    "ballista.memory.pressure.shed.threshold")
            counter("executor_quarantined_total", self.quarantined_total,
                    "executors quarantined after consecutive retryable "
                    "task failures")
            counter("speculative_tasks_launched_total",
                    self.speculative_launched,
                    "speculative duplicate attempts launched against "
                    "straggling tasks")
            counter("speculative_wins_total", self.speculative_wins,
                    "partitions whose speculative attempt finished before "
                    "the original")
            counter("shuffle_integrity_failures_total",
                    self.integrity_failures,
                    "shuffle partitions that failed checksum/decode "
                    "verification after fetch retries")
            counter("aqe_coalesced_partitions_total", self.aqe_coalesced,
                    "planned reduce partitions merged away by adaptive "
                    "partition coalescing")
            counter("aqe_broadcast_switches_total",
                    self.aqe_broadcast_switches,
                    "partitioned joins flipped to broadcast at runtime "
                    "after their build side measured small")
            counter("aqe_skew_splits_total", self.aqe_skew_splits,
                    "hot partitions split into multiple tasks by adaptive "
                    "skew mitigation")
            counter("plan_cache_hits_total", self.plan_cache_hits,
                    "SQL submissions served from a prepared-plan template "
                    "(parse/plan/validate skipped)")
            counter("plan_cache_misses_total", self.plan_cache_misses,
                    "SQL submissions that planned from scratch (no valid "
                    "template for the text/params/config/table versions)")
            counter("result_cache_hits_total", self.result_cache_hits,
                    "queries or shuffle stages served from cached results "
                    "without executing any task")
            counter("cache_evictions_total", self.cache_evictions,
                    "plan templates and result/subplan entries evicted by "
                    "the serving caches' LRU byte/entry budgets")
            counter("journal_events_total", self.journal_events,
                    "flight-recorder events accepted into the scheduler's "
                    "journal (own emissions + executor events absorbed "
                    "from TaskStatus piggybacks)")
            counter("journal_events_dropped_total", self.journal_dropped,
                    "flight-recorder events evicted from the bounded "
                    "journal ring or a per-job timeline at capacity")
            counter("jobs_deadline_exceeded_total", self.deadline_exceeded,
                    "jobs cancelled fleet-wide because they exceeded their "
                    "server-side ballista.query.deadline.seconds budget "
                    "(also counted in job_failed_total)")
            counter("jobs_poisoned_total", self.poisoned,
                    "jobs failed fast by poison-query containment: the "
                    "same partition failed with equivalent errors on "
                    "ballista.poison.distinct_executors distinct executors "
                    "(also counted in job_failed_total)")
            counter("zombie_tasks_reaped_total", self.zombies_reaped,
                    "running tasks reported on executor heartbeats whose "
                    "job was already terminal or unknown — the scheduler "
                    "re-issued the kill the original cancel fanout lost")
            counter("fleet_device_jit_compiles_total",
                    self.device_jit_compiles,
                    "first-time XLA compilations reported by completed "
                    "tasks across the fleet (TaskStatus.device_stats)")
            counter("fleet_device_jit_retraces_total",
                    self.device_jit_retraces,
                    "jit retraces (new shape/static-arg keys of "
                    "already-compiled programs) reported by completed "
                    "tasks across the fleet")
            counter("fleet_device_compile_seconds_total",
                    round(self.device_compile_seconds, 6),
                    "wall time tasks spent inside compiling jit "
                    "dispatches, summed fleet-wide")
            counter("fleet_device_h2d_bytes_total", self.device_h2d_bytes,
                    "host->device transfer bytes reported by completed "
                    "tasks across the fleet")
            counter("fleet_device_d2h_bytes_total", self.device_d2h_bytes,
                    "device->host transfer bytes reported by completed "
                    "tasks across the fleet")
            lines.append("# HELP fleet_device_mem_peak_bytes largest live "
                         "device-buffer watermark any single task reported")
            lines.append("# TYPE fleet_device_mem_peak_bytes gauge")
            lines.append(f"fleet_device_mem_peak_bytes {self.device_mem_peak}")
            lines.append("# HELP fleet_host_mem_peak_bytes largest host RSS "
                         "watermark any single task reported")
            lines.append("# TYPE fleet_host_mem_peak_bytes gauge")
            lines.append(
                f"fleet_host_mem_peak_bytes {self.device_host_mem_peak}")
            lines.append("# HELP quarantined_executors executors currently "
                         "quarantined (no new offers)")
            lines.append("# TYPE quarantined_executors gauge")
            lines.append(
                f"quarantined_executors {self.quarantined_executors}")
            lines.append("# HELP pending_task_queue_size pending tasks")
            lines.append("# TYPE pending_task_queue_size gauge")
            lines.append(f"pending_task_queue_size {self.pending_tasks}")
            lines.append("# HELP admission_queue_depth jobs waiting for admission")
            lines.append("# TYPE admission_queue_depth gauge")
            lines.append(f"admission_queue_depth {self.admission_queue_depth}")
            lines.append("# HELP admission_queue_depth_max high-water mark "
                         "of jobs waiting for admission")
            lines.append("# TYPE admission_queue_depth_max gauge")
            lines.append(
                f"admission_queue_depth_max {self.admission_queue_depth_max}")
            lines.append("# HELP scheduler_event_queue_depth events waiting "
                         "in the scheduler event loop")
            lines.append("# TYPE scheduler_event_queue_depth gauge")
            lines.append(
                f"scheduler_event_queue_depth {self.event_queue_depth}")
            lines.append("# HELP scheduler_event_loop_lag_seconds "
                         "enqueue-to-dequeue lag of the most recent event")
            lines.append("# TYPE scheduler_event_loop_lag_seconds gauge")
            lines.append(
                f"scheduler_event_loop_lag_seconds {self.event_loop_lag_s}")
            lines.append("# HELP alerts_active standing in-flight doctor "
                         "alerts (raised, not yet cleared) on this shard")
            lines.append("# TYPE alerts_active gauge")
            lines.append(f"alerts_active {self.alerts_active}")
            lines.append("# HELP slo_burn_rate rate the latency-SLO error "
                         "budget is being consumed per burn window "
                         "(1.0 = exactly sustainable), shard-local")
            lines.append("# TYPE slo_burn_rate gauge")
            for w in sorted(self.slo_burn_rate):
                lines.append(
                    f'slo_burn_rate{{window="{w}"}} {self.slo_burn_rate[w]}')
            for name, h, help_ in [
                ("planning_time_seconds", self.planning_time, "job planning time"),
                ("job_exec_time_seconds", self.exec_time, "job execution time"),
                ("admission_queue_wait_seconds", self.admission_wait,
                 "time jobs waited for admission"),
            ]:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} histogram")
                acc = 0
                for b, c in zip(h.buckets, h.counts):
                    acc += c
                    lines.append(f'{name}_bucket{{le="{b}"}} {acc}')
                lines.append(f'{name}_bucket{{le="+Inf"}} {h.n}')
                lines.append(f"{name}_sum {h.total}")
                lines.append(f"{name}_count {h.n}")
            return "\n".join(lines) + "\n"
