"""Logical plan -> physical plan with exchange (repartition) insertion.

The reference delegates physical planning to DataFusion and then splits the
result into stages (reference ballista/scheduler/src/state/mod.rs:315-380
``plan_job`` -> planner.rs stage split).  Here physical planning inserts
``RepartitionExec`` markers at the same boundaries DataFusion would
(partial/final aggregates, partitioned joins, shuffle-to-one before sorts),
and ``scheduler/planner.py`` (DistributedPlanner) splits at those markers.

TPU-specific decisions made here:
- **host-finalize projections**: any projection producing float64 (division)
  runs host-side in numpy — keeps the device program f64-free;
- **broadcast joins**: build sides with small estimated row counts skip the
  shuffle (every probe partition reads the whole build side);
- static capacities (agg groups, join fan-out) come from session config.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..catalog import SchemaCatalog
from ..models import expr as E
from ..models import logical as L
from ..ops import operators as O
from ..ops.physical import ExecutionPlan, Partitioning
from ..ops.shuffle import RepartitionExec
from ..utils.config import (
    BROADCAST_THRESHOLD,
    MESH_HYBRID,
    MESH_MIN_ROWS,
    MESH_SHUFFLE,
    BallistaConfig,
)
from ..utils.errors import PlanningError


def _has_float_subexpr(e: E.Expr, schema) -> bool:
    """True if any subexpression is float-typed: such expressions must run
    host-side to keep device programs f64-free (the decimal discipline)."""
    try:
        if e.dtype(schema).kind in ("float32", "float64"):
            return True
    # ballista: allow=recovery-path-logging — typing probe, not recovery
    except Exception:  # noqa: BLE001 — untypable nodes (subquery carriers)
        pass
    return any(_has_float_subexpr(c, schema) for c in e.children())


@dataclasses.dataclass
class PlannedQuery:
    plan: ExecutionPlan
    # scalar subqueries to execute before the main job: (scalar_id, plan)
    scalars: List[Tuple[str, ExecutionPlan]]


def explain_rows(catalog, config, statement, verbose: bool = False):
    """DataFusion-shaped EXPLAIN rows, shared by the local client path and
    the scheduler's wire handler so the two cannot drift.  ``verbose`` adds
    the distributed stage decomposition (the exchange boundaries the
    DistributedPlanner will split at)."""
    from ..sql.optimizer import optimize
    from ..sql.planner import SqlToRel

    optimized = optimize(SqlToRel(catalog).plan(statement))
    planned = PhysicalPlanner(catalog, config).plan_query(optimized)
    rows = [
        {"plan_type": "logical_plan", "plan": optimized.display()},
        {"plan_type": "physical_plan", "plan": planned.plan.display()},
    ]
    if verbose:
        from .planner import DistributedPlanner

        stages = DistributedPlanner().plan_query_stages("explain", planned.plan)
        text = "\n".join(
            f"Stage {s.stage_id}:\n{s.plan.display(1)}" for s in stages)
        rows.append({"plan_type": "distributed_plan", "plan": text})
    return rows


class PhysicalPlanner:
    def __init__(self, catalog: SchemaCatalog, config: BallistaConfig):
        self.catalog = catalog
        self.config = config
        self._scalars: List[Tuple[str, ExecutionPlan]] = []
        self._scalar_seq = 0
        self._partitions: Optional[int] = None

    @property
    def partitions(self) -> int:
        """Effective shuffle partition count.  'auto' (0) derives it from
        the largest scanned table so each task's batch stays near the
        configured batch capacity — the memory-control heuristic the
        reference leaves as TODOs (HBM is small; partition counts are how
        a static-shape engine bounds per-task footprint)."""
        if self._partitions is None:
            self._partitions = self.config.shuffle_partitions or 8
        return self._partitions

    def _resolve_auto_partitions(self, logical: L.LogicalPlan) -> None:
        if self.config.shuffle_partitions != 0:
            self._partitions = self.config.shuffle_partitions
            return
        target = max(1, self.config.batch_size)
        rows = 0
        row_bytes = 0

        def walk(node: L.LogicalPlan):
            nonlocal rows, row_bytes
            if isinstance(node, L.TableScan):
                try:
                    rc = self.catalog.provider(node.table).row_count()
                # ballista: allow=recovery-path-logging — stats probe
                except Exception:  # noqa: BLE001 — stats are best-effort
                    rc = None
                if (rc or 0) > rows:
                    rows = rc or 0
                    try:
                        # node.schema is the PROJECTED scan schema
                        # (projection pushdown already ran), so the width
                        # reflects the columns a task actually holds
                        row_bytes = node.schema.row_byte_width()
                    # ballista: allow=recovery-path-logging — stats probe
                    except Exception:  # noqa: BLE001
                        row_bytes = 64
            for c in node.children():
                walk(c)

        walk(logical)
        if not rows:
            self._partitions = 8
            return
        base = max(1, -(-rows // target))
        # stats-driven memory control (VERDICT r4 #6): a task's input is
        # ~(rows/partitions) * row_bytes, so the per-task budget sets a
        # partition-count FLOOR; the cap relaxes from 64 to 256 only under
        # budget pressure (fine partitioning costs scheduling overhead,
        # so it is bought only when memory demands it)
        from ..utils.config import resolve_task_budget

        budget = resolve_task_budget(self.config)
        if budget:
            need = -(-rows * row_bytes // budget)
            self._partitions = min(256, max(min(64, base), need, 1))
        else:
            self._partitions = min(64, base)

    # --- entry ----------------------------------------------------------
    def plan_query(self, logical: L.LogicalPlan) -> PlannedQuery:
        self._scalars = []
        self._resolve_auto_partitions(logical)
        plan = self.create(logical)
        self._clustered_having_pushdown(plan)
        for _sid, sub in self._scalars:
            self._clustered_having_pushdown(sub)
        return PlannedQuery(plan, list(self._scalars))

    def create(self, node: L.LogicalPlan) -> ExecutionPlan:
        if isinstance(node, L.TableScan):
            provider = self.catalog.provider(node.table)
            filters = [self._prep_expr(f) for f in node.filters]
            return provider.scan(node.projection, filters, self.partitions)

        if isinstance(node, L.SubqueryAlias):
            child = self.create(node.input)
            return O.RenameExec(child, node.schema)

        if isinstance(node, L.Projection):
            child = self.create(node.input)
            exprs = [(self._prep_expr(e), n) for e, n in node.exprs]
            host = any(e.dtype(child.schema).kind == "float64" for e, _ in exprs)
            return O.ProjectionExec(child, exprs, host_mode=host)

        if isinstance(node, L.Filter):
            child = self.create(node.input)
            pred = self._prep_expr(node.predicate)
            return O.FilterExec(child, pred,
                                host_mode=_has_float_subexpr(pred, child.schema))

        if isinstance(node, L.Aggregate):
            return self._plan_aggregate(node)

        if isinstance(node, L.Distinct):
            child_logical = node.input
            groups = [(E.Column(f.name), f.name) for f in child_logical.schema]
            agg = L.Aggregate(child_logical, groups, [])
            return self._plan_aggregate(agg)

        if isinstance(node, L.Join):
            return self._plan_join(node)

        if isinstance(node, L.CrossJoin):
            raise PlanningError("cross joins are not supported yet")

        if isinstance(node, L.Sort):
            child = self.create(node.input)
            child = self._to_single_partition(child)
            keys = [(self._prep_expr(e), asc) for e, asc in node.keys]
            return O.SortExec(child, keys)

        if isinstance(node, L.Limit):
            if isinstance(node.input, L.Sort):
                child = self.create(node.input.input)
                child = self._to_single_partition(child)
                keys = [(self._prep_expr(e), asc) for e, asc in node.input.keys]
                return O.SortExec(child, keys, fetch=node.n)
            child = self.create(node.input)
            return O.LimitExec(child, node.n)

        raise PlanningError(f"cannot create physical plan for {type(node).__name__}")

    # --- pieces ---------------------------------------------------------
    def _prep_expr(self, e: E.Expr) -> E.Expr:
        """Assign stable ids to scalar subqueries and plan them."""
        if isinstance(e, E.ScalarSubquery):
            sid = getattr(e, "scalar_id", None)
            if sid is None:
                sid = f"sq{self._scalar_seq}"
                self._scalar_seq += 1
                object.__setattr__(e, "scalar_id", sid)
                sub_physical = self.create(e.plan)
                sub_physical = self._to_single_partition(sub_physical)
                self._scalars.append((sid, sub_physical))
            return e
        from ..sql.planner import _map_children

        return _map_children(e, self._prep_expr)

    def _to_single_partition(self, plan: ExecutionPlan) -> ExecutionPlan:
        if plan.output_partition_count() <= 1:
            return plan
        return RepartitionExec(plan, Partitioning.single())

    def _plan_aggregate(self, node: L.Aggregate) -> ExecutionPlan:
        node = self._rewrite_distinct_aggs(node)
        child = self.create(node.input)
        groups = [(self._prep_expr(e), n) for e, n in node.group_exprs]
        specs = []
        for a, n in node.agg_exprs:
            if a.distinct:
                raise PlanningError("DISTINCT aggregates not supported yet")
            operand = self._prep_expr(a.operand) if a.operand is not None else None
            specs.append(O.AggSpec(a.func, operand, n))

        single_input = child.output_partition_count() <= 1
        if single_input:
            return O.HashAggregateExec(child, groups, specs, mode="single")

        # TPU fast path: fuse partial agg -> all_to_all -> final agg into one
        # XLA program over the local device mesh (ops/mesh_exec.py) instead
        # of a file-shuffle stage pair.  Hybrid mode keeps the stage pair
        # (tasks spread over executors, file shuffle across hosts) and
        # meshes only the per-task partial — the multi-HOST composition.
        # Adaptive: small exchanges stay on the file path (measured faster
        # there — BENCH_r04 q3 SF1 3.6 s file vs 6.4 s mesh; the mesh's
        # no-materialization advantage only wins at scale, SF10 q3 46 s
        # mesh vs 51 s file), gated on the same row estimates the join
        # broadcast decision already trusts.
        if self.config.get(MESH_SHUFFLE) and (
                self.config.get(MESH_HYBRID)  # explicit multi-host mode
                or self._mesh_worthwhile(self._estimate_rows(node.input))):
            from ..ops.mesh_exec import MeshAggregateExec, MeshPartialAggregateExec

            if MeshAggregateExec.eligible(groups, specs, child.schema):
                if self.config.get(MESH_HYBRID):
                    # eligible() guarantees non-empty groups here (global
                    # aggregates take the plain path)
                    partial = MeshPartialAggregateExec(child, groups, specs)
                    key_exprs = tuple(E.Column(n) for _, n in groups)
                    exchange = RepartitionExec(
                        partial,
                        Partitioning.hash(key_exprs,
                                          self.partitions))
                    final_groups = [(E.Column(n), n) for _, n in groups]
                    return O.HashAggregateExec(exchange, final_groups, specs,
                                               mode="final")
                return MeshAggregateExec(child, groups, specs)

        partial = O.HashAggregateExec(child, groups, specs, mode="partial")
        if groups:
            key_exprs = tuple(E.Column(n) for _, n in groups)
            exchange = RepartitionExec(
                partial, Partitioning.hash(key_exprs, self.partitions)
            )
        else:
            exchange = RepartitionExec(partial, Partitioning.single())
        final_groups = [(E.Column(n), n) for _, n in groups]
        return O.HashAggregateExec(exchange, final_groups, specs, mode="final")

    def _rewrite_distinct_aggs(self, node: L.Aggregate) -> L.Aggregate:
        """agg(distinct x) -> dedup-by-(groups, x) aggregate feeding a plain
        aggregate (the classic two-level rewrite; DataFusion does the same
        for the reference via single_distinct_to_groupby)."""
        distincts = [(a, n) for a, n in node.agg_exprs if a.distinct]
        if not distincts:
            return node
        if len(distincts) != len(node.agg_exprs):
            raise PlanningError("mixing DISTINCT and plain aggregates is not supported")
        operands = {str(a.operand) for a, _ in distincts}
        if len(operands) != 1 or distincts[0][0].operand is None:
            raise PlanningError("DISTINCT aggregates must share one operand")
        dkey = "__distinct_key"
        inner_groups = list(node.group_exprs) + [(distincts[0][0].operand, dkey)]
        inner = L.Aggregate(node.input, inner_groups, [])
        outer_groups = [(E.Column(n), n) for _, n in node.group_exprs]
        outer_aggs = [(E.Agg(a.func, E.Column(dkey)), n) for a, n in distincts]
        return L.Aggregate(inner, outer_groups, outer_aggs)

    def _reorder_inner_chain(self, node: L.Join) -> L.Join:
        """Reorder a left-deep chain of INNER equi-joins so the most
        selective builds apply first (greedy ascending build-size estimate,
        subject to key-column availability).  Inner joins commute; applying
        a 25-row filtered dimension before a 1.5M-row one cuts the probe
        early (q21: nation's n_name filter reduced 3.7M rows to 155k but
        ran LAST in SQL order — 28 task-seconds probing orders for rows
        the nation join was about to discard).  The reference inherits the
        analogous join selection from DataFusion's optimizer."""
        chain = []  # (right, on, filter) from the top down
        cur: L.LogicalPlan = node
        while isinstance(cur, L.Join) and cur.join_type == "inner" \
                and cur.on:
            chain.append((cur.right, cur.on, cur.filter))
            cur = cur.left
        if len(chain) < 2:
            return node
        base = cur
        chain.reverse()  # original application order

        def deps(on, filt, right_names):
            refs = set()
            for le, _re in on:
                refs |= le.column_refs()
            if filt is not None:
                refs |= filt.column_refs() - right_names
            return refs

        items = []
        for right, on, filt in chain:
            rnames = {f.name for f in right.schema}
            items.append({"right": right, "on": on, "filter": filt,
                          "names": rnames,
                          "deps": deps(on, filt, rnames),
                          "est": self._estimate_rows(right)})
        available = {f.name for f in base.schema}
        order = []
        remaining = list(items)
        while remaining:
            ready = [it for it in remaining if it["deps"] <= available]
            if not ready:
                return node  # cross-dependency we don't model: keep SQL order
            pick = min(ready, key=lambda it: it["est"])
            order.append(pick)
            available |= pick["names"]
            remaining.remove(pick)
        # identity comparison: the logical nodes are field-less dataclasses
        # whose generated __eq__ compares nothing (all same-class instances
        # are "equal"), so == would always report the order unchanged
        if all(a["right"] is b["right"] for a, b in zip(order, items)):
            return node
        out: L.LogicalPlan = base
        for it in order:
            out = L.Join(out, it["right"], it["on"], "inner", it["filter"])
        return out

    def _plan_join(self, node: L.Join) -> ExecutionPlan:
        if node.join_type == "inner":
            node = self._reorder_inner_chain(node)
        left = self.create(node.left)
        right = self.create(node.right)
        on = [(self._prep_expr(l), self._prep_expr(r)) for l, r in node.on]
        filt = self._prep_expr(node.filter) if node.filter is not None else None

        # side ordering (inner joins are symmetric; the reference gets this
        # from DataFusion's join selection): when either side fits the
        # broadcast threshold, make the SMALLER side the BUILD (right) —
        # the big probe side then streams partition-parallel with NO
        # repartition at all.  Both-sides-big partitioned joins keep their
        # SQL order (output capacity is count-sized, so a swap would only
        # move the build argsort onto the bigger side).  Column order in
        # the output schema changes; downstream resolves by name.
        left_est = self._estimate_rows(node.left)
        right_est = self._estimate_rows(node.right)
        if node.join_type == "inner" \
                and min(left_est, right_est) <= self.config.get(BROADCAST_THRESHOLD) \
                and left_est < right_est:
            left, right = right, left
            on = [(r, l) for l, r in on]
            left_est, right_est = right_est, left_est

        if node.join_type != "full" and \
                right_est <= self.config.get(BROADCAST_THRESHOLD):
            # full joins can't broadcast: unmatched build rows would be
            # emitted once per probe partition
            right_bc = self._to_single_partition(right)
            return O.JoinExec(left, right_bc, on, node.join_type, filt, dist="broadcast")

        # TPU fast path: fuse both hash repartitions + the join into one XLA
        # program over the local device mesh (ops/mesh_exec.py MeshJoinExec).
        # Hybrid mode keeps the partitioned stage structure (file shuffle
        # across hosts) and meshes only the per-task join — the multi-HOST
        # composition, mirroring MeshPartialAggregateExec.
        if self.config.get(MESH_SHUFFLE) and not self.config.get(MESH_HYBRID) \
                and self._mesh_worthwhile(left_est + right_est):
            from ..ops.mesh_exec import MeshJoinExec

            if MeshJoinExec.eligible(on, node.join_type, filt,
                                     left.schema, right.schema):
                return MeshJoinExec(left, right, on, node.join_type)

        p = self.partitions
        lkeys = tuple(l for l, _ in on)
        rkeys = tuple(r for _, r in on)
        lpart = RepartitionExec(left, Partitioning.hash(lkeys, p))
        rpart = RepartitionExec(right, Partitioning.hash(rkeys, p))
        if self.config.get(MESH_SHUFFLE) and self.config.get(MESH_HYBRID):
            from ..ops.mesh_exec import MeshTaskJoinExec

            if MeshTaskJoinExec.eligible(on, node.join_type, filt,
                                         left.schema, right.schema):
                return MeshTaskJoinExec(lpart, rpart, on, node.join_type)
        return O.JoinExec(lpart, rpart, on, node.join_type, filt, dist="partitioned")

    def _clustered_having_pushdown(self, plan: ExecutionPlan) -> None:
        """Clustered group-by early-HAVING rewrite.

        Pattern: Filter(pred) <- HashAgg(final) <- Repartition(hash keys)
        <- HashAgg(partial) <- Rename* <- ParquetScan, with ONE int group
        key whose parquet row-group stats prove the data is clustered on
        it.  Then a contiguous-partition partial aggregate is already
        FINAL for every key outside neighbor-overlap windows, so the
        HAVING predicate applies in-task and the exchange ships only
        survivors + window keys (q18 SF10: 15M states -> ~700 rows).

        The reference cannot do this: DataFusion's AggregateExec split
        (the stage shape behind reference planner.rs:133-152) has no
        notion of scan clustering.  Static-shape engines WANT it — the
        exchange is the expensive, dynamic part."""
        from ..ops.physical import ParquetScanExec
        from ..ops.shuffle import RepartitionExec as Rep

        def annotate(agg_p, pred) -> bool:
            """Mark a partial agg clustered if eligible.  ``pred`` is the
            downstream HAVING predicate (early-filter form) or None
            (presorted-only form: sort-free grouping, exchange unchanged —
            on TPU this alone removes the minutes-compile sort family)."""
            if len(agg_p.group_exprs) != 1:
                return False
            ge, _gname = agg_p.group_exprs[0]
            if not isinstance(ge, E.Column):
                return False
            if any(a.func not in ("sum", "count", "min", "max")
                   for a in agg_p.aggs):
                return False
            if pred is not None:
                from ..ops.physical import has_scalar_subquery

                if has_scalar_subquery(pred):
                    return False
                if not pred.column_refs() <= set(agg_p.schema.names()):
                    return False
            # resolve the group key through renames down to the scan column
            child, col = agg_p.input, ge.name
            while isinstance(child, O.RenameExec):
                rev = {new: old for old, new in child._mapping}
                if col not in rev:
                    return False
                col = rev[col]
                child = child.input
            if not isinstance(child, ParquetScanExec):
                return False
            try:
                if child.schema.field(col).dtype.np_dtype.kind not in "iu":
                    return False
            # ballista: allow=recovery-path-logging — eligibility probe
            except Exception:  # noqa: BLE001
                return False
            probe = child.clustered_ranges(col)
            if probe is None:
                return False
            groups, ranges = probe
            if not ranges or len(ranges) <= 1:
                # a rejected probe must leave the scan untouched (the
                # regroup would have collapsed its partitions)
                return False
            intervals = [(lo_b, hi_a)
                         for (_lo_a, hi_a), (lo_b, _hi_b)
                         in zip(ranges, ranges[1:]) if lo_b <= hi_a]
            field = child.schema.field(col)
            if field.nullable:
                # NULL keys ride the in-band sentinel, which parquet
                # min/max stats exclude — NULL-group partials can split
                # across partitions, so the sentinel must always ship
                # through the exchange (never be early-filtered as final)
                sent = int(field.dtype.null_sentinel)
                intervals.append((sent, sent))
            # accepted: commit the contiguous regroup to the scan, and
            # carry the declared per-partition key ranges so the runtime
            # can detect stale stats (operators.HashAggregateExec)
            child.groups = groups
            agg_p.clustered = (pred, intervals, [tuple(r) for r in ranges])
            return True

        def walk(node):
            for c in node.children():
                walk(c)
            if isinstance(node, O.HashAggregateExec) \
                    and node.mode == "partial" \
                    and getattr(node, "clustered", None) is None:
                annotate(node, None)  # presorted-only; upgraded below
                return
            if not isinstance(node, O.FilterExec) or node.host_mode:
                return
            agg_f = node.input
            if not isinstance(agg_f, O.HashAggregateExec) \
                    or agg_f.mode != "final":
                return
            rep = agg_f.input
            if not isinstance(rep, Rep):
                return
            agg_p = rep.input
            if not isinstance(agg_p, O.HashAggregateExec) \
                    or agg_p.mode != "partial":
                return
            cl = getattr(agg_p, "clustered", None)
            if cl is not None and cl[0] is not None:
                return  # already carries an early-HAVING annotation
            # upgrade a presorted-only annotation to the early-HAVING form
            agg_p.clustered = None
            if not annotate(agg_p, node.predicate):
                agg_p.clustered = cl  # keep presorted-only if it existed

        walk(plan)

    def _mesh_worthwhile(self, est_rows: int) -> bool:
        """Adaptive per-exchange transport choice (the VERDICT r4 ask: pick
        mesh vs file from the scheduler's size knowledge, the same family
        of estimates ``maybe_coalesce`` exploits post-resolve).  0 disables
        the gate (always mesh) — tests and operators forcing the mesh path
        set ``ballista.shuffle.mesh.min_rows=0``."""
        floor = self.config.get(MESH_MIN_ROWS)
        return floor <= 0 or est_rows >= floor

    def _estimate_rows(self, node: L.LogicalPlan) -> int:
        if isinstance(node, L.TableScan):
            n = self.catalog.provider(node.table).row_count()
            est = n if n is not None else 10_000_000
            return max(1, est // (4 if node.filters else 1))
        if isinstance(node, L.Filter):
            if isinstance(node.input, L.Aggregate):
                # HAVING over an aggregate is selective by design (same 1%
                # convention as semi-join subqueries below; q18's HAVING
                # keeps 673 of 15M groups).  This is what lets the
                # orders x (HAVING subquery) join pick broadcast and skip
                # shuffling the big probe side.
                return max(1, self._estimate_rows(node.input) // 100)
            return max(1, self._estimate_rows(node.input) // 4)
        if isinstance(node, (L.Projection, L.SubqueryAlias, L.Sort)):
            return self._estimate_rows(node.input)
        if isinstance(node, L.Limit):
            return node.n
        if isinstance(node, L.Aggregate):
            return max(1, self._estimate_rows(node.input) // 8)
        if isinstance(node, L.Distinct):
            return self._estimate_rows(node.input)
        if isinstance(node, L.Join):
            if node.join_type == "semi":
                # a semi join keeps the left rows matching the (typically
                # selective) subquery — assume a strong cut so downstream
                # joins can pick broadcast (q18: 57 of 15M orders survive;
                # estimating 'left' kept the next join partitioned and
                # shuffled 60M lineitem rows at SF10).  The output is
                # bounded by the LEFT side only (many left rows can match
                # one right key), so the right estimate is not a valid
                # cap; 1% match selectivity is the working guess for
                # IN/EXISTS over filtered/aggregated subqueries (q18's
                # HAVING subquery keeps 673 of 15M orders — 1/22000; the
                # earlier 5% guess left the estimate above the broadcast
                # threshold and forced a 60M-row shuffle).  Worst case of
                # an under-estimate is a large broadcast build —
                # materialized once (build cache) and streamed against,
                # not fatal.
                return max(1, self._estimate_rows(node.left) // 100)
            if node.join_type == "anti":
                return self._estimate_rows(node.left)
            if node.join_type == "full":
                return self._estimate_rows(node.left) + self._estimate_rows(node.right)
            # inner/left equi-joins in analytic schemas are key-FK: the
            # output is bounded by the fact side.  Which side that is can't
            # be known statically, so trust the SMALL side's estimate only
            # when it is decisively small (a quarter of the broadcast
            # threshold — semi/aggregate-derived inputs land here) and fall
            # back to max() otherwise.  Plain min() made q3's
            # (customer x orders) subtree look like 375k rows when the join
            # truly produces 1.46M at SF10, flipping a rightly-partitioned
            # join to a 1.5M-row broadcast build (+22% wall); max() alone
            # made q18's (orders-semi x customer) look like 1.5M rows when
            # the truth is ~500, forcing a 60M-row lineitem shuffle.
            left_e = self._estimate_rows(node.left)
            right_e = self._estimate_rows(node.right)
            decisive = self.config.get(BROADCAST_THRESHOLD) // 4
            est = max(left_e, right_e)
            if min(left_e, right_e) <= decisive:
                est = min(left_e, right_e)
            if node.join_type == "left":
                # every left row is emitted at least once: the decisive-
                # small shortcut is only valid for inner joins
                est = max(est, left_e)
            return est
        if isinstance(node, L.CrossJoin):
            return self._estimate_rows(node.left) * self._estimate_rows(node.right)
        return 10_000_000
