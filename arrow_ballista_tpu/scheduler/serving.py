"""Serving front half of a SQL submission, shared by the standalone
cluster (`StandaloneCluster.execute_sql`) and the network service
(`SchedulerNetService._execute_query`) so their cache behaviour cannot
drift.

``prepare_sql_submission`` consults the scheduler's serving caches
(scheduler/serving_cache.py) and returns one of two outcomes:

- a **cached result payload** — the query's bytes are already in the
  result cache for the current table versions and session config; nothing
  is submitted, planned, or executed;
- a **plan closure + ServingJobInfo** for ``SchedulerServer.submit_job``.
  On a plan-template hit the closure merely clones the validated template
  (parse/plan/validate/scalar-subqueries all skipped); on a miss it runs
  the full pipeline and arms template/result capture for next time.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from .serving_cache import (
    PlanTemplate,
    RecordingCatalog,
    ServingJobInfo,
    clone_plan,
    config_fingerprint,
    normalize_sql,
    plan_cache_enabled,
    result_cache_enabled,
    result_cache_key,
    subplan_cache_enabled,
    table_versions_fp,
)


def prepare_sql_submission(server, sql_text: str, catalog, config,
                           job_id: str, subplan_ok: bool = False,
                           work_dir: Optional[str] = None,
                           statement=None,
                           schema_cb: Optional[Callable] = None
                           ) -> Tuple[Optional[dict], Optional[Callable],
                                      ServingJobInfo]:
    """Returns ``(cached_payload, plan_fn, serving)``; exactly one of
    ``cached_payload`` / ``plan_fn`` is non-None.

    ``subplan_ok`` gates shuffle-stage preload/capture: spooled stage
    files are read via filesystem paths (port-0 locations), which only
    works when executors share the scheduler's filesystem — true
    in-process (standalone), not guaranteed for networked executors.
    ``statement`` optionally carries an already-parsed AST (the client's
    per-session parse memo); ``schema_cb`` is invoked with the final
    Schema as soon as it is known (template hit: inside the returned
    closure before any task runs)."""
    plan_on = plan_cache_enabled(config)
    result_on = result_cache_enabled(config)
    track = plan_on or result_on
    norm_text, params = normalize_sql(sql_text) if track else (sql_text, ())
    config_fp = config_fingerprint(config) if track else ""
    serving = ServingJobInfo(
        config_fp=config_fp,
        subplan=subplan_ok and subplan_cache_enabled(config),
        capture_result=result_on)

    template = server.plan_cache.lookup(norm_text, params, config_fp,
                                        catalog) if plan_on else None
    if template is None and result_on and not plan_on:
        # no template to learn the referenced tables from: fall back to the
        # result cache's capture-time hint so the result cache works with
        # the plan cache disabled
        tables = server.result_cache.tables_for((norm_text, params,
                                                 config_fp))
        if tables:
            table_fp = table_versions_fp(catalog, tables)
            payload = server.result_cache.get(
                result_cache_key(norm_text, params, config_fp, table_fp))
            if payload is not None:
                return payload, None, serving

    if template is not None:
        serving.table_fp = template.table_fp
        serving.prevalidated = True
        serving.schema = template.schema
        serving.tables = template.tables
        if result_on:
            rkey = result_cache_key(norm_text, params, config_fp,
                                    template.table_fp)
            payload = server.result_cache.get(rkey)
            if payload is not None:
                return payload, None, serving
            serving.result_key = rkey

        def plan_fn():
            if schema_cb is not None:
                schema_cb(template.schema)
            # fresh clone per run: stage splitting / shuffle resolution /
            # AQE mutate the plan in place, and AQE re-optimizes THIS run
            # from its own shuffle stats (the template is pre-AQE)
            return template.bind(), dict(template.scalars)

        return None, plan_fn, serving

    def plan_fn():
        from ..client.context import extract_scalar
        from ..ops.physical import TaskContext
        from ..sql.optimizer import optimize
        from ..sql.parser import parse_sql
        from ..sql.planner import SqlToRel
        from .physical_planner import PhysicalPlanner

        rec = RecordingCatalog(catalog)
        stmt = statement if statement is not None else parse_sql(sql_text)
        logical = optimize(SqlToRel(rec).plan(stmt))
        planned = PhysicalPlanner(rec, config).plan_query(logical)
        ctx = TaskContext(config=config, job_id=f"{job_id}-scalars",
                          **({"work_dir": work_dir} if work_dir else {}))
        scalars = {}
        for sid, splan in planned.scalars:
            ctx.scalars = scalars
            scalars[sid] = extract_scalar(splan, ctx)
        serving.schema = planned.plan.schema
        if schema_cb is not None:
            schema_cb(planned.plan.schema)
        if track:
            tables = tuple(sorted(rec.used))
            serving.tables = tables
            table_fp = table_versions_fp(catalog, tables)
            serving.table_fp = table_fp
            if result_on:
                serving.result_key = result_cache_key(
                    norm_text, params, config_fp, table_fp)
            if plan_on:
                # pristine clone BEFORE the graph build mutates the plan;
                # stored by the scheduler only after validation passes
                serving.pending_template = PlanTemplate(
                    norm_text, params, config_fp,
                    master_plan=clone_plan(planned.plan),
                    scalars=dict(scalars), schema=planned.plan.schema,
                    tables=tables, table_fp=table_fp)
        return planned.plan, scalars

    return None, plan_fn, serving
