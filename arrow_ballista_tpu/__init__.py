"""arrow_ballista_tpu: a TPU-native distributed SQL query engine.

Ground-up rebuild of the capabilities of arrow-ballista (distributed SQL on
Arrow/DataFusion, reference at /root/reference) re-designed for TPU:

- columnar data lives as fixed-capacity JAX device arrays (HBM-resident),
- physical operators are XLA/Pallas programs with static shapes,
- shuffles run over the ICI mesh via all_to_all when co-located, with an
  Arrow-IPC file/stream fallback across hosts,
- the control plane (scheduler, execution graph, fault tolerance) keeps the
  reference's architecture: stage DAGs split at exchange boundaries, event-
  driven scheduling, shuffle-lineage retry.
"""
from __future__ import annotations

import os as _os
import sys as _sys

# pyarrow's bundled mimalloc pool was observed corrupting memory when it
# shares a process with XLA's runtime (scheduler daemon SIGSEGV inside
# ipc write_table, ~60% of runs; 10/10 clean with the system allocator).
# Force the system pool before pyarrow first allocates.
_os.environ.setdefault("ARROW_DEFAULT_MEMORY_POOL", "system")
if "pyarrow" in _sys.modules:  # imported before us: switch the pool live
    try:
        _sys.modules["pyarrow"].set_memory_pool(
            _sys.modules["pyarrow"].system_memory_pool())
    except Exception:  # noqa: BLE001 — allocator choice is a mitigation
        pass

import jax as _jax

# int64 is load-bearing: decimals are fixed-point int64 (exact money math on
# TPU, which has no native f64).  Without x64, JAX silently truncates to int32.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: every ctx.sql() builds fresh operator
# instances, so in-memory jit caches never hit across queries — but the HLO
# is identical, and TPU sort programs take 30-110s to compile (measured on
# v5e).  The disk cache turns repeat compiles into millisecond loads, across
# queries AND processes.  Opt out with BALLISTA_XLA_CACHE=0 or point it
# elsewhere with BALLISTA_XLA_CACHE=<dir>.
_cache = _os.environ.get("BALLISTA_XLA_CACHE", "")
if _cache != "0":
    # every persistent-cache AOT load emits a ~3KB benign ERROR pair on
    # XLA's C++ stderr channel (the prefer-no-scatter/gather tuning
    # pseudo-features never appear in the host probe, so same-machine
    # entries still "mismatch").  Engine errors surface as Python
    # exceptions; silence the C++ diagnostics unless the user overrides.
    _os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    # CPU processes use the cache too (round 5): the host-CPU fingerprint
    # in the cache path (below) keys entries per machine GENERATION, which
    # removes the cross-migration hazards that once argued for skipping it
    # (machine-feature-stamped AOT entries: ~3KB LOG(ERROR) per mismatched
    # load — enough to fill a captured stdout pipe and freeze a daemon —
    # and SIGILL risk).  And "CPU compiles are cheap" stopped being true:
    # the migrating VM measured ~35s of first-run compiles for TPC-H q3.
    # Disable with BALLISTA_XLA_CACHE=0, relocate with =<dir>.
    if not _cache:
        # per-platform dirs: entries carry machine-specific AOT artifacts
        # (a TPU-tunnel process compiles host programs on the REMOTE
        # machine; loading those on this host warns about mismatched CPU
        # features and risks SIGILL), so cpu-forced and tpu processes must
        # never share a cache
        _plat = (_os.environ.get("JAX_PLATFORMS", "").split(",")[0]
                 or "default")
        # fingerprint the host CPU into the cache path: AOT entries encode
        # machine features, and this host can change generations across
        # runs (observed: entries compiled with amx-complex loaded on a
        # host without it — "could lead to execution errors such as
        # SIGILL", and one executor daemon did abort)
        try:
            import hashlib as _hl

            with open("/proc/cpuinfo") as _f:
                for _line in _f:
                    if _line.startswith("flags"):
                        _plat += "-" + _hl.sha256(
                            _line.encode()).hexdigest()[:8]
                        break
        except OSError:
            pass
        _cache = _os.path.join(
            _os.environ.get("XDG_CACHE_HOME",
                            _os.path.expanduser("~/.cache")),
            "ballista_tpu_xla", _plat)
    try:
        _os.makedirs(_cache, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass

__version__ = "0.1.0"

from .models.schema import (  # noqa: E402,F401
    BOOL,
    DATE32,
    FLOAT32,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    DataType,
    Field,
    Schema,
    decimal,
)
from .models.batch import ColumnBatch, concat_batches  # noqa: E402,F401
from .utils.config import BallistaConfig  # noqa: E402,F401


def __getattr__(name):
    # Lazy: avoid importing the whole engine for schema-only users.
    if name == "BallistaContext":
        try:
            from .client.context import BallistaContext
        except ModuleNotFoundError as e:
            raise AttributeError(f"BallistaContext unavailable: {e}") from e
        return BallistaContext
    raise AttributeError(name)
