"""arrow_ballista_tpu: a TPU-native distributed SQL query engine.

Ground-up rebuild of the capabilities of arrow-ballista (distributed SQL on
Arrow/DataFusion, reference at /root/reference) re-designed for TPU:

- columnar data lives as fixed-capacity JAX device arrays (HBM-resident),
- physical operators are XLA/Pallas programs with static shapes,
- shuffles run over the ICI mesh via all_to_all when co-located, with an
  Arrow-IPC file/stream fallback across hosts,
- the control plane (scheduler, execution graph, fault tolerance) keeps the
  reference's architecture: stage DAGs split at exchange boundaries, event-
  driven scheduling, shuffle-lineage retry.
"""
from __future__ import annotations

import jax as _jax

# int64 is load-bearing: decimals are fixed-point int64 (exact money math on
# TPU, which has no native f64).  Without x64, JAX silently truncates to int32.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from .models.schema import (  # noqa: E402,F401
    BOOL,
    DATE32,
    FLOAT32,
    FLOAT64,
    INT32,
    INT64,
    STRING,
    DataType,
    Field,
    Schema,
    decimal,
)
from .models.batch import ColumnBatch, concat_batches  # noqa: E402,F401
from .utils.config import BallistaConfig  # noqa: E402,F401


def __getattr__(name):
    # Lazy: avoid importing the whole engine for schema-only users.
    if name == "BallistaContext":
        try:
            from .client.context import BallistaContext
        except ModuleNotFoundError as e:
            raise AttributeError(f"BallistaContext unavailable: {e}") from e
        return BallistaContext
    raise AttributeError(name)
