"""Memory-pressure robustness plane (PR 19).

- :mod:`.governor` — per-executor reserve/grant/release accounting over
  host-RSS and device-HBM pools; denials are retryable back-pressure.
- :mod:`.spill` — Arrow IPC spill runs with CRC-verified read-back,
  written when a reservation is denied and merged on read.
"""
from .governor import POOLS, STATS, MemoryGovernor, Reservation
from .spill import Spiller, SpillRun

__all__ = [
    "MemoryGovernor",
    "Reservation",
    "Spiller",
    "SpillRun",
    "STATS",
    "POOLS",
]
