"""Per-executor memory governor: reserve -> grant -> release accounting.

The data plane's two unbounded-state consumers — hash-join build sides
and grouped-aggregation state — ask the governor for a reservation
*before* materializing.  A grant means "proceed in memory"; a denial
means "degrade to spill" (memory/spill.py), never "crash the executor".
Two pools:

- ``host``   — RSS budget (``ballista.memory.host.budget.bytes``).
  Pure reservation accounting: the governor is the only admission gate,
  so reserved bytes are the authoritative model of operator-held state.
- ``device`` — HBM budget (``ballista.memory.device.budget.bytes``),
  fed by the PR-12 watermark sampler: availability subtracts the *live*
  device-buffer bytes the observatory measures, so reservations compose
  with allocations the governor never saw (compiled program temps,
  cached build sides).

The reserve path is a failpoint (``executor.memory.reserve``): chaos
runs deny or delay grants here to force the spill path and prove it
bit-identical.  A denial raises :class:`~..utils.errors.MemoryExhausted`
— retryable back-pressure by taxonomy, and explicitly exempted from
quarantine strikes (scheduler/scheduler.py): an executor protecting
itself must not be blamed into quarantine for it.

Process-global :data:`STATS` mirrors the data-plane/device observatories
(models/ipc.py STATS, obs/device.py STATS): executor metrics gather the
``memory_reserved_bytes`` gauge and ``memory_spill_bytes_total`` counter
from here.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from .. import faults
from ..utils.config import (
    MEM_DEVICE_BUDGET,
    MEM_HOST_BUDGET,
    MEM_SPILL_ENABLED,
    resolve_pool_budget,
)
from ..utils.errors import MemoryExhausted

#: reservation pools; ``host`` covers operator state materialized via
#: host-visible buffers, ``device`` covers HBM-resident state.
POOLS = ("host", "device")


class _MemoryStats:
    """Process-global memory-plane totals (one per executor process;
    standalone in-proc executors share it, same as the data-plane
    STATS)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, float] = {}
        self._reserved: Dict[str, int] = {p: 0 for p in POOLS}

    def add(self, key: str, v: float = 1) -> None:
        with self._lock:
            self._c[key] = self._c.get(key, 0) + v

    def reserve_delta(self, pool: str, delta: int) -> None:
        with self._lock:
            self._reserved[pool] = self._reserved.get(pool, 0) + delta

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._c)
            for p, v in self._reserved.items():
                out[f"reserved_bytes.{p}"] = v
            return out

    def reset(self) -> None:
        with self._lock:
            self._c.clear()
            self._reserved = {p: 0 for p in POOLS}


STATS = _MemoryStats()


def _device_live_bytes() -> int:
    """Live HBM bytes per the PR-12 watermark sampler (0 when the
    observatory is off — the device pool then degrades to pure
    reservation accounting, same model as the host pool)."""
    try:
        from ..obs import device as device_obs

        sample = device_obs.sample_watermarks()
        if sample is not None:
            return int(sample[0])
    except Exception:
        pass
    return 0


class Reservation:
    """A granted byte reservation; context-managed or released
    explicitly.  ``release()`` is idempotent (operators release eagerly
    on the happy path and rely on ``with`` for unwind)."""

    __slots__ = ("pool", "nbytes", "_gov")

    def __init__(self, gov: "MemoryGovernor", pool: str, nbytes: int):
        self._gov = gov
        self.pool = pool
        self.nbytes = int(nbytes)

    def release(self) -> None:
        gov, self._gov = self._gov, None
        if gov is not None:
            gov._release(self.pool, self.nbytes)

    def __enter__(self) -> "Reservation":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self):
        state = "released" if self._gov is None else "held"
        return f"Reservation({self.pool}, {self.nbytes} bytes, {state})"


class MemoryGovernor:
    """Reserve/grant/release accounting over the host and device pools.

    Budget 0 means *unlimited* (the default): every reservation is
    granted and only the accounting runs — so the pressure signal and
    metrics work even on unconstrained executors.  Thread-safe: task
    pool threads reserve concurrently.
    """

    def __init__(self, host_budget: int = 0, device_budget: int = 0,
                 spill_enabled: bool = True):
        self._lock = threading.Lock()
        self._budget = {"host": int(host_budget),
                        "device": int(device_budget)}
        self._reserved = {p: 0 for p in POOLS}
        self.spill_enabled = bool(spill_enabled)

    @staticmethod
    def from_config(cfg) -> "MemoryGovernor":
        return MemoryGovernor(
            host_budget=resolve_pool_budget(cfg, MEM_HOST_BUDGET),
            device_budget=resolve_pool_budget(cfg, MEM_DEVICE_BUDGET),
            spill_enabled=cfg.get(MEM_SPILL_ENABLED))

    # --- introspection --------------------------------------------------
    def budget(self, pool: str = "host") -> int:
        return self._budget[pool]

    def reserved(self, pool: str = "host") -> int:
        with self._lock:
            return self._reserved[pool]

    def available(self, pool: str = "host") -> Optional[int]:
        """Grantable bytes, or None when the pool is unlimited."""
        budget = self._budget[pool]
        if budget <= 0:
            return None
        extern = _device_live_bytes() if pool == "device" else 0
        with self._lock:
            return budget - self._reserved[pool] - extern

    def pressure(self) -> float:
        """Fraction of the most-loaded budgeted pool in use (0.0 when
        every pool is unlimited).  Rides executor heartbeats into the
        scheduler's offer ordering and admission shed decisions."""
        worst = 0.0
        for pool, budget in self._budget.items():
            if budget <= 0:
                continue
            extern = _device_live_bytes() if pool == "device" else 0
            with self._lock:
                used = self._reserved[pool] + extern
            worst = max(worst, used / budget)
        return worst

    # --- reserve / release ----------------------------------------------
    def reserve(self, nbytes: int, pool: str = "host", *,
                site: str = "") -> Reservation:
        """Grant ``nbytes`` from ``pool`` or raise
        :class:`MemoryExhausted`.  The failpoint fires first so chaos
        plans can deny (``error=resource``) or delay any grant."""
        nbytes = int(nbytes)
        faults.inject("executor.memory.reserve", pool=pool, nbytes=nbytes,
                      op=site)
        budget = self._budget[pool]
        extern = _device_live_bytes() if pool == "device" else 0
        with self._lock:
            if budget > 0:
                avail = budget - self._reserved[pool] - extern
                if nbytes > avail:
                    raise MemoryExhausted(pool, nbytes, max(0, avail), site)
            self._reserved[pool] += nbytes
        STATS.reserve_delta(pool, nbytes)
        return Reservation(self, pool, nbytes)

    def try_reserve(self, nbytes: int, pool: str = "host", *,
                    site: str = "") -> Optional[Reservation]:
        """Grant-or-None: the operator protocol.  None tells the caller
        to take its spill path (or, with spill disabled, to re-raise the
        denial so the scheduler retries the task elsewhere)."""
        try:
            return self.reserve(nbytes, pool, site=site)
        except MemoryExhausted:
            STATS.add("reserve_denied_total")
            if not self.spill_enabled:
                raise
            return None

    def force_reserve(self, nbytes: int, pool: str = "host", *,
                      site: str = "") -> Reservation:
        """Over-budget grant for operators with a hard single-pass
        requirement (left/full outer joins must see the whole build
        side).  Never denies; the overshoot is visible in the pressure
        signal and the ``over_budget_grants_total`` counter so the
        doctor can point at the query shape."""
        nbytes = int(nbytes)
        avail = self.available(pool)
        if avail is not None and nbytes > avail:
            STATS.add("over_budget_grants_total")
        with self._lock:
            self._reserved[pool] += nbytes
        STATS.reserve_delta(pool, nbytes)
        return Reservation(self, pool, nbytes)

    def _release(self, pool: str, nbytes: int) -> None:
        with self._lock:
            self._reserved[pool] -= nbytes
        STATS.reserve_delta(pool, -nbytes)
