"""Spill-to-disk runs: Arrow IPC files written under the task work dir.

When the governor denies a reservation, an operator writes its partial
state as IPC *runs* (models/ipc.py — the same writer/reader the shuffle
data plane uses, so dictionary pruning, int64 narrowing, and the
unified-sorted-dictionary read path are all shared) and merges them on
read.  Every run records the CRC-32 of the bytes it put on disk; the
read path re-hashes before decode, so silent disk corruption surfaces
as a *retryable* :class:`~..utils.errors.IntegrityError` — the task
retry re-reads its shuffle inputs and recomputes, which is lineage
recovery, not data corruption.

The write is a failpoint (``executor.spill.write``): ``raise`` turns a
spill into an I/O failure, ``corrupt`` flips bytes on disk *after* the
CRC is recorded so the read-back check must catch it.
"""
from __future__ import annotations

import os
import shutil
import threading
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import faults
from ..models.ipc import crc32_file, read_ipc_files, write_ipc_rows
from ..models.schema import Schema
from ..utils.errors import IntegrityError
from .governor import STATS


class SpillRun:
    """One spilled IPC file + the checksum its reader must see."""

    __slots__ = ("path", "crc", "num_rows", "num_bytes")

    def __init__(self, path: str, crc: int, num_rows: int, num_bytes: int):
        self.path = path
        self.crc = crc
        self.num_rows = num_rows
        self.num_bytes = num_bytes

    def __repr__(self):
        return (f"SpillRun({os.path.basename(self.path)}, "
                f"rows={self.num_rows}, bytes={self.num_bytes})")


class Spiller:
    """Writes/reads spill runs for one operator execution.

    Files live under ``<work_dir>/<job_id>/spill/<unique>/`` so
    concurrent tasks of the same job never collide and ``cleanup()``
    can remove the whole directory."""

    def __init__(self, work_dir: str, job_id: str = "", tag: str = "op"):
        self.dir = os.path.join(work_dir, job_id or "_adhoc", "spill",
                                f"{tag}-{uuid.uuid4().hex[:12]}")
        self._seq = 0
        self._lock = threading.Lock()
        self.runs: List[SpillRun] = []

    # --- write ----------------------------------------------------------
    def write_run(self, schema: Schema, data: Dict[str, np.ndarray],
                  dicts: Dict[str, np.ndarray]) -> SpillRun:
        """Spill already-compacted host rows as one IPC run."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, f"run-{seq}.arrow")
        rule = faults.inject("executor.spill.write", path=path)
        num_rows, num_bytes = write_ipc_rows(schema, data, dicts, path)
        crc = crc32_file(path)
        if rule is not None and rule.action == "corrupt":
            # after the CRC: the reader's integrity check must catch it
            with open(path, "rb") as fh:
                raw = fh.read()
            with open(path, "wb") as fh:
                fh.write(faults.corrupt_bytes(raw))
        run = SpillRun(path, crc, num_rows, num_bytes)
        with self._lock:
            self.runs.append(run)
        STATS.add("spill_runs_total")
        STATS.add("spill_bytes_total", num_bytes)
        return run

    def write_batch(self, batch) -> SpillRun:
        """Spill a device batch's live rows (one packed device->host
        transfer via ``compacted_numpy``)."""
        return self.write_run(batch.schema, batch.compacted_numpy(),
                              batch.dicts)

    # --- read -----------------------------------------------------------
    def read(self, schema: Schema, runs: Optional[Sequence[SpillRun]] = None,
             capacity: Optional[int] = None) -> List:
        """Read runs back into device batches (unified sorted
        dictionaries across runs, exactly the shuffle read path).
        Verifies every run's CRC first; a mismatch is retryable — the
        retry recomputes from shuffle inputs (lineage), so corruption
        on disk never becomes corruption in results."""
        runs = self.runs if runs is None else list(runs)
        for run in runs:
            actual = crc32_file(run.path)
            if actual != run.crc:
                raise IntegrityError(
                    "executor.spill.read",
                    "spill run failed CRC verification on read-back",
                    path=run.path, expected=run.crc, actual=actual)
        return read_ipc_files([r.path for r in runs], schema,
                              capacity=capacity)

    def cleanup(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)
        with self._lock:
            self.runs = []
