"""Executor daemon: ``python -m arrow_ballista_tpu.executor_daemon``.

Parity: the ballista-executor binary (reference ballista/executor/src/
bin/main.rs + executor_process.rs — work_dir setup, scheduler connect with
retry, graceful SIGTERM shutdown draining in-flight tasks).
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="arrow_ballista_tpu executor")
    ap.add_argument("--scheduler-host", default="127.0.0.1")
    ap.add_argument("--scheduler-port", type=int, default=50050)
    ap.add_argument("--bind-host", default="127.0.0.1")
    ap.add_argument("--bind-port", type=int, default=0)
    ap.add_argument("--external-host",
                    default=os.environ.get("BALLISTA_EXTERNAL_HOST") or None,
                    help="address advertised to peers for shuffle fetch "
                         "(env BALLISTA_EXTERNAL_HOST; defaults to bind "
                         "host, or hostname when 0.0.0.0)")
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--concurrent-tasks", type=int, default=4)
    ap.add_argument("--connect-timeout-s", type=float, default=30.0)
    ap.add_argument("--scheduling-policy", choices=["push", "pull"],
                    default="push")
    ap.add_argument("--flight-port", type=int, default=-1,
                    help="standard Arrow Flight data plane port "
                         "(0 = ephemeral, -1 = disabled)")
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="observability HTTP port serving prometheus "
                         "/metrics and /health (0 = ephemeral, "
                         "-1 = disabled)")
    ap.add_argument("--log-level", default="INFO")
    ap.add_argument("--log-dir", default=None,
                    help="write rotating log files here instead of stderr")
    ap.add_argument("--log-file-name-prefix", default="executor")
    ap.add_argument("--log-rotation-policy", default="daily",
                    choices=["minutely", "hourly", "daily", "never"])
    ap.add_argument("--log-format", default=None, choices=["text", "json"],
                    help="log output format (default: BALLISTA_LOG_FORMAT "
                         "env or text; json = one object per line with "
                         "job/trace correlation fields)")
    args = ap.parse_args(argv)

    # XLA's C++ stderr (absl) logs bypass python logging; persistent-cache
    # AOT loads emit a ~3KB benign feature-mismatch ERROR per program
    # (prefer-no-* tuning pseudo-features never match the host probe) —
    # enough to wedge a daemon whose stderr pipe nobody drains.  Daemons
    # report operational errors through python logging, so silence the
    # C++ channel unless the operator overrides.
    import os as _os

    _os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

    from .utils.logsetup import init_logging

    init_logging(args.log_level, args.log_dir, args.log_file_name_prefix,
                 args.log_rotation_policy, fmt=args.log_format)
    # native-crash forensics: a SIGSEGV in a daemon otherwise dies silently
    import faulthandler

    faulthandler.enable()

    from .executor.server import ExecutorServer
    from .net import wire

    # connect-with-retry (reference executor_process.rs:194-232)
    deadline = time.monotonic() + args.connect_timeout_s
    while True:
        try:
            wire.call(args.scheduler_host, args.scheduler_port, "ping", timeout=3.0)
            break
        except Exception as e:  # noqa: BLE001
            if time.monotonic() > deadline:
                raise SystemExit(f"cannot reach scheduler: {e}")
            time.sleep(0.5)

    server = ExecutorServer(
        args.scheduler_host, args.scheduler_port, args.bind_host,
        args.bind_port, args.work_dir, args.concurrent_tasks,
        external_host=args.external_host, policy=args.scheduling_policy,
        flight_port=args.flight_port, metrics_port=args.metrics_port)
    server.start()
    logging.info("executor %s on %s:%s (work_dir %s)",
                 server.metadata.executor_id, server.rpc.host, server.rpc.port,
                 server.work_dir)

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.5)
    logging.info("executor draining %d tasks", server.executor.active_tasks())
    server.drain_and_stop()


if __name__ == "__main__":
    main()
