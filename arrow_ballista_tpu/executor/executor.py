"""Executor: runs query-stage tasks and reports status.

Parity: reference ballista/executor/src/executor.rs:56-166 (task execution
with cancellation + metrics) and lib.rs:36-102 (result -> TaskStatus
mapping with the failure taxonomy).  The reference's DedicatedExecutor
(separate runtime for CPU-bound work) maps to a ThreadPoolExecutor here:
XLA dispatch releases the GIL, so pool threads genuinely overlap host IO
with device compute.
"""
from __future__ import annotations

import logging
import threading
import time
import traceback
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Set

from .. import faults
from ..memory import MemoryGovernor
from ..ops.physical import TaskContext
from ..utils.config import BallistaConfig
from ..utils.errors import (CancelledError, ExecutorKilled, FetchFailedError,
                            IntegrityError, IOError_, MemoryExhausted,
                            ResourceExhausted)
from ..scheduler.types import (
    EXECUTION_ERROR,
    FETCH_PARTITION_ERROR,
    IO_ERROR,
    RESOURCE_EXHAUSTED,
    TASK_KILLED,
    ExecutorMetadata,
    FailedReason,
    TaskDescription,
    TaskStatus,
)
from .execution_engine import DefaultExecutionEngine, ExecutionEngine

log = logging.getLogger(__name__)

# one id per executor PROCESS: in-proc standalone executors share plan
# instances (and so cumulative MetricsSets) — stage metric aggregation keys
# snapshots by this, not by executor_id
PROCESS_ID = __import__("uuid").uuid4().hex[:12]


def remove_job_data(work_dir: str, job_id: str) -> None:
    """Delete ``<work_dir>/<job_id>`` (path-traversal guarded) and drop the
    job's cached broadcast build tables.  Shared by the executor server's
    remove_job_data RPC, its TTL janitor, and the standalone launcher's
    scheduler-driven cleanup (reference executor_server.rs remove_job_data
    with is_subdirectory guard)."""
    import os
    import shutil

    from ..ops.operators import clear_job_build_caches

    root = os.path.realpath(work_dir)
    job_dir = os.path.realpath(os.path.join(work_dir, job_id))
    if job_dir != root and os.path.commonpath([job_dir, root]) == root \
            and os.path.isdir(job_dir):
        shutil.rmtree(job_dir, ignore_errors=True)
    clear_job_build_caches(job_id)


class Executor:
    def __init__(self, metadata: ExecutorMetadata, work_dir: str,
                 config: Optional[BallistaConfig] = None,
                 engine: Optional[ExecutionEngine] = None,
                 concurrent_tasks: int = 4):
        self.metadata = metadata
        self.work_dir = work_dir
        self.config = config or BallistaConfig()
        self.engine = engine or DefaultExecutionEngine()
        self.pool = ThreadPoolExecutor(max_workers=concurrent_tasks,
                                       thread_name_prefix=f"task-{metadata.executor_id}")
        # job-level cancel flags (reference abort_handles, executor.rs:93-111;
        # python threads can't be killed, so in-flight operators run to
        # completion and the *result* is dropped as 'killed').  Bounded so a
        # long-lived executor doesn't accumulate ids forever.
        self._cancelled_jobs: "OrderedDict[str, None]" = OrderedDict()
        # single-attempt cancel flags, keyed (job, stage, partition, attempt):
        # the scheduler reaps the losing duplicate of a speculative race
        # without touching the job's other tasks
        self._cancelled_tasks: "OrderedDict[tuple, None]" = OrderedDict()
        self._max_cancelled = 1024
        self._lock = threading.Lock()
        self._active = 0
        # in-flight registry: (job, stage, partition, attempt) -> the
        # attempt's cooperative CancelToken.  Feeds the heartbeat's
        # running-task set (zombie reconciliation) and lets cancel fanout
        # flip tokens so a cancel lands at the next batch boundary even in
        # contexts without a wired probe
        self._inflight: Dict[tuple, object] = {}
        # prometheus-style process counters (served by ExecutorServer's
        # /metrics listener; always collected — they are a few ints)
        from .metrics import ExecutorMetrics

        self.metrics = ExecutorMetrics()
        # memory governor: operators holding unbounded state (join builds,
        # agg state) reserve through this before materializing and spill
        # on denial; its pressure() rides heartbeats into the scheduler
        self.governor = MemoryGovernor.from_config(self.config)
        from ..utils.config import (OBS_DEVICE_ENABLED, OBS_DEVICE_WATERMARKS,
                                    OBS_TRACING)

        self._tracing = bool(self.config.get(OBS_TRACING))
        # device observatory switches are process-global (the jit wrappers
        # and transfer sites it instruments are process-wide); every
        # executor in the process shares one config in practice
        from ..obs import device as device_obs

        device_obs.set_enabled(bool(self.config.get(OBS_DEVICE_ENABLED)))
        device_obs.set_watermarks(bool(self.config.get(OBS_DEVICE_WATERMARKS)))
        # flight recorder: enable-only (never force-off — in-proc standalone
        # executors share the scheduler's process-global journal, and a
        # default-config executor must not stomp a test's explicit enable)
        from ..obs import journal
        from ..utils.config import (JOURNAL_CAPACITY, JOURNAL_ENABLED,
                                    JOURNAL_SPILL_PATH, env_flag)

        if env_flag("BALLISTA_JOURNAL") \
                or bool(self.config.get(JOURNAL_ENABLED)):
            journal.set_enabled(True)
            journal.configure(
                capacity=int(self.config.get(JOURNAL_CAPACITY)),
                spill_path=str(self.config.get(JOURNAL_SPILL_PATH)))
        if journal.enabled() and not journal.actor():
            journal.set_actor(metadata.executor_id)

    # --- task execution --------------------------------------------------
    def run_task(self, task: TaskDescription) -> TaskStatus:
        """Execute one task synchronously (callers use ``submit_task`` for
        pool execution).

        This wrapper owns observability — the task span tree (parented on
        the job's execution span via ``task.trace``) and the process
        counters; ``_run_task_inner`` owns execution and the failure
        taxonomy.  Spans attach to every outcome, so failed tasks profile
        too."""
        tid = task.task
        launch_ms = int(time.time() * 1000)
        recorder = None
        if self._tracing:
            from ..obs.tracing import TaskSpanRecorder

            trace = task.trace or {}
            recorder = TaskSpanRecorder(
                trace.get("trace_id"), trace.get("span_id", ""),
                name=f"task {tid.job_id}/{tid.stage_id}/{tid.partition}",
                kind="executor",
                attrs={"job_id": tid.job_id, "stage_id": tid.stage_id,
                       "partition": tid.partition,
                       "task_attempt": tid.task_attempt,
                       "executor_id": self.metadata.executor_id,
                       "actor": f"executor {self.metadata.executor_id}",
                       "lane": f"stage {tid.stage_id} / p{tid.partition}"})
        t0 = time.perf_counter()
        from ..obs import device as device_obs
        from ..obs import journal
        from ..utils.logsetup import log_scope

        _trace = task.trace or {}
        with log_scope(job_id=tid.job_id,
                       trace_id=str(_trace.get("trace_id") or ""),
                       span_id=str(_trace.get("span_id") or "")), \
                device_obs.task_scope() as dev_acc, \
                journal.task_scope() as jbuf:
            if jbuf is not None:
                journal.emit("task.run", job_id=tid.job_id,
                             stage_id=tid.stage_id, partition=tid.partition,
                             attempt=tid.task_attempt,
                             executor_id=self.metadata.executor_id,
                             speculative=tid.speculative)
            status = self._run_task_inner(task, launch_ms, recorder)
            if (status.state == "killed"
                    and tid.job_id in self._cancelled_jobs):
                # a task that slipped past its cancel checkpoints (e.g. a
                # single-batch partition) can write shuffle files AFTER
                # the scheduler's cleanup fanout already ran — the last
                # dying task of a cancelled job sweeps the job's data so
                # the workspace never leaks what nothing registered
                remove_job_data(self.work_dir, tid.job_id)
        if dev_acc is not None:
            status.device_stats = dev_acc.snapshot()
        if jbuf:
            # ship the task's flight-record buffer piggyback on the status
            # (merged into the job timeline scheduler-side); empty buffer =
            # no wire key, same contract as device_stats
            status.journal = jbuf
        if recorder is not None:
            if status.shuffle_writes:
                recorder.annotate(
                    rows_written=int(sum(w.num_rows
                                         for w in status.shuffle_writes)),
                    bytes_shuffled=int(sum(w.num_bytes
                                           for w in status.shuffle_writes)),
                    output_partitions=len(status.shuffle_writes))
            status.spans = recorder.finish(
                "ok" if status.state == "success" else status.state)
        self.metrics.record_task(status, time.perf_counter() - t0)
        return status

    def _run_task_inner(self, task: TaskDescription, launch_ms: int,
                        recorder) -> TaskStatus:
        from ..ops.physical import CancelToken, install_cancel_token

        tid = task.task
        key = (tid.job_id, tid.stage_id, tid.partition, tid.task_attempt)
        token = CancelToken()
        with self._lock:
            self._active += 1
            self._inflight[key] = token
        # thread-local install: TaskContext.check_cancelled (and the free
        # checkpoint()) consult the token between batch iterations and
        # fused-kernel invocations, so cancel/deadline lands in seconds
        install_cancel_token(token)
        if self._is_cancelled(tid):
            token.cancel()  # cancel arrived before launch
        try:
            if self._is_cancelled(tid):
                return TaskStatus(tid, self.metadata.executor_id, "killed")
            faults.inject("executor.task.before_run",
                          executor_id=self.metadata.executor_id,
                          job_id=tid.job_id, stage_id=tid.stage_id,
                          partition=tid.partition,
                          task_attempt=tid.task_attempt)
            stage_exec = self.engine.create_query_stage_exec(
                tid.job_id, tid.stage_id, task.plan, self.work_dir)
            ctx = TaskContext(config=self.config, scalars=dict(task.scalars),
                              work_dir=self.work_dir, job_id=tid.job_id,
                              stage_id=tid.stage_id,
                              executor_id=self.metadata.executor_id,
                              executor_host=self.metadata.host,
                              cancelled=lambda: self._is_cancelled(tid),
                              span_recorder=recorder,
                              governor=self.governor)
            start_ms = int(time.time() * 1000)
            # deterministic straggler: a 'delay' rule here stalls the task
            # mid-run, which is what the speculation monitor watches for
            faults.inject("executor.task.slow",
                          executor_id=self.metadata.executor_id,
                          job_id=tid.job_id, stage_id=tid.stage_id,
                          partition=tid.partition,
                          task_attempt=tid.task_attempt,
                          speculative=tid.speculative)
            writes = stage_exec.execute_query_stage(tid.partition, ctx)
            end_ms = int(time.time() * 1000)
            if self._is_cancelled(tid):
                return TaskStatus(tid, self.metadata.executor_id, "killed")
            return TaskStatus(tid, self.metadata.executor_id, "success",
                              shuffle_writes=writes,
                              launch_time_ms=launch_ms,
                              start_time_ms=start_ms, end_time_ms=end_ms,
                              metrics=stage_exec.collect_plan_metrics(),
                              # key = plan INSTANCE: cumulative MetricsSets
                              # are monotone per decoded plan object, and a
                              # process can host several instances of one
                              # stage (fetch-failure re-resolve changes the
                              # plan blob; LRU eviction re-decodes) — see
                              # ExecutionStage.aggregate_metrics
                              process_id=f"{PROCESS_ID}-{id(task.plan):x}")
        except CancelledError:
            # the operator noticed the job's cancel flag between batches
            # (reference abortable execution, executor.rs:114-144): the
            # slot frees without waiting out the plan
            return TaskStatus(tid, self.metadata.executor_id, "killed")
        except ExecutorKilled:
            # faults kill action: this executor is simulating SIGKILL.  The
            # task unwinds as 'killed' (the graph ignores it); the scheduler
            # learns of the death via heartbeat timeout / launch failures.
            return TaskStatus(tid, self.metadata.executor_id, "killed")
        except FetchFailedError as e:
            return TaskStatus(tid, self.metadata.executor_id, "failed",
                              failure=FailedReason(
                                  FETCH_PARTITION_ERROR, str(e),
                                  map_stage_id=e.map_stage_id,
                                  map_partition_id=e.map_partition_id,
                                  executor_id=e.executor_id))
        except (MemoryExhausted, ResourceExhausted) as e:
            # governor-caught denial that could not degrade to spill:
            # retryable back-pressure, exempt from quarantine strikes —
            # never an executor fault
            return TaskStatus(tid, self.metadata.executor_id, "failed",
                              failure=FailedReason(RESOURCE_EXHAUSTED,
                                                   str(e)))
        except (OSError, IOError_, IntegrityError) as e:
            # IntegrityError covers spill-run read-back CRC mismatches:
            # the retry recomputes from the (immutable) shuffle inputs —
            # lineage recovery, not data corruption
            return TaskStatus(tid, self.metadata.executor_id, "failed",
                              failure=FailedReason(IO_ERROR, str(e)))
        except Exception as e:  # noqa: BLE001 — anything else is fatal
            log.debug("task %s failed:\n%s", tid, traceback.format_exc())
            return TaskStatus(tid, self.metadata.executor_id, "failed",
                              failure=FailedReason(EXECUTION_ERROR,
                                                   f"{type(e).__name__}: {e}"))
        finally:
            install_cancel_token(None)
            with self._lock:
                self._active -= 1
                self._inflight.pop(key, None)

    def submit_task(self, task: TaskDescription,
                    on_done: Callable[[TaskStatus], None]) -> None:
        def run():
            on_done(self.run_task(task))

        self.pool.submit(run)

    # --- cancellation ----------------------------------------------------
    def _is_cancelled(self, tid) -> bool:
        return (tid.job_id in self._cancelled_jobs
                or (tid.job_id, tid.stage_id, tid.partition,
                    tid.task_attempt) in self._cancelled_tasks)

    def cancel_job_tasks(self, job_id: str) -> None:
        self._cancelled_jobs[job_id] = None
        while len(self._cancelled_jobs) > self._max_cancelled:
            self._cancelled_jobs.popitem(last=False)
        # flip the in-flight tokens too: the thread-local checkpoint fires
        # at the next batch boundary even where no probe was wired
        with self._lock:
            for key, token in self._inflight.items():
                if key[0] == job_id:
                    token.cancel()

    def cancel_task(self, task_id) -> None:
        """Cancel ONE attempt (a speculative race's loser): the flag is
        checked between batches and before the result is reported, so the
        attempt unwinds as 'killed' and its outputs are discarded."""
        key = (task_id.job_id, task_id.stage_id, task_id.partition,
               task_id.task_attempt)
        self._cancelled_tasks[key] = None
        while len(self._cancelled_tasks) > self._max_cancelled:
            self._cancelled_tasks.popitem(last=False)
        with self._lock:
            token = self._inflight.get(key)
        if token is not None:
            token.cancel()

    def active_tasks(self) -> int:
        with self._lock:
            return self._active

    def running_task_ids(self) -> List[tuple]:
        """(job, stage, partition, attempt) of in-flight tasks — the
        heartbeat's running-task set (zombie reconciliation).  Empty for
        an idle executor, so the heartbeat wire shape is unchanged."""
        with self._lock:
            return sorted(self._inflight)

    def active_job_ids(self) -> Set[str]:
        """Jobs with at least one in-flight task here (the shuffle
        janitor's live-job guard)."""
        with self._lock:
            return {key[0] for key in self._inflight}

    def shutdown(self) -> None:
        self.pool.shutdown(wait=True)
