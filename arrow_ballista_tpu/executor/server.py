"""Executor network service: task intake + shuffle data plane.

Parity: reference ballista/executor/src/executor_server.rs (push-mode gRPC:
launch_multi_task / cancel_tasks / remove_job_data / stop_executor, status
batching back to the scheduler, 60 s heartbeats) + flight_service.rs
(do_get FetchPartition with IPC streaming).  Both services share one RPC
port here; the path-traversal guard mirrors is_subdirectory
(executor_server.rs:839-876).
"""
from __future__ import annotations

import logging
import os
import queue
import shutil
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from .. import faults, serde
from ..net import wire
from ..net.rpc import RpcServer
from ..net.retry import RetryPolicy, call_with_retry
from ..scheduler.types import ExecutorHeartbeat, ExecutorMetadata, TaskStatus
from ..utils.config import BallistaConfig
from ..utils.errors import ExecutionError
from ..utils.logsetup import ThrottledLogger
from .executor import Executor

log = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 60.0
# interval-class for throttled retry-loop logging: one record per loop kind
# per this many seconds, suppressed occurrences counted (satellite: the
# reporter used to warn once per second for as long as the scheduler was
# down)
RETRY_LOG_INTERVAL_S = 60.0


class StagePlanCache:
    """Tasks of one stage share ONE decoded plan instance so operators'
    lazily-built XLA programs compile once per stage, not once per task
    (the reference decodes a MultiTaskDefinition's stage plan once,
    executor_server.rs:613-697).  Keyed by plan CONTENT, not just
    (job, stage): a stage re-run after lineage rollback carries new shuffle
    locations and must not reuse the stale instance."""

    def __init__(self, max_entries: int = 64):
        import collections

        self._cache = collections.OrderedDict()
        self._max = max_entries
        self._lock = threading.Lock()

    def decode(self, t: dict):
        import hashlib
        import json

        from ..scheduler.types import TaskDescription, TaskId

        tid = t.get("task", {})
        blob = json.dumps(t.get("plan"), sort_keys=True,
                          separators=(",", ":")).encode()
        key = (tid.get("job_id"), tid.get("stage_id"),
               hashlib.sha256(blob).hexdigest())
        with self._lock:
            cached = self._cache.get(key)
            if cached is not None:
                self._cache.move_to_end(key)
        if cached is not None:
            # cache hit: only the cheap task envelope is decoded
            return TaskDescription(TaskId(**t["task"]), cached,
                                   t.get("internal_id", 0),
                                   dict(t.get("scalars", {})),
                                   trace=dict(t.get("trace", {})))
        td = serde.task_from_obj(t)
        with self._lock:
            # re-check: a racing decode of the same stage wins ties
            now = self._cache.get(key)
            if now is not None:
                td.plan = now
            else:
                self._cache[key] = td.plan
                while len(self._cache) > self._max:
                    self._cache.popitem(last=False)
        return td


class SchedulerClient:
    """Executor -> scheduler control-plane client.

    Every call goes through ``net.retry.call_with_retry``: connect/read
    deadlines plus capped jittered backoff bounded by the policy's give-up
    deadline, after which :class:`net.retry.GiveUpError` (retryable at the
    caller) surfaces instead of a hung socket."""

    def __init__(self, host: str, port: int,
                 policy: Optional[RetryPolicy] = None):
        self.host, self.port = host, port
        self.policy = policy or RetryPolicy()

    def _call(self, method: str, payload: dict) -> dict:
        resp, _ = call_with_retry(self.host, self.port, method, payload,
                                  policy=self.policy)
        return resp

    def register_executor(self, meta: ExecutorMetadata) -> None:
        self._call("register_executor",
                   {"meta": serde.executor_metadata_to_obj(meta)})

    def heartbeat(self, executor_id: str, status: str = "active",
                  meta: Optional[ExecutorMetadata] = None,
                  pressure: float = 0.0,
                  running: Optional[List[tuple]] = None) -> None:
        if faults.dropped("executor.heartbeat.send", executor_id=executor_id,
                          status=status):
            raise ConnectionError(
                "failpoint executor.heartbeat.send dropped the heartbeat")
        payload = {"executor_id": executor_id, "status": status}
        if meta is not None:
            payload["meta"] = serde.executor_metadata_to_obj(meta)
        # memory-governor pressure: 0.0 (unbudgeted) omits the key so the
        # wire format is unchanged for unconstrained fleets
        if pressure:
            payload["memory_pressure"] = pressure
        # in-flight (job, stage, partition, attempt) set for zombie-task
        # reconciliation; idle executors omit the key (wire-silent)
        if running:
            payload["running"] = [list(t) for t in running]
        self._call("heartbeat", payload)

    def update_task_status(self, executor_id: str,
                           statuses: List[TaskStatus]) -> None:
        # the drop fires BEFORE the retrying transport so the report is
        # lost outright and the reporter loop's own retry path must redeem
        # it (the chaos suite's dropped-status-report scenario)
        if faults.dropped("executor.status.report", executor_id=executor_id,
                          count=len(statuses)):
            raise ConnectionError(
                "failpoint executor.status.report dropped the payload")
        self._call("update_task_status",
                   {"executor_id": executor_id,
                    "statuses": [serde.status_to_obj(s) for s in statuses]})

    def poll_work(self, executor_id: str, num_free_slots: int,
                  statuses: List[TaskStatus], decode=serde.task_from_obj):
        # single-shot ON PURPOSE: the server POPS tasks into the reply, so a
        # transport-level retry after a lost response would leak the popped
        # tasks.  The poll loop itself retries (re-queueing statuses); only
        # the policy's deadlines apply here.
        payload, _ = wire.call(self.host, self.port, "poll_work", {
            "executor_id": executor_id, "num_free_slots": num_free_slots,
            "statuses": [serde.status_to_obj(s) for s in statuses]},
            timeout=self.policy.read_timeout_s,
            connect_timeout=self.policy.connect_timeout_s)
        from ..scheduler.netservice import ungroup_tasks

        return [decode(t) for t in ungroup_tasks(payload)]

    def executor_stopped(self, executor_id: str, reason: str = "") -> None:
        self._call("executor_stopped",
                   {"executor_id": executor_id, "reason": reason})


class ExecutorServer:
    def __init__(self, scheduler_host: str, scheduler_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 work_dir: Optional[str] = None, concurrent_tasks: int = 4,
                 executor_id: Optional[str] = None,
                 config: Optional[BallistaConfig] = None,
                 external_host: Optional[str] = None,
                 policy: str = "push",
                 job_data_ttl_s: float = 3600.0,
                 janitor_interval_s: float = 300.0,
                 flight_port: int = -1,
                 metrics_port: int = -1,
                 heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
                 scheduler_endpoints: Optional[List[Tuple[str, int]]] = None):
        import socket as socketmod
        import tempfile
        import uuid

        faults.configure(config)
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-exec-")
        executor_id = executor_id or f"exec-{uuid.uuid4().hex[:8]}"
        self.rpc = RpcServer(host, port)
        # advertised address: what peers dial for shuffle fetch (reference
        # executor's external_host flag).  Binding 0.0.0.0 is not routable,
        # so fall back to the machine hostname there.
        if external_host is None:
            external_host = host if host not in ("0.0.0.0", "::") \
                else socketmod.gethostname()
        # data plane: prefer the native (C++) server — shuffle bytes then
        # move kernel->socket via sendfile with no GIL involvement
        # (reference analog: the Flight service next to the gRPC port).
        # One native server per process; extra in-proc executors fall back
        # to the Python RPC handler.  Claimed-and-nulled under
        # _teardown_lock in stop()/kill()
        self._native_dp = None  # ballista: guarded-by=_teardown_lock
        data_port = self.rpc.port
        # shared-secret auth + bounded fan-in (reference issues bearer tokens
        # at Flight handshake, flight_service.rs:136-157, and bounds fetch
        # concurrency with a 50-permit semaphore, shuffle_reader.rs:123)
        self._dp_token = os.environ.get("BALLISTA_DATA_PLANE_TOKEN", "")
        from .. import native as native_mod

        lib = native_mod.dataplane()
        if lib is not None:
            p = lib.dp_start(self.work_dir.encode(), 0,
                             self._dp_token.encode(), 64)
            if p > 0:
                self._native_dp = lib
                data_port = p
                log.info("native data plane on port %d", p)
        self.metadata = ExecutorMetadata(
            executor_id=executor_id, host=external_host, port=data_port,
            grpc_port=self.rpc.port, task_slots=concurrent_tasks)
        self.executor = Executor(self.metadata, self.work_dir, config,
                                 concurrent_tasks=concurrent_tasks)
        self.retry_policy = RetryPolicy.from_config(config) \
            if config is not None else RetryPolicy()
        self.scheduler = SchedulerClient(scheduler_host, scheduler_port,
                                         policy=self.retry_policy)
        # fleet mode: one control-plane client per scheduler shard.  The
        # primary (index 0 / scheduler_host:port) keeps the single-scheduler
        # surface (self.scheduler, _scheduler_down) intact; extra shards get
        # registration + heartbeats so the shared-KV heartbeat row keeps
        # refreshing even after the primary dies, and task statuses route
        # back to whichever shard LAUNCHED the task (see _route_client —
        # a broadcast would double-free shared slot accounting).
        self._route_lock = threading.Lock()
        primary = (scheduler_host, scheduler_port)
        self._clients: Dict[Tuple[str, int], SchedulerClient] = \
            {primary: self.scheduler}  # ballista: guarded-by=_route_lock
        for ep in (scheduler_endpoints or []):
            ep = (ep[0], int(ep[1]))
            if ep not in self._clients:
                self._clients[ep] = SchedulerClient(
                    ep[0], ep[1], policy=self.retry_policy)
        # job -> launching shard endpoint, learned from launch payloads;
        # LRU-bounded (routes die with the job's data cleanup anyway)
        self._job_routes: "OrderedDict[str, Tuple[str, int]]" = \
            OrderedDict()  # ballista: guarded-by=_route_lock
        self._max_job_routes = 512
        assert policy in ("push", "pull")
        self.policy = policy
        self.heartbeat_interval_s = heartbeat_interval_s
        self._stop = threading.Event()
        # monotonic False->True flip written by drain_and_stop() (RPC/main
        # thread) and read by the poll loop + /health route; CPython bool
        # loads are atomic and readers tolerate one stale iteration
        self._draining = False  # ballista: guarded-by=none
        # _teardown_lock serializes stop() vs kill(): chaos fault injection
        # kills from a pool thread while a fixture teardown stops — without
        # it both pass the None-checks and double-stop obs_http/_native_dp
        self._teardown_lock = threading.Lock()
        self._killed = False
        # satellite: bounded/throttled retry loops.  One transition log when
        # the scheduler becomes unreachable (a call blew its give-up
        # deadline); on the next successful call we re-register so a
        # restarted scheduler relearns our metadata immediately.
        self._sched_state_lock = threading.Lock()
        self._scheduler_down = False
        self._log_throttle = ThrottledLogger(log,
                                             interval_s=RETRY_LOG_INTERVAL_S)
        faults.register_kill_target(self.metadata.executor_id, self.kill)
        # loop threads: written once by start() before any of them runs,
        # read only by _join_threads() during shutdown (start happens-before
        # stop), so no lock is needed
        self._hb_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._poll_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._reporter_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._status_queue: "queue.Queue[TaskStatus]" = queue.Queue()
        self.job_data_ttl_s = job_data_ttl_s
        self.janitor_interval_s = janitor_interval_s
        self._janitor_thread: Optional[threading.Thread] = None  # ballista: guarded-by=none
        self._plan_cache = StagePlanCache()

        # optional standard Arrow Flight door (reference
        # flight_service.rs:82-120): any stock Arrow client can do_get a
        # shuffle partition; peers keep using the native/RPC plane
        self.flight = None
        if flight_port >= 0:
            from .flight_service import ExecutorFlightServer

            self.flight = ExecutorFlightServer(self.work_dir, self._dp_token,
                                               host, flight_port)

        # observability listener mirroring the scheduler's exposition:
        # prometheus /metrics + /health (-1 = disabled, 0 = ephemeral port).
        # Claimed-and-nulled under _teardown_lock in stop()/kill(); start()
        # reads it before any other thread exists
        self.obs_http = None  # ballista: guarded-by=_teardown_lock
        if metrics_port >= 0:
            import json as jsonmod

            from ..obs.http import PROM_CTYPE, ObsHttpServer

            def _metrics():
                return (self.executor.metrics.gather(
                    self.executor.active_tasks()), PROM_CTYPE)

            def _health():
                return (jsonmod.dumps({
                    "status": "draining" if self._draining else "ok",
                    "executor_id": self.metadata.executor_id,
                    "policy": self.policy,
                    "task_slots": self.metadata.task_slots,
                    "active_tasks": self.executor.active_tasks(),
                }), "application/json")

            self.obs_http = ObsHttpServer(host, metrics_port,
                                          {"/metrics": _metrics,
                                           "/health": _health})

        self.rpc.register("launch_multi_task", self._launch_multi_task)
        self.rpc.register("cancel_tasks", self._cancel_tasks)
        self.rpc.register("cancel_task", self._cancel_task)
        self.rpc.register("fetch_partition", self._fetch_partition)
        self.rpc.register_stream("fetch_partition_stream",
                                 self._fetch_partition_stream)
        self.rpc.register("remove_job_data", self._remove_job_data)
        self.rpc.register("stop_executor", self._stop_executor)
        self.rpc.register("ping", lambda p, b: ({"executor_id": executor_id}, b""))

    # --- lifecycle -------------------------------------------------------
    def start(self, register: bool = True) -> None:
        self.rpc.start()
        if self.flight is not None:
            self.flight.start()
        if self.obs_http is not None:
            self.obs_http.start()
        if register:
            self.scheduler.register_executor(self.metadata)
            # extra shards are best-effort: a shard that is down now learns
            # us later from the metadata riding on every heartbeat
            for ep, client in self._extra_clients():
                try:
                    client.register_executor(self.metadata)
                except Exception:  # noqa: BLE001 — heartbeat re-registers
                    log.warning("register to scheduler shard %s:%d failed "
                                "(heartbeats will retry)", ep[0], ep[1])
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="executor-heartbeat", daemon=True)
        self._hb_thread.start()
        if self.policy == "pull":
            self._poll_thread = threading.Thread(target=self._poll_loop,
                                                 name="executor-poll", daemon=True)
            self._poll_thread.start()
        else:
            self._reporter_thread = threading.Thread(
                target=self._reporter_loop, name="status-reporter", daemon=True)
            self._reporter_thread.start()
        self._janitor_thread = threading.Thread(target=self._janitor_loop,
                                                name="shuffle-janitor",
                                                daemon=True)
        self._janitor_thread.start()

    def _janitor_loop(self) -> None:
        """Shuffle-data TTL janitor (reference clean_shuffle_data_loop,
        executor_process.rs:245-273): delete job dirs untouched for longer
        than the TTL."""
        while not self._stop.wait(self.janitor_interval_s):
            try:
                now = time.time()
                live = self.executor.active_job_ids()
                for entry in os.scandir(self.work_dir):
                    if not entry.is_dir():
                        continue
                    if entry.name in live:
                        # a job with a task RUNNING here is alive whatever
                        # its files' mtimes say — a long-running producer
                        # that wrote stage 1 output hours ago must not
                        # lose it mid-query to the TTL scan
                        continue
                    newest = entry.stat().st_mtime
                    for root, _dirs, files in os.walk(entry.path):
                        for fn in files:
                            try:
                                newest = max(newest, os.stat(
                                    os.path.join(root, fn)).st_mtime)
                            except OSError:
                                pass
                    if now - newest > self.job_data_ttl_s:
                        log.info("janitor removing stale job data %s", entry.path)
                        from .executor import remove_job_data

                        remove_job_data(self.work_dir, entry.name)
            except Exception:  # noqa: BLE001 — janitor must survive
                log.exception("shuffle janitor iteration failed")

    def _poll_loop(self) -> None:
        """Pull-mode work loop (reference execution_loop.rs:49-133):
        report drained statuses, ask for as many tasks as there are free
        slots, idle-sleep 100 ms when nothing came back."""
        while not self._stop.is_set():
            statuses: List[TaskStatus] = []
            while True:
                try:
                    statuses.append(self._status_queue.get_nowait())
                except queue.Empty:
                    break
            # draining: keep polling to drain statuses, but take no new work
            free = 0 if self._draining else \
                self.metadata.task_slots - self.executor.active_tasks()
            try:
                tasks = self.scheduler.poll_work(self.metadata.executor_id,
                                                 max(0, free), statuses,
                                                 decode=self._plan_cache.decode)
            except Exception:  # noqa: BLE001 — scheduler briefly unreachable
                self._mark_scheduler_down("poll_work")
                self._log_throttle.warning("poll", "poll_work failed",
                                           exc_info=True)
                # re-queue unreported statuses for the next poll
                for st in statuses:
                    self._status_queue.put(st)
                self._stop.wait(1.0)
                continue
            self._mark_scheduler_up()
            for task in tasks:
                self.executor.submit_task(task, self._status_queue.put)
            if not tasks and not statuses:
                self._stop.wait(0.1)

    def drain_and_stop(self, grace_s: float = 30.0) -> None:
        """Graceful shutdown (reference executor_process.rs:309-320):
        Terminating heartbeat -> scheduler stops assigning -> wait for
        in-flight tasks (bounded by ``grace_s``) -> notify -> exit.
        Pull mode additionally stops asking for new work (the poll loop
        keeps running to drain statuses)."""
        self._draining = True
        try:
            self.scheduler.heartbeat(self.metadata.executor_id,
                                     status="terminating", meta=self.metadata)
        # drain proceeds regardless; the scheduler may already be gone
        # ballista: allow=recovery-path-logging — best-effort terminating ping
        except Exception:  # noqa: BLE001 — scheduler may already be gone
            pass
        deadline = time.monotonic() + grace_s
        while self.executor.active_tasks() > 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        # give the status reporter one last chance to flush results
        for _ in range(20):
            if self._status_queue.empty():
                break
            time.sleep(0.1)
        self.stop(notify=True)

    def stop(self, notify: bool = True) -> None:
        with self._teardown_lock:
            if self._killed:
                # kill() already tore the sockets down abruptly; a later
                # fixture teardown must not double-stop or notify
                self._stop.set()
                return
            self._stop.set()
            # claim the shared resources under the lock so a racing kill()
            # cannot stop them a second time (or trip over the None)
            obs_http, self.obs_http = self.obs_http, None
            native_dp, self._native_dp = self._native_dp, None
        faults.unregister_kill_target(self.metadata.executor_id)
        if notify:
            try:
                self.scheduler.executor_stopped(self.metadata.executor_id, "shutdown")
            # best-effort goodbye on shutdown; the scheduler may be gone
            # ballista: allow=recovery-path-logging — outcome needs no trace
            except Exception:  # noqa: BLE001 — scheduler may be gone
                pass
        self.executor.shutdown()
        self.rpc.stop()
        if self.flight is not None:
            self.flight.stop()
        if obs_http is not None:
            obs_http.stop()
        if native_dp is not None:
            native_dp.dp_stop()
        self._join_threads()

    def _join_threads(self) -> None:
        """Bounded join of the long-lived loops: _stop is already set, so
        each exits within one poll interval; the timeout keeps a wedged
        loop from hanging shutdown (the threads are daemons regardless).
        Skip the current thread: the reporter's final flush can be the one
        calling stop() via _stop_executor."""
        cur = threading.current_thread()
        if self._hb_thread is not None and self._hb_thread is not cur:
            self._hb_thread.join(timeout=5.0)
        if self._poll_thread is not None and self._poll_thread is not cur:
            self._poll_thread.join(timeout=5.0)
        if self._reporter_thread is not None and self._reporter_thread is not cur:
            self._reporter_thread.join(timeout=5.0)
        if self._janitor_thread is not None and self._janitor_thread is not cur:
            self._janitor_thread.join(timeout=5.0)

    def kill(self) -> None:
        """Abrupt death for chaos tests (the ``faults`` kill action):
        simulate SIGKILL as closely as one process allows — drop off the
        network NOW.  No Terminating heartbeat, no executor_stopped notify,
        no final status flush; in-flight tasks unwind as ``killed`` and are
        never reported.  The scheduler must discover the death the hard
        way: launch failures, fetch failures, heartbeat timeout."""
        with self._teardown_lock:
            if self._killed:
                return
            self._killed = True
            self._stop.set()
            obs_http, self.obs_http = self.obs_http, None
            native_dp, self._native_dp = self._native_dp, None
        faults.unregister_kill_target(self.metadata.executor_id)
        log.warning("executor %s killed by fault injection",
                    self.metadata.executor_id)
        self.rpc.stop()
        if self.flight is not None:
            self.flight.stop()
        if obs_http is not None:
            obs_http.stop()
        if native_dp is not None:
            native_dp.dp_stop()
        # wait=False: this may run on a pool thread (the task that tripped
        # the failpoint); a joining shutdown would deadlock on itself
        self.executor.pool.shutdown(wait=False)

    def _mark_scheduler_down(self, what: str) -> None:
        with self._sched_state_lock:
            if self._scheduler_down:
                return
            self._scheduler_down = True
        log.warning(
            "scheduler unreachable (%s failed past the %.1fs give-up "
            "deadline); will re-register on reconnect", what,
            self.retry_policy.give_up_after_s)

    def _mark_scheduler_up(self) -> None:
        """First successful call after an outage: re-register, because the
        scheduler may have restarted (or expired us) while unreachable."""
        with self._sched_state_lock:
            if not self._scheduler_down:
                return
            self._scheduler_down = False
        log.info("scheduler reachable again; re-registering executor %s",
                 self.metadata.executor_id)
        try:
            self.scheduler.register_executor(self.metadata)
        except Exception:  # noqa: BLE001 — the next loop pass re-detects
            self._log_throttle.warning(
                "re-register", "re-register after reconnect failed",
                exc_info=True)
            with self._sched_state_lock:
                self._scheduler_down = True

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval_s):
            # memory-governor pressure rides every beat: the scheduler
            # degrades this executor's offer ordering with it, and the
            # fleet-wide floor feeds admission shed
            pressure = self.executor.governor.pressure()
            # in-flight task set: the scheduler diffs it against job truth
            # and re-issues kills for zombies (lost cancel fanouts)
            running = self.executor.running_task_ids()
            try:
                # metadata rides along so a restarted scheduler re-registers
                # us (reference heart_beat_from_executor, grpc.rs:174-241)
                self.scheduler.heartbeat(self.metadata.executor_id,
                                         meta=self.metadata,
                                         pressure=pressure,
                                         running=running)
                self._mark_scheduler_up()
            except Exception:  # noqa: BLE001 — retried next interval
                self._mark_scheduler_down("heartbeat")
                self._log_throttle.warning(
                    "heartbeat", "heartbeat to scheduler failed",
                    exc_info=True)
            # fleet: every shard gets a beat so the shared heartbeat row
            # keeps refreshing through ANY live shard — an executor must
            # not be reaped just because its primary shard died
            for ep, client in self._extra_clients():
                try:
                    client.heartbeat(self.metadata.executor_id,
                                     meta=self.metadata,
                                     pressure=pressure,
                                     running=running)
                except Exception:  # noqa: BLE001 — that shard may be dead
                    self._log_throttle.warning(
                        f"heartbeat-{ep[0]}:{ep[1]}",
                        "heartbeat to scheduler shard %s:%d failed",
                        ep[0], ep[1], exc_info=True)

    # --- fleet routing ---------------------------------------------------
    #: consecutive failed reporter rounds against one shard before its
    #: statuses fail over to a sibling (each round already spends the
    #: client's full in-call retry deadline, so 2 rounds ≈ several seconds
    #: of continuous unreachability — a dead shard, not a blip)
    REROUTE_AFTER = 2

    def _extra_clients(self):
        with self._route_lock:
            return [(ep, c) for ep, c in self._clients.items()
                    if c is not self.scheduler]

    def _primary_endpoint(self) -> Optional[Tuple[str, int]]:
        # an injected in-process scheduler (tests, embedded standalone mode)
        # has no endpoint; routing then collapses to the single-scheduler
        # path: every status goes straight through self.scheduler
        host = getattr(self.scheduler, "host", None)
        port = getattr(self.scheduler, "port", None)
        if host is None or port is None:
            return None
        return (host, int(port))

    def _client_for(self, ep: Optional[Tuple[str, int]]) -> SchedulerClient:
        if ep is None:
            return self.scheduler
        with self._route_lock:
            client = self._clients.get(ep)
            if client is None:
                client = SchedulerClient(ep[0], ep[1],
                                         policy=self.retry_policy)
                self._clients[ep] = client
            return client

    def _route_endpoint(self, job_id: str) -> Optional[Tuple[str, int]]:
        """The shard that most recently launched tasks for this job: task
        statuses must go back to the shard DRIVING the job.  A broadcast
        would double-free the shared slot accounting, and pinning the
        primary would strand statuses after an adoption re-homes the job
        (the adopter's launches overwrite the route).  ``None`` means the
        in-process injected scheduler (no endpoint to route by)."""
        with self._route_lock:
            return self._job_routes.get(job_id) or self._primary_endpoint()

    def _route_client(self, job_id: str) -> SchedulerClient:
        return self._client_for(self._route_endpoint(job_id))

    def _reroute_jobs(self, job_ids, dead_ep: Optional[Tuple[str, int]],
                      attempt: int) -> Optional[Tuple[str, int]]:
        """Re-home these jobs' statuses to a sibling shard: their routed
        shard stayed unreachable for REROUTE_AFTER reporter rounds (killed
        or partitioned away).  Delivering to ANY live shard frees the
        shared slot accounting — without this, slots reserved by a dead
        shard's in-flight tasks leak and the adopter can never relaunch —
        and once the adopter launches, its payload overwrites the route
        with itself.  Continued failure walks the candidate list."""
        if dead_ep is None:
            # the injected in-process scheduler has no siblings; rerouting
            # to a networked endpoint would strand the statuses instead
            return None
        with self._route_lock:
            candidates = [e for e in self._clients if e != dead_ep]
            if not candidates:
                return None
            fallback = candidates[attempt % len(candidates)]
            for job_id in job_ids:
                self._job_routes[job_id] = fallback
                self._job_routes.move_to_end(job_id)
        return fallback

    def _learn_routes(self, payload: dict, tasks) -> None:
        sched = payload.get("scheduler")
        if not sched:
            return
        ep = (sched["host"], int(sched["port"]))
        with self._route_lock:
            for task in tasks:
                self._job_routes[task.task.job_id] = ep
                self._job_routes.move_to_end(task.task.job_id)
            while len(self._job_routes) > self._max_job_routes:
                self._job_routes.popitem(last=False)

    # --- RPC handlers ----------------------------------------------------
    def _launch_multi_task(self, payload: dict, _bin: bytes):
        from ..scheduler.netservice import ungroup_tasks

        # MultiTaskDefinition shape (one plan + N task envelopes) or the
        # legacy flat shape
        tasks = [self._decode_task(t) for t in ungroup_tasks(payload)]
        self._learn_routes(payload, tasks)
        for task in tasks:
            self.executor.submit_task(task, self._report_status)
        return {"accepted": len(tasks)}, b""

    def _decode_task(self, t: dict):
        return self._plan_cache.decode(t)

    def _report_status(self, status: TaskStatus) -> None:
        # push mode routes through the batching reporter loop so a transient
        # scheduler-connection failure can never lose a TaskStatus (the
        # reference batches + retries the same way, executor_server.rs
        # TaskRunnerPool reporter loop; pull mode re-queues in _poll_loop)
        self._status_queue.put(status)

    def _reporter_loop(self) -> None:
        pending: List[TaskStatus] = []
        # consecutive failed rounds per shard endpoint; reaching
        # REROUTE_AFTER re-homes that shard's statuses to a sibling
        route_fails: Dict[Tuple[str, int], int] = {}
        while not self._stop.is_set():
            try:
                pending.append(self._status_queue.get(timeout=0.2))
            except queue.Empty:
                pass
            while True:
                try:
                    pending.append(self._status_queue.get_nowait())
                except queue.Empty:
                    break
            if not pending:
                continue
            # fleet: group by the shard that launched each job's tasks and
            # flush per shard — one dead shard must not dam statuses bound
            # for live ones.  Routes are re-resolved on every attempt, so
            # statuses stranded toward a dead shard drain to the adopter as
            # soon as its first launch overwrites the job's route.
            groups: Dict[Tuple[str, int], List[TaskStatus]] = {}
            for st in pending:
                groups.setdefault(self._route_endpoint(st.task.job_id),
                                  []).append(st)
            primary = self._primary_endpoint()
            still_pending: List[TaskStatus] = []
            for ep, sts in groups.items():
                client = self._client_for(ep)
                try:
                    client.update_task_status(self.metadata.executor_id,
                                              list(sts))
                    route_fails.pop(ep, None)
                    if ep == primary:
                        self._mark_scheduler_up()
                except Exception:  # noqa: BLE001 — keep and retry next round
                    fails = route_fails.get(ep, 0) + 1
                    route_fails[ep] = fails
                    if ep == primary:
                        self._mark_scheduler_down("status report")
                    ep_label = "%s:%d" % ep if ep else "in-process"
                    if fails >= self.REROUTE_AFTER:
                        fallback = self._reroute_jobs(
                            {st.task.job_id for st in sts}, ep,
                            fails - self.REROUTE_AFTER)
                        if fallback is not None:
                            log.warning(
                                "shard %s unreachable for %d status "
                                "rounds; rerouting %d status(es) to %s:%d",
                                ep_label, fails, len(sts),
                                fallback[0], fallback[1])
                    self._log_throttle.warning(
                        "status-report",
                        "status report to %s failed (%d pending, will "
                        "retry)", ep_label, len(sts), exc_info=True)
                    still_pending.extend(sts)
            pending = still_pending
            if pending:
                self._stop.wait(1.0)
        # final best-effort flush on shutdown — but NOT after kill():
        # a SIGKILLed executor reports nothing
        with self._teardown_lock:
            killed = self._killed
        if pending and not killed:
            flush: Dict[int, List[TaskStatus]] = {}
            fclients: Dict[int, SchedulerClient] = {}
            for st in pending:
                client = self._route_client(st.task.job_id)
                fclients[id(client)] = client
                flush.setdefault(id(client), []).append(st)
            for key, sts in flush.items():
                try:
                    fclients[key].update_task_status(
                        self.metadata.executor_id, list(sts))
                # last-gasp flush on shutdown; nothing listens to a failure
                # ballista: allow=recovery-path-logging — best effort
                except Exception:  # noqa: BLE001
                    pass

    def _cancel_tasks(self, payload: dict, _bin: bytes):
        self.executor.cancel_job_tasks(payload["job_id"])
        return {}, b""

    def _cancel_task(self, payload: dict, _bin: bytes):
        # single-attempt cancel: the losing duplicate of a speculative race
        self.executor.cancel_task(serde.taskid_from_obj(payload["task"]))
        return {}, b""

    def _is_under_work_dir(self, path: str) -> bool:
        base = os.path.realpath(self.work_dir)
        target = os.path.realpath(path)
        return os.path.commonpath([base, target]) == base

    def _fetch_partition(self, payload: dict, _bin: bytes):
        if self._dp_token and payload.get("token", "") != self._dp_token:
            raise ExecutionError("data plane auth failed")
        path = payload["path"]
        if not self._is_under_work_dir(path):
            raise ExecutionError(f"path {path!r} escapes the work dir")
        if not os.path.exists(path):
            raise ExecutionError(f"no such shuffle file: {path}")
        with open(path, "rb") as f:
            data = f.read()
        return {"num_bytes": len(data)}, data

    def _fetch_partition_stream(self, payload: dict, _bin: bytes, send):
        """Chunked shuffle fetch: same auth + path guard as the whole-file
        protocol, then the framing is delegated to the shared data-plane
        server half (net/dataplane.stream_partition)."""
        from ..net.dataplane import stream_partition

        if self._dp_token and payload.get("token", "") != self._dp_token:
            raise ExecutionError("data plane auth failed")
        path = payload["path"]
        if not self._is_under_work_dir(path):
            raise ExecutionError(f"path {path!r} escapes the work dir")
        if not os.path.exists(path):
            raise ExecutionError(f"no such shuffle file: {path}")
        stream_partition(path, payload, send)

    def _remove_job_data(self, payload: dict, _bin: bytes):
        from .executor import remove_job_data

        remove_job_data(self.work_dir, payload["job_id"])
        return {}, b""

    def _stop_executor(self, payload: dict, _bin: bytes):
        threading.Thread(target=self.stop, kwargs={"notify": False},
                         daemon=True).start()
        return {}, b""
