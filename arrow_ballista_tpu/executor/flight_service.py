"""Standard Arrow Flight data plane on the executor.

Parity: reference executors serve shuffle partitions to peers AND stock
Arrow clients via Flight ``do_get(Ticket{FetchPartition})``
(reference ballista/executor/src/flight_service.rs:82-120, two-slot
streaming channel; handshake issues a bearer token, :136-157).  The
engine's own peers prefer the native C++ sendfile plane (net/dataplane +
native/dataplane.cpp) — this door exists so ANY Arrow-speaking client can
fetch a partition with no Ballista code: the shuffle files on disk are
plain Arrow IPC in physical representation (models/ipc.py), streamed
as-is.

Tickets: JSON ``{"path": ..., "token": ...}`` or raw path bytes — the
scheme a stock ``pyarrow.flight`` client can build by hand from the
PartitionLocation the scheduler hands out.  Auth mirrors the RPC data
plane: when BALLISTA_DATA_PLANE_TOKEN is set, tickets must carry it.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

log = logging.getLogger(__name__)


class ExecutorFlightServer:
    """Flight door over an ExecutorServer's work dir (lazy pyarrow.flight
    import, same pattern as the scheduler's BallistaFlightServer)."""

    def __init__(self, work_dir: str, token: str = "",
                 host: str = "127.0.0.1", port: int = 0):
        import pyarrow.flight as fl

        outer = self
        self.work_dir = work_dir
        self._token = token

        class _Server(fl.FlightServerBase):
            def __init__(self):
                super().__init__(location=f"grpc://{host}:{port}")

            def do_get(self, context, ticket):
                return outer._do_get(bytes(ticket.ticket))

        self._fl = fl
        self._server = _Server()
        self.host = host
        self.port = self._server.port
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve,
                                        name=f"exec-flight-{self.port}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        try:
            self._server.shutdown()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            log.debug("executor flight shutdown", exc_info=True)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # --- serving ---------------------------------------------------------
    def _resolve(self, raw: bytes) -> str:
        token = ""
        try:
            obj = json.loads(raw.decode("utf-8"))
            path = obj["path"]
            token = obj.get("token", "")
        # not an error path: a non-JSON ticket IS the raw shuffle-file path
        # ballista: allow=recovery-path-logging — expected legacy-ticket shape
        except Exception:  # noqa: BLE001 — raw path ticket
            path = raw.decode("utf-8")
        if self._token and token != self._token:
            raise self._fl.FlightUnauthorizedError("data plane auth failed")
        base = os.path.realpath(self.work_dir)
        target = os.path.realpath(path)
        if os.path.commonpath([base, target]) != base:
            raise self._fl.FlightServerError(
                f"path {path!r} escapes the work dir")
        if not os.path.exists(target):
            raise self._fl.FlightServerError(f"no such shuffle file: {path}")
        return target

    def _do_get(self, raw: bytes):
        import pyarrow as pa

        path = self._resolve(raw)
        reader = pa.ipc.open_file(pa.memory_map(path))
        # stream batch-by-batch off the memory map (the reference's
        # two-slot streaming channel shape) — read_all() would hold the
        # whole partition in executor RAM per concurrent fetch
        batches = (reader.get_batch(i)
                   for i in range(reader.num_record_batches))
        return self._fl.GeneratorStream(reader.schema, batches)
