"""Executor metrics: process counters + prometheus text exposition.

Mirrors the scheduler's exposition format (scheduler/metrics.py) so one
scrape config covers both roles; parity target is the reference
executor's ExecutorMetricsCollector surface.  Served by the
``ExecutorServer`` observability listener (``--metrics-port``) at
``/metrics``, with ``/health`` alongside for liveness probes.
"""
from __future__ import annotations

import threading

from ..scheduler.metrics import Histogram


class ExecutorMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.launched = 0
        self.completed = 0
        self.failed = 0
        self.killed = 0
        self.shuffle_bytes = 0
        self.shuffle_rows = 0
        self.task_duration = Histogram([0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                                        30.0, 120.0])

    def record_task(self, status, duration_s: float) -> None:
        """Fold one finished task's outcome (every run_task return path)."""
        with self._lock:
            self.launched += 1
            if status.state == "success":
                self.completed += 1
            elif status.state == "killed":
                self.killed += 1
            else:
                self.failed += 1
            for w in status.shuffle_writes or []:
                self.shuffle_bytes += int(w.num_bytes)
                self.shuffle_rows += int(w.num_rows)
            self.task_duration.observe(max(0.0, duration_s))

    def gather(self, active_tasks: int = 0) -> str:
        with self._lock:
            lines = []

            def counter(name, v, help_):
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {v}")

            counter("executor_tasks_launched_total", self.launched,
                    "tasks this executor started")
            counter("executor_tasks_completed_total", self.completed,
                    "tasks that finished successfully")
            counter("executor_tasks_failed_total", self.failed,
                    "tasks that finished in failure")
            counter("executor_tasks_killed_total", self.killed,
                    "tasks killed by job cancellation")
            counter("executor_shuffle_bytes_written_total",
                    self.shuffle_bytes, "shuffle bytes written")
            counter("executor_shuffle_rows_written_total",
                    self.shuffle_rows, "shuffle rows written")
            # read-side data-plane accounting: process-global (one view per
            # executor process; standalone in-proc executors share it)
            from ..net.dataplane import STATS as dp_stats

            snap = dp_stats.snapshot()
            name = "shuffle_bytes_fetched_total"
            lines.append(f"# HELP {name} shuffle bytes read by this process, "
                         "by transport path (local_mmap = zero-copy "
                         "co-located read, local_copy = non-mmap local read, "
                         "remote = data-plane fetch; remote counts "
                         "bytes-on-wire, post-compression)")
            lines.append(f"# TYPE {name} counter")
            for p, v in sorted(snap["bytes_fetched"].items()):
                lines.append(f'{name}{{path="{p}"}} {v}')
            counter("shuffle_fetch_chunks_total", snap["chunks"],
                    "chunks received over the streaming shuffle protocol")
            counter("shuffle_fetch_chunks_resumed_total",
                    snap["resumed_chunks"],
                    "chunks skipped by resuming a retried stream at the "
                    "first unverified chunk")
            lines.append("# HELP shuffle_wire_compression_ratio raw/wire "
                         "byte ratio of streamed shuffle fetches (>1 = "
                         "compression shrank the wire; 1.0 = none yet)")
            lines.append("# TYPE shuffle_wire_compression_ratio gauge")
            lines.append("shuffle_wire_compression_ratio "
                         f"{dp_stats.compression_ratio():.4f}")
            # device-observatory process totals (obs/device.py STATS):
            # process-global like the data-plane counters above
            from ..obs.device import STATS as dev_stats

            dsnap = dev_stats.snapshot()
            counter("device_jit_compiles_total",
                    int(dsnap["jit_compiles"]),
                    "first-time XLA compilations observed through the "
                    "engine's jit wrappers")
            counter("device_jit_retraces_total",
                    int(dsnap["jit_retraces"]),
                    "re-compilations of an already-compiled program at a "
                    "new (shape, dtype, static-arg) key")
            counter("device_jit_cache_hits_total",
                    int(dsnap["jit_cache_hits"]),
                    "jitted calls served by an already-compiled executable")
            counter("device_jit_compile_seconds_total",
                    round(float(dsnap["jit_compile_time"]), 6),
                    "wall time spent inside compiling jit dispatches "
                    "(trace + lowering + backend compile)")
            counter("device_program_cache_hits_total",
                    int(dsnap["program_cache_hits"]),
                    "cross-job shared_program closure-cache hits "
                    "(ops/physical.py)")
            counter("device_program_cache_misses_total",
                    int(dsnap["program_cache_misses"]),
                    "shared_program closure-cache misses (a closure was "
                    "built and inserted)")
            counter("device_h2d_bytes_total", int(dsnap["h2d_bytes"]),
                    "bytes moved host->device through accounted "
                    "device_put sites (batch materialization)")
            counter("device_d2h_bytes_total", int(dsnap["d2h_bytes"]),
                    "bytes moved device->host through accounted "
                    "device_get sites (packed host collects)")
            lines.append("# HELP device_live_bytes_peak high-water mark of "
                         "live device-buffer bytes sampled at task/operator "
                         "boundaries (jax.live_arrays)")
            lines.append("# TYPE device_live_bytes_peak gauge")
            lines.append(
                f"device_live_bytes_peak {int(dsnap['device_live_peak_bytes'])}")
            lines.append("# HELP host_rss_bytes_peak high-water mark of "
                         "this process's resident set (ru_maxrss; "
                         "KB-granular on Linux)")
            lines.append("# TYPE host_rss_bytes_peak gauge")
            lines.append(
                f"host_rss_bytes_peak {int(dsnap['host_rss_peak_bytes'])}")
            # memory-governor process totals (memory/governor.py STATS):
            # reservation accounting + spill volume, process-global
            from ..memory import STATS as mem_stats

            msnap = mem_stats.snapshot()
            name = "memory_reserved_bytes"
            lines.append(f"# HELP {name} bytes currently reserved from the "
                         "memory governor by running operators, per pool")
            lines.append(f"# TYPE {name} gauge")
            for pool in ("host", "device"):
                v = int(msnap.get(f"reserved_bytes.{pool}", 0))
                lines.append(f'{name}{{pool="{pool}"}} {v}')
            counter("memory_spill_bytes_total",
                    int(msnap.get("spill_bytes_total", 0)),
                    "bytes written to disk as Arrow IPC spill runs by "
                    "operators the governor denied an in-memory grant")
            counter("memory_spill_runs_total",
                    int(msnap.get("spill_runs_total", 0)),
                    "spill run files written (agg partial runs + join "
                    "build partitions)")
            counter("memory_reserve_denied_total",
                    int(msnap.get("reserve_denied_total", 0)),
                    "governor reservation denials (each degraded an "
                    "operator to its spill path, or failed the task "
                    "retriably with spill disabled)")
            counter("memory_over_budget_grants_total",
                    int(msnap.get("over_budget_grants_total", 0)),
                    "forced over-budget grants to operators with a hard "
                    "single-pass requirement (left/full outer join build "
                    "sides)")
            lines.append("# HELP executor_active_tasks tasks currently "
                         "executing")
            lines.append("# TYPE executor_active_tasks gauge")
            lines.append(f"executor_active_tasks {active_tasks}")
            h = self.task_duration
            name = "executor_task_duration_seconds"
            lines.append(f"# HELP {name} wall time per task")
            lines.append(f"# TYPE {name} histogram")
            acc = 0
            for b, c in zip(h.buckets, h.counts):
                acc += c
                lines.append(f'{name}_bucket{{le="{b}"}} {acc}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {h.n}')
            lines.append(f"{name}_sum {h.total}")
            lines.append(f"{name}_count {h.n}")
            return "\n".join(lines) + "\n"
