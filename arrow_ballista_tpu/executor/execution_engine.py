"""ExecutionEngine: the pluggable backend seam.

Parity: reference ballista/executor/src/execution_engine.rs:32-121 — the
trait through which alternative engines (there: a possible Ballista fork;
here: the TPU engine vs a host-side debug engine) plug into the executor.
``create_query_stage_exec`` rebinds the scheduler-sent plan to the
executor's work_dir; ``QueryStageExecutor.execute_query_stage`` runs one
partition and returns shuffle-write metadata.
"""
from __future__ import annotations

from typing import Dict, List

from ..ops.physical import TaskContext
from ..ops.shuffle import ShuffleWritePartition, ShuffleWriterExec
from ..utils.config import BallistaConfig


class QueryStageExecutor:
    def execute_query_stage(self, partition: int, ctx: TaskContext
                            ) -> List[ShuffleWritePartition]:
        raise NotImplementedError

    def collect_plan_metrics(self) -> Dict[str, Dict[str, float]]:
        return {}


class DefaultQueryStageExecutor(QueryStageExecutor):
    def __init__(self, plan: ShuffleWriterExec):
        self.plan = plan

    def execute_query_stage(self, partition: int, ctx: TaskContext
                            ) -> List[ShuffleWritePartition]:
        writes = self.plan.execute_write(partition, ctx)
        rec = getattr(ctx, "span_recorder", None)
        if rec is not None and writes:
            rec.annotate(
                rows_written=int(sum(w.num_rows for w in writes)),
                bytes_shuffled=int(sum(w.num_bytes for w in writes)))
        return writes

    def collect_plan_metrics(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}

        def walk(p, path="0"):
            # to_dict (not .values) so deferred metrics — counts that were
            # device-resident at record time — resolve into the snapshot
            out[f"{path}:{type(p).__name__}"] = p.metrics().to_dict()
            for i, c in enumerate(p.children()):
                walk(c, f"{path}.{i}")

        walk(self.plan)
        return out


class ExecutionEngine:
    def create_query_stage_exec(self, job_id: str, stage_id: int,
                                plan: ShuffleWriterExec, work_dir: str
                                ) -> QueryStageExecutor:
        raise NotImplementedError


class DefaultExecutionEngine(ExecutionEngine):
    """The TPU engine: plans arrive as ShuffleWriterExec trees whose
    operators compile to XLA programs on first execute (parity with the
    reference default engine rewrapping ShuffleWriterExec,
    execution_engine.rs:62-89)."""

    def create_query_stage_exec(self, job_id, stage_id, plan, work_dir):
        if not isinstance(plan, ShuffleWriterExec):
            raise TypeError(f"stage plan must be a ShuffleWriterExec, "
                            f"got {type(plan).__name__}")
        return DefaultQueryStageExecutor(plan)
