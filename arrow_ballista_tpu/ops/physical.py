"""Physical plan base + scan operators.

The ExecutionPlan interface mirrors the one trait the whole reference leans
on (DataFusion's ExecutionPlan as used by e.g.
reference ballista/core/src/execution_plans/shuffle_writer.rs:291-415):
``execute(partition) -> batches``, ``output_partition_count``, ``schema``,
``children``.  TPU-first difference: ``execute`` returns a *list* of
fixed-capacity device ColumnBatches (usually exactly one large batch per
partition — big static shapes feed the VPU/MXU well), not a pull-based
stream of small batches.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models import expr as E
from ..models.batch import ColumnBatch, concat_batches, round_capacity
from ..models.schema import DataType, Schema
from ..obs import device as device_obs
from ..obs.device import observed_jit
from ..utils.config import BallistaConfig
from ..utils.errors import ExecutionError, InternalError
from .expressions import ExprCompiler


# --------------------------------------------------------------------------
# execution context & metrics
# --------------------------------------------------------------------------


class MetricsSet:
    """Per-operator metrics, the analog of the reference's OperatorMetric
    proto (reference ballista/core/proto/ballista.proto:248-281).
    Thread-safe: same-stage tasks share the operator instance and record
    concurrently once device dispatch runs outside the xla_lock."""

    def __init__(self):
        self.values: Dict[str, float] = {}
        # RLock: deferred resolvers run under the lock in to_dict and may
        # themselves record metrics (e.g. a fused aggregate latching its
        # passthrough fallback once the output row count becomes host-known)
        self._lock = threading.RLock()
        self._deferred = []  # [(name, fn)] resolved lazily in to_dict

    def add(self, name: str, v: float):
        with self._lock:
            self.values[name] = self.values.get(name, 0) + v

    def add_deferred(self, name: str, fn):
        """Record a metric whose value would cost a device->host sync right
        now (~75 ms fixed latency on remote-attached devices).  ``fn()``
        must return the value, or None while it is not yet host-known —
        not-ready entries stay queued for the next snapshot.  Downstream
        materialization (the shuffle writer's packed fetch) normally makes
        the value free before any snapshot happens."""
        with self._lock:
            self._deferred.append((name, fn))

    def timer(self, name: str):
        return _Timer(self, name)

    def to_dict(self):
        with self._lock:
            pending = []
            for name, fn in self._deferred:
                v = fn()
                if v is None:
                    pending.append((name, fn))
                else:
                    self.values[name] = self.values.get(name, 0) + v
            self._deferred = pending
            return dict(self.values)


# --------------------------------------------------------------------------
# cross-job compiled-program cache
# --------------------------------------------------------------------------
#
# Operators lazily build their compiled closures (ExprCompiler output +
# jax.jit wrappers) per plan INSTANCE, and plan instances are per job — so
# re-running the same query re-traced every program (~0.2 s per program on
# the remote TPU backend even with the in-process executable cache, ~1.5-2 s
# per TPC-H query).  Closures whose behavior depends only on (exprs, input
# schema) are shared process-wide here, keyed by that signature.  The jit
# wrapper travels with the closure, so its shape-keyed executable cache is
# shared too.  Instance-local adaptive state (capacity hints, build caches)
# stays on the operator.  The reference has no analog: its operators are
# interpreted, not compiled (DataFusion executes loose; only the TPU
# backend pays per-trace costs).

_program_cache = collections.OrderedDict()
_PROGRAM_CACHE_MAX = 256
_program_cache_lock = threading.Lock()


def shared_program(key, build):
    """Memoize ``build()`` under ``key`` (hashable compile signature).
    Concurrent builders may race outside the lock; first insert wins so
    every caller converges on one closure/jit object.  A key containing
    None (an expression with no serde signature) disables sharing."""
    if any(k is None for k in key):
        return build()
    with _program_cache_lock:
        hit = _program_cache.get(key)
        if hit is not None:
            _program_cache.move_to_end(key)
            device_obs.record_program_cache(hit=True)
            return hit
    device_obs.record_program_cache(hit=False)
    built = build()
    with _program_cache_lock:
        now = _program_cache.get(key)
        if now is not None:
            return now
        _program_cache[key] = built
        while len(_program_cache) > _PROGRAM_CACHE_MAX:
            _program_cache.popitem(last=False)
    return built


def schema_sig(s) -> tuple:
    return tuple((f.name, f.dtype.kind, f.dtype.scale, f.nullable)
                 for f in s)


def exprs_sig(exprs):
    """Stable signature of expressions via their serde form; None when any
    expression has no serde (callers must then skip program sharing).
    UDF calls bake the registry's current fn into the compiled closure, so
    the signature carries the registry generation — a re-registered UDF
    must never be served from a stale cached program."""
    import json

    from .. import serde
    from ..models import expr as E

    def has_udf(e):
        if e is None:
            return False
        return isinstance(e, E.Udf) or any(has_udf(c) for c in e.children())

    try:
        sig = json.dumps([serde.expr_to_obj(e) if e is not None else None
                          for e in exprs], sort_keys=True,
                         separators=(",", ":"))
    except Exception:  # noqa: BLE001 — unknown expr node: don't share
        return None
    if any(has_udf(e) for e in exprs):
        from ..udf import GLOBAL_UDFS

        sig = f"udfgen={GLOBAL_UDFS.generation};{sig}"
    return sig


def has_scalar_subquery(*exprs) -> bool:
    """True when any expression embeds a ScalarSubquery: its value is
    substituted per job (ctx.scalars), so the compiled closure bakes a
    job-specific literal and must NOT be shared across jobs."""
    from ..models import expr as E

    def walk(e):
        if e is None:
            return False
        if isinstance(e, E.ScalarSubquery):
            return True
        return any(walk(c) for c in e.children())

    return any(walk(e) for e in exprs)


def deferred_rows(ms: MetricsSet, name: str, batch) -> None:
    """Record ``batch``'s row count as a deferred metric WITHOUT pinning the
    batch: the closure holds a weakref, so device buffers are never kept
    alive by metrics.  If the batch is GC'd before its count became
    host-known (it was never materialized), the entry resolves to 0 rather
    than staying queued forever."""
    import weakref

    ref = weakref.ref(batch)

    def fn():
        b = ref()
        if b is None:
            return 0
        return b._num_rows

    ms.add_deferred(name, fn)


class _Timer:
    def __init__(self, ms: MetricsSet, name: str):
        self.ms, self.name = ms, name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.ms.add(self.name, time.perf_counter() - self.t0)


@contextlib.contextmanager
def _span_and_device(span_cm, op):
    """Tracing span + device-attribution scope around one operator
    execute: the device scope nests inside the span so the span's
    metric-delta snapshot (TaskSpanRecorder.op_span) sees the device
    counters this call added."""
    with span_cm, device_obs.op_scope(op):
        yield


# --------------------------------------------------------------------------
# cooperative cancellation token (query lifecycle guardrails)
# --------------------------------------------------------------------------
#
# The executor's task wrapper installs a CancelToken in thread-local
# storage around each task run; cancel/deadline fanout flips the token.
# ``TaskContext.check_cancelled`` (and the free function ``checkpoint()``
# for code paths with no ctx handle, e.g. between fused-kernel
# invocations) consult it in addition to the wired probe, so a cancel
# lands at the next batch boundary even in contexts constructed without a
# probe.  Cost when unset: one thread-local attribute read.

class CancelToken:
    """One task attempt's cancel flag.  Plain bool write/read — flips are
    idempotent and the reader tolerates staleness by one batch."""

    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


_CANCEL_TLS = threading.local()


def install_cancel_token(token: Optional[CancelToken]) -> None:
    """Bind ``token`` to the calling thread (None uninstalls).  Called by
    the executor's task wrapper around each task run."""
    _CANCEL_TLS.token = token


def current_cancel_token() -> Optional[CancelToken]:
    return getattr(_CANCEL_TLS, "token", None)


def checkpoint(job_id: str = "") -> None:
    """Module-level cancellation checkpoint: raises CancelledError when
    the calling thread's installed token has been cancelled.  A no-op
    (one thread-local read) when no token is installed — library code may
    call it unconditionally."""
    token = getattr(_CANCEL_TLS, "token", None)
    if token is not None and token.cancelled:
        from .. import faults
        from ..utils.errors import CancelledError

        # delay failpoint: widen the window between the flag flip and the
        # raise so chaos tests can race cancellation against completion
        faults.inject("executor.task.cancel.checkpoint", job_id=job_id)
        raise CancelledError(f"job {job_id} cancelled" if job_id
                             else "task cancelled")


@dataclasses.dataclass
class TaskContext:
    config: BallistaConfig = dataclasses.field(default_factory=BallistaConfig)
    scalars: Dict[str, object] = dataclasses.field(default_factory=dict)
    work_dir: str = "/tmp/ballista_tpu"
    job_id: str = ""
    stage_id: int = 0
    executor_id: str = ""  # identity of the executing node (shuffle locality)
    # advertised host of the executing node: a PartitionLocation whose host
    # matches is on the same machine, so its shuffle file can be mmap'd
    # locally instead of fetched over the data plane ("" = unknown, never
    # host-matches)
    executor_host: str = ""
    # shuffle partition locations: (stage_id, partition) -> list of paths/addrs
    shuffle_locations: Dict = dataclasses.field(default_factory=dict)
    # cooperative cancellation probe (executor wires the job's cancel flag);
    # operators call check_cancelled() at batch/operator boundaries so a
    # cancelled job frees its slot without waiting out the whole plan
    # (reference: abortable execution, executor.rs:114-144)
    cancelled: Optional[Callable[[], bool]] = None
    # obs.tracing.TaskSpanRecorder for the running task; None = tracing off
    span_recorder: Optional[object] = None
    # memory.MemoryGovernor of the executing node; None = ungoverned
    # (operators then materialize unbounded state without reservations)
    governor: Optional[object] = None

    def check_cancelled(self) -> None:
        # thread-local token first: it covers contexts constructed without
        # a wired probe (subplan execution, fused-kernel interiors) and is
        # one attribute read when no token is installed
        token = getattr(_CANCEL_TLS, "token", None)
        if token is not None and token.cancelled:
            from .. import faults
            from ..utils.errors import CancelledError

            faults.inject("executor.task.cancel.checkpoint",
                          job_id=self.job_id, stage_id=self.stage_id)
            raise CancelledError(f"job {self.job_id} cancelled")
        if self.cancelled is not None and self.cancelled():
            from .. import faults
            from ..utils.errors import CancelledError

            faults.inject("executor.task.cancel.checkpoint",
                          job_id=self.job_id, stage_id=self.stage_id)
            raise CancelledError(f"job {self.job_id} cancelled")

    def op_span(self, op):
        """Context manager spanning one operator's execute call: always
        enters the device-observatory attribution scope (obs/device.py —
        a shared null context when that is off), plus the tracing span
        when a recorder rides along; operators instrument
        unconditionally."""
        if self.span_recorder is None:
            return device_obs.op_scope(op)
        return _span_and_device(self.span_recorder.op_span(op), op)


# --------------------------------------------------------------------------
# partitioning descriptors (reference: datafusion Partitioning / proto
# PhysicalHashRepartition, ballista.proto)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Partitioning:
    kind: str  # 'unknown' | 'hash' | 'single'
    count: int
    exprs: Sequence[E.Expr] = ()

    @staticmethod
    def unknown(n: int) -> "Partitioning":
        return Partitioning("unknown", n)

    @staticmethod
    def hash(exprs: Sequence[E.Expr], n: int) -> "Partitioning":
        return Partitioning("hash", n, tuple(exprs))

    @staticmethod
    def single() -> "Partitioning":
        return Partitioning("single", 1)


_LOCK_CREATE = threading.Lock()


class ExecutionPlan:
    """Base physical operator."""

    _schema: Schema

    @property
    def schema(self) -> Schema:
        return self._schema

    def xla_lock(self) -> threading.Lock:
        """Per-operator lock guarding the lazy jit-closure build.

        Same-stage tasks share one operator instance; without this, N pool
        threads race the lazy ``self._compiled`` build and trigger N
        duplicate XLA compilations (minutes each on TPU).  Hold it ONLY
        around the build: device dispatch runs outside so one task's
        host<->device transfers overlap another's device compute
        (HashAggregateExec/JoinExec do this) — which also means the lock
        does NOT protect shared state touched during execution; any such
        state needs its own synchronization (MetricsSet and the
        ExprCompiler aux cache carry their own locks)."""
        lock = getattr(self, "_xla_lock", None)
        if lock is None:
            with _LOCK_CREATE:
                lock = getattr(self, "_xla_lock", None)
                if lock is None:
                    self._xla_lock = lock = threading.Lock()
        return lock

    def children(self) -> List["ExecutionPlan"]:
        return []

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.output_partition_count())

    def output_partition_count(self) -> int:
        raise NotImplementedError

    def execute(self, partition: int, ctx: TaskContext) -> List[ColumnBatch]:
        raise NotImplementedError

    def metrics(self) -> MetricsSet:
        # double-checked under the module lock: concurrent first calls from
        # same-stage tasks (dispatch runs outside xla_lock) must not create
        # two MetricsSet instances and lose one task's records
        ms = getattr(self, "_metrics", None)
        if ms is None:
            with _LOCK_CREATE:
                ms = getattr(self, "_metrics", None)
                if ms is None:
                    self._metrics = ms = MetricsSet()
        return ms

    # display
    def display(self, indent: int = 0) -> str:
        s = "  " * indent + self._label()
        for c in self.children():
            s += "\n" + c.display(indent + 1)
        return s

    def _label(self) -> str:
        return type(self).__name__

    def __repr__(self):
        return self.display()


# --------------------------------------------------------------------------
# arrow -> physical conversion
# --------------------------------------------------------------------------


def _sorted_dictionary(dic: np.ndarray, codes: np.ndarray):
    """Re-sort a dictionary lexicographically and remap codes (engine
    invariant: dictionaries are sorted, so code order == string order)."""
    order = np.argsort(dic)
    rank = np.empty(len(order), dtype=np.int32)
    rank[order] = np.arange(len(order), dtype=np.int32)
    new_codes = np.where(codes >= 0, rank[np.clip(codes, 0, None)], -1).astype(np.int32)
    return dic[order], new_codes


def table_to_physical(table, schema: Schema):
    """pyarrow Table -> (numpy cols dict, dicts dict) in physical repr."""
    import pyarrow as pa
    import pyarrow.compute as pc

    cols: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}
    for f in schema:
        arr = table.column(f.name)
        if f.dtype.is_string:
            combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
            if not pa.types.is_dictionary(combined.type):
                combined = pc.dictionary_encode(combined)
            if isinstance(combined, pa.ChunkedArray):
                combined = combined.combine_chunks()
            indices = pc.fill_null(combined.indices, -1)
            codes = indices.to_numpy(zero_copy_only=False).astype(np.int32)
            dic = np.asarray(combined.dictionary.to_pylist(), dtype=object)
            dic_sorted, codes = _sorted_dictionary(dic, codes) if len(dic) else (dic, codes)
            cols[f.name] = codes
            dicts[f.name] = dic_sorted if len(dic) else dic
        elif f.dtype.kind == "date32":
            a = arr
            if not pa.types.is_date32(a.type if not isinstance(a, pa.ChunkedArray) else a.type):
                a = a.cast(pa.date32())
            a = a.cast(pa.int32())
            if a.null_count:
                a = pc.fill_null(a, int(f.dtype.null_sentinel))
            cols[f.name] = a.to_numpy(zero_copy_only=False).astype(np.int32)
        elif f.dtype.is_decimal:
            ftype = table.schema.field(f.name)
            if pa.types.is_integer(ftype.type):
                # int64-stored decimal (unscaled values; metadata carries
                # the storage scale — benchmarks/tpch.py
                # decimal_to_int64_storage / models/ipc.py convention):
                # already the engine's physical representation, up to a
                # power-of-ten rescale when schemas disagree
                from ..models.ipc import int64_decimal_storage_scale

                sscale = int64_decimal_storage_scale(ftype) or 0
                nulls = None
                a = arr
                if a.null_count:
                    if isinstance(a, pa.ChunkedArray):
                        a = a.combine_chunks()
                    nulls = pc.is_null(a).to_numpy(zero_copy_only=False)
                    a = pc.fill_null(a, 0)
                vals = a.cast(pa.int64()).to_numpy(zero_copy_only=False)
                if sscale != f.dtype.scale:
                    if f.dtype.scale > sscale:
                        factor = np.int64(10 ** (f.dtype.scale - sscale))
                        # int64 multiplication wraps silently: keep the
                        # overflow guard the float path had
                        if len(vals) and np.abs(vals).max() > (2**63 - 1) // int(factor):
                            raise ExecutionError(
                                f"decimal column {f.name} exceeds int64 "
                                "range after rescale")
                        vals = vals * factor
                    else:
                        vals = vals // np.int64(10 ** (sscale - f.dtype.scale))
                vals = vals.astype(np.int64, copy=False)
                if nulls is not None:
                    vals = vals.copy()
                    vals[nulls] = np.int64(f.dtype.null_sentinel)
                cols[f.name] = vals
                continue
            # NULLs can't ride the float64 conversion (the int64-min
            # sentinel exceeds the 2^52 exact range): remember them, fill
            # with 0 for conversion, then stamp the sentinel back in
            nulls = None
            a = arr
            if a.null_count:
                if isinstance(a, pa.ChunkedArray):
                    a = a.combine_chunks()
                nulls = pc.is_null(a).to_numpy(zero_copy_only=False)
                a = pc.fill_null(a, 0)
            fl = a.cast(pa.float64()).to_numpy(zero_copy_only=False)
            scaled = np.round(fl * (10 ** f.dtype.scale))
            if np.any(np.abs(scaled) > 2**52):
                raise ExecutionError(
                    f"decimal column {f.name} exceeds exact float64 conversion range"
                )
            out = scaled.astype(np.int64)
            if nulls is not None:
                out[nulls] = np.int64(f.dtype.null_sentinel)
            cols[f.name] = out
        else:
            a = arr
            if a.null_count:
                # real input NULLs -> the per-dtype in-band sentinel; the
                # field must be declared nullable for aggregate/IS NULL
                # semantics to see them (providers set this from null stats)
                sent = f.dtype.null_sentinel
                if isinstance(sent, float):
                    a = a.cast(pa.float64())
                    vals = a.to_numpy(zero_copy_only=False)  # nulls -> NaN
                    cols[f.name] = vals.astype(f.dtype.np_dtype)
                    continue
                a = pc.fill_null(a, int(sent) if not isinstance(sent, bool) else sent)
            cols[f.name] = a.to_numpy(zero_copy_only=False).astype(f.dtype.np_dtype)
    return cols, dicts


def table_to_batches(table, schema: Schema, capacity: int) -> List[ColumnBatch]:
    """Split an arrow table into fixed-capacity device batches (shared,
    sorted dictionaries across all batches of this table)."""
    cols, dicts = table_to_physical(table, schema)
    n = table.num_rows
    if n == 0:
        return [ColumnBatch.empty(schema, min(capacity, 1024))]
    out = []
    for start in range(0, n, capacity):
        end = min(start + capacity, n)
        chunk = {k: v[start:end] for k, v in cols.items()}
        cap = capacity if end - start == capacity else round_capacity(end - start)
        out.append(ColumnBatch.from_numpy(schema, chunk, dicts=dicts, capacity=cap))
    return out


# --------------------------------------------------------------------------
# scans
# --------------------------------------------------------------------------


class ScanExec(ExecutionPlan):
    """Base: reads arrow tables per partition, converts to device batches,
    applies pushed-down filters inside the scan."""

    def __init__(self, schema: Schema, filters: Sequence[E.Expr] = ()):
        self._schema = schema
        self.filters = list(filters)
        self._filter_compiler: Optional[ExprCompiler] = None
        self._filter_fn = None

    def _read_partition(self, partition: int):  # -> pyarrow table
        raise NotImplementedError

    def _cache_key(self, partition: int, capacity: int):
        """Key for the device-resident scan cache, or None when this scan
        can't be cached (volatile source).  Must embed source versioning
        (file mtime/size) so stale data can never be served."""
        return None

    def output_partition_count(self) -> int:
        raise NotImplementedError

    def _produce_batches(self, partition: int, ctx: TaskContext,
                         capacity: int) -> List[ColumnBatch]:
        """Read + convert one partition to device batches (pre-filter)."""
        with self.metrics().timer("scan_read_time"):
            table = self._read_partition(partition)
        ctx.check_cancelled()
        with self.metrics().timer("scan_convert_time"):
            return table_to_batches(table, self._schema, capacity)

    def execute(self, partition: int, ctx: TaskContext) -> List[ColumnBatch]:
        with ctx.op_span(self):
            return self._execute(partition, ctx)

    def _execute(self, partition: int, ctx: TaskContext) -> List[ColumnBatch]:
        import jax
        import jax.numpy as jnp

        from ..utils import table_cache
        from ..utils.config import SCAN_CACHE_BYTES

        ctx.check_cancelled()
        capacity = ctx.config.batch_size
        budget = table_cache.resolve_budget(ctx.config.get(SCAN_CACHE_BYTES))
        key = self._cache_key(partition, capacity) if budget else None
        batches = table_cache.CACHE.get(key) if key is not None else None
        if batches is None:
            batches = self._produce_batches(partition, ctx, capacity)
            if key is not None:
                table_cache.CACHE.set_budget(budget)
                table_cache.CACHE.put(key, batches)
        else:
            self.metrics().add("scan_cache_hits", 1)
        self.metrics().add("output_rows", sum(b.num_rows for b in batches))
        if not self.filters:
            return batches
        # compile the conjunction once per (schema, filters) — shared
        # across jobs re-running the same query (scan filters never embed
        # scalar subqueries; those stay above the scan)
        with self.xla_lock():
            if self._filter_fn is None:
                def build():
                    comp = ExprCompiler(self._schema, "device")
                    pred = comp.compile_pred(E.and_all(self.filters))
                    return comp, observed_jit(
                        "scan.filter",
                        lambda cols, mask, aux: mask & pred.fn(cols, aux))

                self._filter_compiler, self._filter_fn = shared_program(
                    ("scanfilter", schema_sig(self._schema),
                     exprs_sig(self.filters)), build)
            out = []
            for b in batches:
                aux = self._filter_compiler.aux_arrays(b.dicts)
                new_mask = self._filter_fn(b.columns, b.mask, aux)
                out.append(ColumnBatch(b.schema, b.columns, new_mask, b.dicts))
        return out


class MemoryScanExec(ScanExec):
    """In-memory table scan, row-sliced into partitions."""

    def __init__(self, schema: Schema, table, partitions: int = 1,
                 filters: Sequence[E.Expr] = ()):
        super().__init__(schema, filters)
        self.table = table.select(schema.names())
        self.partitions = max(1, min(partitions, max(1, self.table.num_rows)))

    def output_partition_count(self) -> int:
        return self.partitions

    def _read_partition(self, partition: int):
        n = self.table.num_rows
        per = (n + self.partitions - 1) // self.partitions
        start = partition * per
        return self.table.slice(start, per)

    def _label(self):
        return f"MemoryScanExec: {self.table.num_rows} rows, {self.partitions} partitions"


def _simple_predicates(filters: Sequence[E.Expr], schema: Schema):
    """Extract ``column <op> literal`` conjuncts usable against parquet
    row-group statistics.  Returns [(col_name, op, value, dtype)] with the
    literal converted to the column's **physical** value domain — the same
    one the executed predicate compares in (dates as epoch days, decimals
    as scaled ints via the same rounding as ExprCompiler._lit_physical) —
    so pruning can never disagree with execution."""
    from .expressions import ExprCompiler, fold_constants

    conv = ExprCompiler(schema, "host")
    out = []
    for f in filters:
        for c in E.conjuncts(f):
            c = fold_constants(c)
            if not (isinstance(c, E.BinOp) and c.op in ("=", "<", "<=", ">", ">=")):
                continue
            col, lit, op = None, None, c.op
            if isinstance(c.left, E.Column) and isinstance(c.right, E.Lit):
                col, lit = c.left, c.right
            elif isinstance(c.right, E.Column) and isinstance(c.left, E.Lit):
                col, lit = c.right, c.left
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}[op]
            if col is None or col.name not in schema:
                continue
            v = lit.value
            if isinstance(v, bool) or v is None:
                continue
            dt = schema.field(col.name).dtype
            if not isinstance(v, str):
                try:
                    v = conv._lit_physical(lit, dt)
                except Exception:
                    continue
            out.append((col.name, op, v, dt))
    return out


def _stats_refute(stats, op: str, value, dt: DataType,
                  stats_scale: Optional[int] = None) -> bool:
    """True iff row-group stats prove no row can satisfy ``col op value``.
    ``value`` is in the column's physical domain (see _simple_predicates);
    stats min/max are converted into that same domain before comparing.
    ``stats_scale``: for int64-stored decimal columns, the storage scale —
    integer stats are then already scaled by 10^stats_scale and must NOT
    be scaled again (double-scaling would wrongly refute matching row
    groups)."""
    if stats is None or not stats.has_min_max:
        return False
    lo, hi = stats.min, stats.max
    try:
        if isinstance(value, str):
            if not isinstance(lo, (str, bytes)):
                return False
            if isinstance(lo, bytes):
                lo, hi = lo.decode("utf-8", "replace"), hi.decode("utf-8", "replace")
        else:
            import datetime
            import decimal as pydec

            def phys(x):
                # datetime.datetime must be checked before datetime.date
                # (it's a subclass); both map to epoch days
                if isinstance(x, datetime.datetime):
                    return (x.date() - datetime.date(1970, 1, 1)).days
                if isinstance(x, datetime.date):
                    return (x - datetime.date(1970, 1, 1)).days
                if dt.is_decimal:
                    if stats_scale is not None and isinstance(x, int):
                        # python ints: exact; floor division matches the
                        # row conversion's // so pruning can never disagree
                        # with execution
                        if dt.scale >= stats_scale:
                            return x * (10 ** (dt.scale - stats_scale))
                        return x // (10 ** (stats_scale - dt.scale))
                    if isinstance(x, pydec.Decimal):
                        return int(x.scaleb(dt.scale))  # exact
                    return float(x) * (10 ** dt.scale)
                if isinstance(x, (int, float, pydec.Decimal)):
                    return float(x)
                raise TypeError(f"unusable stats value {x!r}")

            lo, hi = phys(lo), phys(hi)
        if op == "=":
            return value < lo or value > hi
        if op == "<":
            return lo >= value
        if op == "<=":
            return lo > value
        if op == ">":
            return hi <= value
        if op == ">=":
            return hi < value
    except (TypeError, ValueError, ArithmeticError):
        return False
    return False


class ParquetScanExec(ScanExec):
    """Parquet scan at **row-group granularity**: the partition unit is a
    (file, row_group) pair, balanced across ``target_partitions`` by row
    count, so a single large file still scans in parallel (the reference
    gets file-level parallelism from DataFusion's ParquetExec; row groups
    are the TPU-friendly unit because each becomes one padded device batch).

    Pushdown: simple ``col <op> literal`` conjuncts are checked against
    row-group min/max statistics at plan time — refuted row groups are never
    read.  All predicates are re-applied on device afterwards (pruning is
    only ever an over-approximation)."""

    def __init__(self, schema: Schema, paths: List[str], target_partitions: int,
                 filters: Sequence[E.Expr] = (), table_schema: Optional[Schema] = None):
        super().__init__(schema, filters)
        from ..utils import object_store as obs

        self.table_schema = table_schema or schema
        files = []
        for p in paths:
            files.extend(obs.list_files(p, (".parquet",)))
        if not files:
            raise ExecutionError(f"no parquet files found in {paths}")
        self.files = files

        import pyarrow as pa

        preds = _simple_predicates(self.filters, self.table_schema)
        units: List[Tuple[str, int, int]] = []  # (file, row_group, rows)
        self.pruned_row_groups = 0
        for f in files:
            pf = obs.parquet_file(f)
            meta = pf.metadata
            name_to_idx = {meta.schema.column(i).name: i
                           for i in range(meta.num_columns)}
            # int64-stored decimal columns: their integer stats are in the
            # storage-scaled domain (metadata convention, see
            # table_to_physical)
            from ..models.ipc import int64_decimal_storage_scale

            stats_scales = {}
            for af in pf.schema_arrow:
                s = int64_decimal_storage_scale(af)
                if s is not None:
                    stats_scales[af.name] = s
            for rg in range(meta.num_row_groups):
                g = meta.row_group(rg)
                refuted = False
                for col, op, v, dt in preds:
                    ci = name_to_idx.get(col)
                    if ci is None:
                        continue
                    if _stats_refute(g.column(ci).statistics, op, v, dt,
                                     stats_scale=stats_scales.get(col)):
                        refuted = True
                        break
                if refuted:
                    self.pruned_row_groups += 1
                else:
                    units.append((f, rg, g.num_rows))
        self._total_rows = sum(u[2] for u in units)
        if not units:  # everything pruned: keep one empty partition
            self.groups: List[List[Tuple[str, int, int]]] = [[]]
        else:
            # greedy row-count balancing into k partitions
            k = max(1, min(target_partitions, len(units)))
            heaps = [(0, i) for i in range(k)]
            groups: List[List[Tuple[str, int, int]]] = [[] for _ in range(k)]
            import heapq

            heapq.heapify(heaps)
            for u in sorted(units, key=lambda u: -u[2]):
                rows, i = heapq.heappop(heaps)
                groups[i].append(u)
                heapq.heappush(heaps, (rows + u[2], i))
            self.groups = [g for g in groups if g]

    def output_partition_count(self) -> int:
        return len(self.groups)

    def _cache_key(self, partition: int, capacity: int):
        """(file, row-group, mtime, size) units + projection + capacity.
        Local files embed stat() versioning; object-store URLs (no local
        stat) skip caching rather than risk staleness."""
        units = self.groups[partition]
        if not units:
            return None
        import os as _os

        versioned = []
        for f, rg, _rows in units:
            try:
                st = _os.stat(f)
            except OSError:
                return None
            versioned.append((f, rg, st.st_mtime_ns, st.st_size))
        return ("parquet", tuple(versioned), tuple(self._schema.names()), capacity)

    def _read_units(self, units):
        import pyarrow as pa

        from ..utils import object_store as obs

        if not units:
            return self._schema.to_arrow_empty()
        by_file: Dict[str, List[int]] = {}
        for f, rg, _ in units:
            by_file.setdefault(f, []).append(rg)
        cols = self._schema.names()
        # string columns come back dictionary-decoded straight from the
        # parquet pages: the engine dictionary-codes them on device anyway,
        # so this skips a full re-encode pass in table_to_physical
        rd = [f.name for f in self._schema if f.dtype.is_string] or None
        if len(by_file) == 1:
            f, rgs = next(iter(by_file.items()))
            return obs.read_parquet_row_groups(f, sorted(rgs), cols,
                                               read_dictionary=rd)
        # overlap reads across files (each pyarrow read releases the GIL;
        # object-store fetches overlap their network latency)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(by_file))) as pool:
            tables = list(pool.map(
                lambda kv: obs.read_parquet_row_groups(
                    kv[0], sorted(kv[1]), cols, read_dictionary=rd),
                by_file.items()))
        return pa.concat_tables(tables)

    def _read_partition(self, partition: int):
        return self._read_units(self.groups[partition])

    def _produce_batches(self, partition: int, ctx: TaskContext,
                         capacity: int) -> List[ColumnBatch]:
        """Double-buffered cold path: read chunk i+1 on a background thread
        while chunk i converts and transfers to the device, so a cold scan
        costs ~max(read, convert+H2D) instead of their sum (the streaming
        shape of the reference's shuffle-writer pull loop,
        reference shuffle_writer.rs:214-252, applied to the scan).

        Chunks group row-group units to >= ``capacity`` rows, so the device
        batch shapes match the unpipelined path and the jit cache stays
        small.  Per-chunk string dictionaries can differ across chunks;
        downstream consumers unify on demand (models/batch.py
        _unify_string_dicts) — same contract as mixed scan partitions."""
        units = self.groups[partition]
        chunks: List[List[Tuple[str, int, int]]] = []
        cur, cur_rows = [], 0
        for u in sorted(units):
            cur.append(u)
            cur_rows += u[2]
            if cur_rows >= capacity:
                chunks.append(cur)
                cur, cur_rows = [], 0
        if cur:
            chunks.append(cur)
        if len(chunks) <= 1:
            return super()._produce_batches(partition, ctx, capacity)
        from concurrent.futures import ThreadPoolExecutor

        batches: List[ColumnBatch] = []
        pool = ThreadPoolExecutor(max_workers=1)
        try:
            fut = pool.submit(self._read_units, chunks[0])
            for i in range(len(chunks)):
                ctx.check_cancelled()
                # scan_read_time records time BLOCKED on IO; overlapped
                # read time hides behind the previous chunk's convert+H2D
                with self.metrics().timer("scan_read_time"):
                    table = fut.result()
                if i + 1 < len(chunks):
                    fut = pool.submit(self._read_units, chunks[i + 1])
                with self.metrics().timer("scan_convert_time"):
                    batches.extend(table_to_batches(table, self._schema, capacity))
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
        return batches

    def row_count_estimate(self) -> int:
        return self._total_rows

    def clustered_ranges(self, col_name: str):
        """If the data is CLUSTERED on ``col_name`` (per-row-group min/max
        stats non-decreasing in row order), compute a regroup of this
        scan's partitions into contiguous row-group runs and return
        ``(groups, ranges)`` — the new partition groups and their
        per-partition (min, max) key ranges; else None.

        Side-effect free: the caller commits ``groups`` to ``self.groups``
        only when the annotation is accepted.  (Probing used to mutate the
        scan in place, so a probe that produced a single range — possible
        when one huge trailing row group absorbs the whole regroup — was
        rejected by the planner AFTER having already collapsed the scan's
        partitions.)

        Basis of the clustered group-by early-HAVING rewrite
        (scheduler/physical_planner.py): for a clustered key, a partial
        aggregate over a contiguous partition is already FINAL for every
        key except those in range overlaps between neighboring partitions.
        The reference has no analog — DataFusion's partial/final agg split
        (the reference's stage shape for q18's subquery) always ships every
        partial state through the exchange.

        Memoized per column: the planner pass may probe the same scan
        twice (presorted-only annotate, then the early-HAVING upgrade),
        and the stats sweep walks every row group's metadata."""
        cache = getattr(self, "_clustered_cache", None)
        if cache is None:
            self._clustered_cache = cache = {}
        if col_name in cache:
            return cache[col_name]
        cache[col_name] = self._clustered_ranges_impl(col_name)
        return cache[col_name]

    def _clustered_ranges_impl(self, col_name: str):
        from ..utils import object_store as obs

        units = sorted(u for g in self.groups for u in g)
        if len(units) <= 1 or not units:
            return None
        stats_per_unit = []
        for f, rg, _rows in units:
            pf = obs.parquet_file(f)
            meta = pf.metadata
            idx = None
            for i in range(meta.num_columns):
                if meta.schema.column(i).name == col_name:
                    idx = i
                    break
            if idx is None:
                return None
            st = meta.row_group(rg).column(idx).statistics
            if st is None or not st.has_min_max:
                return None
            if not isinstance(st.min, int) or not isinstance(st.max, int):
                return None  # int keys only (exact, order-stable)
            stats_per_unit.append((st.min, st.max))
        # clustered iff unit ranges are non-decreasing in row order
        for (lo_a, hi_a), (lo_b, hi_b) in zip(stats_per_unit,
                                              stats_per_unit[1:]):
            if hi_a > lo_b:
                return None
        # contiguous regroup at the same partition count, row-balanced
        k = len(self.groups)
        total = sum(u[2] for u in units)
        per = max(1, -(-total // k))
        new_groups, new_ranges = [], []
        cur, cur_rows, cur_lo, cur_hi = [], 0, None, None
        for u, (lo, hi) in zip(units, stats_per_unit):
            cur.append(u)
            cur_rows += u[2]
            cur_lo = lo if cur_lo is None else min(cur_lo, lo)
            cur_hi = hi if cur_hi is None else max(cur_hi, hi)
            if cur_rows >= per and len(new_groups) < k - 1:
                new_groups.append(cur)
                new_ranges.append((cur_lo, cur_hi))
                cur, cur_rows, cur_lo, cur_hi = [], 0, None, None
        if cur:
            new_groups.append(cur)
            new_ranges.append((cur_lo, cur_hi))
        return new_groups, new_ranges

    def _label(self):
        pruned = f", {self.pruned_row_groups} row-groups pruned" if self.pruned_row_groups else ""
        n_units = sum(len(g) for g in self.groups)
        return (f"ParquetScanExec: {len(self.files)} files, {n_units} row-groups, "
                f"{len(self.groups)} partitions{pruned}")


def _arrow_type_of(dt: DataType):
    """Engine dtype -> the arrow type file readers should parse into."""
    import pyarrow as pa

    return {
        "int32": pa.int32(), "int64": pa.int64(), "float32": pa.float32(),
        "float64": pa.float64(), "bool": pa.bool_(), "date32": pa.date32(),
        "decimal": pa.float64(), "string": pa.string(),
    }[dt.kind]


class FileListScanExec(ScanExec):
    """Shared scaffolding for whole-file scans (csv/json/avro): object-store
    listing, round-robin file grouping into partitions, per-file read +
    concat.  Parquet scans stay separate (row-group granularity)."""

    SUFFIXES: Tuple[str, ...] = ()
    FORMAT = "file"

    def __init__(self, schema: Schema, paths: List[str], target_partitions: int,
                 filters: Sequence[E.Expr] = (), table_schema: Optional[Schema] = None):
        super().__init__(schema, filters)
        from ..utils import object_store as obs

        self.table_schema = table_schema or schema
        files = []
        for p in paths:
            files.extend(obs.list_files(p, self.SUFFIXES))
        if not files:
            raise ExecutionError(f"no {self.FORMAT} files found in {paths}")
        self.files = files
        k = max(1, min(target_partitions, len(files)))
        self.groups = [files[i::k] for i in range(k)]

    def output_partition_count(self) -> int:
        return len(self.groups)

    def _read_one(self, path: str):
        raise NotImplementedError

    def _read_partition(self, partition: int):
        import pyarrow as pa

        tables = [self._read_one(f) for f in self.groups[partition]]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

    def _label(self):
        return (f"{type(self).__name__}: {len(self.files)} files, "
                f"{len(self.groups)} partitions")


class CsvScanExec(FileListScanExec):
    """CSV scan (including TPC-H ``.tbl`` pipe-delimited files)."""

    SUFFIXES = (".csv", ".tbl")
    FORMAT = "csv"

    def __init__(self, schema: Schema, paths: List[str], target_partitions: int,
                 filters: Sequence[E.Expr] = (), table_schema: Optional[Schema] = None,
                 delimiter: str = ",", has_header: bool = True):
        super().__init__(schema, paths, target_partitions, filters, table_schema)
        self.delimiter = delimiter
        self.has_header = has_header

    def _read_one(self, path: str):
        import pyarrow.csv as pacsv

        from ..utils import object_store as obs

        names = self.table_schema.names()
        column_types = {f.name: _arrow_type_of(f.dtype) for f in self.table_schema}
        trailing = _has_trailing_delimiter(path, self.delimiter)
        read_names = None if self.has_header else names + (["__trail"] if trailing else [])
        ropts = pacsv.ReadOptions(column_names=read_names)
        popts = pacsv.ParseOptions(delimiter=self.delimiter)
        copts = pacsv.ConvertOptions(
            column_types=column_types, include_columns=self._schema.names()
        )
        with obs.open_input(path) as fh:
            return pacsv.read_csv(fh, read_options=ropts, parse_options=popts,
                                  convert_options=copts)


class JsonScanExec(FileListScanExec):
    """Newline-delimited JSON scan (reference reads json via DataFusion's
    NdJson reader, client context.rs register_json).  Parsing uses the
    TABLE schema explicitly — per-file type inference would let two files
    of one table disagree (int vs null vs double) and break the concat."""

    SUFFIXES = (".json", ".jsonl", ".ndjson")
    FORMAT = "json"

    def _read_one(self, path: str):
        import pyarrow as pa
        import pyarrow.json as pajson

        from ..utils import object_store as obs

        explicit = pa.schema([
            pa.field(f.name, _arrow_type_of(f.dtype))
            for f in self.table_schema])
        popts = pajson.ParseOptions(explicit_schema=explicit)
        with obs.open_input(path) as fh:
            table = pajson.read_json(fh, parse_options=popts)
        return table.select(self._schema.names())


class AvroScanExec(FileListScanExec):
    """Avro object-container-file scan (reference reads avro via DataFusion;
    the container codec lives in utils/avro.py — no external avro library
    exists in this image)."""

    SUFFIXES = (".avro",)
    FORMAT = "avro"

    def _read_one(self, path: str):
        from ..utils import object_store as obs
        from ..utils.avro import avro_to_arrow

        with obs.open_input(path) as fh:
            return avro_to_arrow(fh).select(self._schema.names())


def _has_trailing_delimiter(path: str, delim: str) -> bool:
    from ..utils import object_store as obs

    buf = b""
    with obs.open_input(path) as fh:
        # read until the first newline (or EOF) — never misjudge a first
        # line longer than one chunk
        while b"\n" not in buf:
            chunk = fh.read(1 << 16)
            if not chunk:
                break
            buf += chunk
    line = buf.split(b"\n", 1)[0].rstrip(b"\r")
    return line.endswith(delim.encode())
