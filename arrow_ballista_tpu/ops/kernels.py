"""Core device kernels: pure jit-compatible functions over padded arrays.

These are the TPU replacements for the DataFusion operator internals the
reference leans on (hash aggregate / hash join / sort inside the
ShuffleWriter hot loop, reference
ballista/core/src/execution_plans/shuffle_writer.rs:214-252).  Every kernel
keeps **static shapes**: data-dependent cardinalities (group counts, join
fan-out) go to fixed capacities with liveness masks, which is what lets XLA
compile one fused program per stage.

Key techniques:
- grouping is sort-based (lexsort -> boundary flags -> segment reductions),
  exact for any key combination, no hash tables in HBM required;
- joins sort the build side by a 64-bit mixed key, probe via searchsorted,
  expand variable fan-out through a cumulative-offset inversion, then verify
  *real* key equality so hash collisions never corrupt results;
- calendar decomposition (EXTRACT) uses the civil-from-days algorithm in
  pure integer arithmetic.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.device import observed_jit

I64_MAX = jnp.int64(2**63 - 1)


# --------------------------------------------------------------------------
# hashing
# --------------------------------------------------------------------------


def force_hash_collisions() -> bool:
    """Collision-stress mode (the reference ships this as the
    ``force_hash_collisions`` cargo feature, reference
    ballista/core/Cargo.toml:40-41): every hash64 becomes a constant, so
    all rows collide into one shuffle bucket / join probe range.  Join and
    aggregate correctness must survive because both re-verify real key
    equality after hashing.  Process-level env flag — set
    ``BALLISTA_FORCE_HASH_COLLISIONS=1`` before any program traces — the
    first read is cached for the process lifetime, so already-traced and
    newly-traced programs can never disagree about hashing (a mid-process
    flip would silently split keys across transports)."""
    global _FORCE_COLLISIONS
    if _FORCE_COLLISIONS is None:
        from ..utils.config import env_flag

        _FORCE_COLLISIONS = bool(env_flag("BALLISTA_FORCE_HASH_COLLISIONS"))
    return _FORCE_COLLISIONS


_FORCE_COLLISIONS: Optional[bool] = None


def hash64(arrays: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Combine columns into a 64-bit mixed hash (splitmix64-style)."""
    if force_hash_collisions():
        return jnp.zeros(arrays[0].shape, dtype=jnp.uint64)
    h = jnp.zeros(arrays[0].shape, dtype=jnp.uint64)
    for a in arrays:
        x = a.astype(jnp.uint64)
        x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
        x = x ^ (x >> 31)
        h = h * jnp.uint64(0x9E3779B97F4A7C15) + x
        h = h ^ (h >> 29)
    return h


def bucket_of(key_arrays: Sequence[jnp.ndarray], num_buckets: int) -> jnp.ndarray:
    """Shuffle partition id per row (same role as the reference's
    BatchPartitioner hash path, shuffle_writer.rs:201-252)."""
    return (hash64(key_arrays) % jnp.uint64(num_buckets)).astype(jnp.int32)


# --------------------------------------------------------------------------
# compaction
# --------------------------------------------------------------------------


def compaction_order(mask: jnp.ndarray) -> jnp.ndarray:
    """Stable permutation moving live rows to the front.

    Sort-free: destinations come from two cumsums and the permutation from
    one scatter — O(n) work, and (unlike jnp.argsort on this backend) the
    XLA program compiles in seconds, not minutes."""
    n = mask.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    live_pos = jnp.cumsum(mask) - 1
    dead_pos = jnp.sum(mask) + jnp.cumsum(~mask) - 1
    dest = jnp.where(mask, live_pos, dead_pos).astype(jnp.int32)
    return jnp.zeros(n, dtype=jnp.int32).at[dest].set(idx)


def compact_columns(cols: Dict[str, jnp.ndarray], mask: jnp.ndarray):
    order = compaction_order(mask)
    return {k: v[order] for k, v in cols.items()}, mask[order]


# --------------------------------------------------------------------------
# wire packing: ONE device->host transfer per materialization boundary
# --------------------------------------------------------------------------
#
# The device->host path of a remote-attached accelerator (the axon tunnel)
# has ~75 ms FIXED latency per transfer — even for a scalar — plus ~20 MB/s
# streaming, 100x below host->device.  A boundary that fetches per-column
# padded arrays (or syncs num_rows separately) pays that fixed cost many
# times over.  pack_for_host compacts live rows, bit-packs every column AND
# the row count into one int64 buffer on device, so a boundary costs exactly
# one fetch of (live rows x columns) bytes.  The reference has no analog —
# its operators live host-side (shuffle_writer.rs streams host batches);
# this is the TPU-native replacement for that hot loop's memory traffic.


@observed_jit("kernels.pack_for_host",
              static_argnames=("target", "namesi64", "namesf64", "names32"))
def pack_for_host(cols, mask, target: int, namesi64, namesf64, names32):
    """Compact live rows to the front and pack columns + live-row count for
    a minimal device->host transfer.

    Returns ``(buf, fbuf)``: ``buf`` is one flat int64 buffer laid out as
    [count:1][each int64 column:target][all 32-bit columns, bit-paired into
    int64: len(names32)*target/2]; ``fbuf`` stacks float64 columns
    separately (or None) because the TPU X64-emulation pass implements
    s32<->s64 bitcasts but not f64 ones — f64 columns only occur in small
    late-stage outputs (averages), so the extra transfer leaf rides the
    same device_get.  float32 bitcasts to int32 (exact); bool widens to
    int32.  ``target`` caps the packed row count — the host checks
    count<=target and refetches at a larger target otherwise (count rides
    in the same buffer, so the common case is one transfer with no separate
    num_rows sync)."""
    order = compaction_order(mask)[:target]
    parts = [jnp.sum(mask).astype(jnp.int64)[None]]
    for k in namesi64:
        parts.append(cols[k][order])
    if names32:
        w32 = []
        for k in names32:
            v = cols[k]
            if v.dtype == jnp.float32:
                v = jax.lax.bitcast_convert_type(v, jnp.int32)
            else:
                v = v.astype(jnp.int32)
            w32.append(v[order])
        w = jnp.concatenate(w32)
        if w.shape[0] % 2:
            w = jnp.concatenate([w, jnp.zeros(1, jnp.int32)])
        parts.append(jax.lax.bitcast_convert_type(w.reshape(-1, 2), jnp.int64))
    buf = jnp.concatenate(parts)
    fbuf = (jnp.stack([cols[k][order] for k in namesf64])
            if namesf64 else None)
    return buf, fbuf


def unpack_from_host(buf, fbuf, target: int, fieldsi64, fieldsf64, fields32):
    """Host half of pack_for_host: slice the fetched buffers back into
    per-column numpy arrays (views where possible).  ``fields*`` are
    [(name, np_dtype)] in pack order.  Returns (cols, n) or (None, n) when
    the packed target was too small and the caller must refetch."""
    n = int(buf[0])
    if n > target:
        return None, n
    out = {}
    off = 1
    for name, _dt in fieldsi64:
        out[name] = buf[off:off + target][:n]
        off += target
    if fields32:
        w = buf[off:].view(np.int32)[: len(fields32) * target]
        for i, (name, dt) in enumerate(fields32):
            seg = w[i * target:i * target + target][:n]
            if dt.kind == "f":
                out[name] = seg.view(dt)
            elif dt == np.bool_:
                out[name] = seg.astype(np.bool_)
            else:
                out[name] = seg.astype(dt, copy=False)
    for i, (name, _dt) in enumerate(fieldsf64):
        out[name] = fbuf[i][:n]
    return out, n


# --------------------------------------------------------------------------
# sorting
# --------------------------------------------------------------------------


def sort_order(keys: Sequence[Tuple[jnp.ndarray, bool]], mask: jnp.ndarray) -> jnp.ndarray:
    """Permutation sorting live rows by (k1, k2, ...) with per-key
    ascending flags; dead rows sort to the end."""
    seq = []
    for arr, asc in reversed(list(keys)):
        a = arr
        if not asc:
            if a.dtype == jnp.bool_:
                a = ~a
            else:
                a = -a.astype(jnp.int64) if a.dtype.kind == "i" else -a
        seq.append(a)
    seq.append(~mask)  # primary: live rows first
    return jnp.lexsort(seq)


# --------------------------------------------------------------------------
# grouped aggregation (sort-based, static output capacity)
# --------------------------------------------------------------------------

AGG_SUM = "sum"
AGG_COUNT = "count"
AGG_MIN = "min"
AGG_MAX = "max"


DENSE_DOMAIN_LIMIT = 1 << 16  # max enumerable key-combination count


def dense_domain(key_ranges) -> Optional[int]:
    """Enumerable key-combination count when EVERY key has static (lo, hi)
    bounds and the product is within DENSE_DOMAIN_LIMIT; else None.  The
    single authority for 'does the dense path apply' — callers use it to
    clamp output capacities to what the kernel will actually produce."""
    if not key_ranges or any(r is None for r in key_ranges):
        return None
    domain = 1
    for lo, hi in key_ranges:
        domain *= max(0, hi - lo + 1)
    return domain if 0 < domain <= DENSE_DOMAIN_LIMIT else None


def grouped_aggregate_presorted(
    key_cols: List[jnp.ndarray],
    val_cols: List[Tuple[jnp.ndarray, str]],
    mask: jnp.ndarray,
    out_capacity: int,
):
    """Sort-FREE grouping for inputs already ordered by the single group
    key (clustered scans: physical_planner._clustered_having_pushdown).
    Compaction (two cumsums + scatter) replaces the argsort — on TPU this
    is the difference between a seconds and a minutes compile
    (grouped_aggregate docstring), and at SF10 it drops a per-task 1M-row
    sort on CPU too.

    Returns (out_keys, out_vals, out_mask, overflow, disorder): ``disorder``
    is True when live keys were NOT non-decreasing — the caller must then
    discard the result and re-run the sorted path (split runs of one key
    would otherwise emit duplicate partial states, which merge fine at a
    final aggregate but break early-HAVING filters)."""
    assert len(key_cols) == 1, "presorted grouping is single-key"
    order = compaction_order(mask)
    mask_s = mask[order]
    k = key_cols[0][order]
    disorder = jnp.any(mask_s[1:] & mask_s[:-1] & (k[1:] < k[:-1]))
    out_keys, out_vals, out_mask, overflow = _grouped_aggregate_on_order(
        [k], [(v[order], how) for v, how in val_cols], mask_s,
        out_capacity, mask.shape[0])
    return out_keys, out_vals, out_mask, overflow, disorder


def grouped_aggregate(
    key_cols: List[jnp.ndarray],
    val_cols: List[Tuple[jnp.ndarray, str]],
    mask: jnp.ndarray,
    out_capacity: int,
    key_ranges: Optional[Tuple[Optional[Tuple[int, int]], ...]] = None,
):
    """Group by ``key_cols`` and reduce ``val_cols`` (list of (array, how)).

    Returns (out_keys: list, out_vals: list, out_mask, overflow: bool scalar).
    Exact for arbitrary keys.  ``out_capacity`` bounds distinct groups;
    ``overflow`` flags truncation (host raises CapacityError).

    ``key_ranges``: optional static (lo, hi) bounds per key (inclusive), e.g.
    dictionary-code ranges for string keys.  When every key is bounded and
    the enumerable domain is small, grouping takes the **dense path**: the
    fused key IS the segment id — no sort at all.  This matters enormously
    on TPU, where the sort-based program's XLA compile takes minutes while
    the dense program compiles in seconds (measured: 163 s vs 3.8 s for the
    q1 shape on v5e) and runs ~2.5x faster.  Otherwise grouping is
    sort-based (lexsort -> boundary flags -> segment reductions).

    CONTRACT: ``key_ranges`` bounds are a caller-guaranteed invariant — every
    live row's key must lie inside its declared range.  On the dense path,
    when the domain fits ``out_capacity`` the overflow flag is statically
    None and out-of-range rows are **silently folded into the scratch slot**
    (dropped); only when the domain exceeds ``out_capacity`` does the
    returned flag also surface bad rows.  Engine callers build ranges
    structurally (dictionary code ranges, bool {0,1}) so violation is
    impossible there; external callers passing literal ranges own the
    guarantee.
    """
    if key_cols:
        domain = dense_domain(key_ranges)
        if domain is not None:
            return _grouped_aggregate_dense(key_cols, val_cols, mask,
                                            out_capacity, key_ranges, domain)
    n = mask.shape[0]
    if key_cols:
        order = sort_order([(k, True) for k in key_cols], mask)
    else:
        order = compaction_order(mask)
    mask_s = mask[order]
    return _grouped_aggregate_on_order(
        [k[order] for k in key_cols],
        [(v[order], how) for v, how in val_cols], mask_s, out_capacity, n)


def _grouped_aggregate_on_order(
    keys_s: List[jnp.ndarray],
    val_cols: List[Tuple[jnp.ndarray, str]],
    mask_s: jnp.ndarray,
    out_capacity: int,
    n: int,
):
    """Grouping over rows ALREADY in group order (live rows contiguous,
    equal keys adjacent): boundary flags -> segment reductions.  Shared by
    the sort path (grouped_aggregate) and the clustered presorted path
    (grouped_aggregate_presorted)."""
    if keys_s:
        first = jnp.zeros(n, dtype=bool).at[0].set(True)
        diff = jnp.zeros(n, dtype=bool)
        for k in keys_s:
            diff = diff | (k != jnp.roll(k, 1))
        boundary = mask_s & (first | diff)
    else:
        # global aggregate: one group iff any live row
        boundary = (jnp.arange(n) == 0) & (jnp.sum(mask_s) > 0)

    seg = jnp.cumsum(boundary) - 1  # group index per sorted row (-1 before first)
    num_groups = jnp.sum(boundary)
    # dead or out-of-capacity rows -> dump segment
    seg_ok = mask_s & (seg >= 0) & (seg < out_capacity)
    seg_ids = jnp.where(seg_ok, seg, out_capacity).astype(jnp.int32)

    # int64 sums/counts batch through the limb path (grouped_sums_i64 —
    # segment order does not matter there): on TPU an int64 segment_sum is
    # a 64-bit scatter measured 1-18M rows/s, and the first alternative
    # tried (sorted-run cumsum differences) turned out to COMPILE for 44 s
    # per shape on this backend, which per-job recompiles turned into a
    # regression.  The limb programs compile in ~1-2 s and run at memory
    # speed.
    i64_positions: List[int] = []
    i64_vals: List[jnp.ndarray] = []
    out_vals: List[Optional[jnp.ndarray]] = []
    for a, how in val_cols:
        if how == AGG_COUNT or (how == AGG_SUM and a.dtype == jnp.int64):
            if how == AGG_COUNT:
                pre = jnp.where(seg_ok, 1, 0).astype(jnp.int64)
            else:
                pre = jnp.where(seg_ok, a, jnp.zeros((), a.dtype))
            i64_positions.append(len(out_vals))
            i64_vals.append(pre)
            out_vals.append(None)
            continue
        elif how == AGG_SUM:
            v = jax.ops.segment_sum(jnp.where(seg_ok, a, jnp.zeros((), a.dtype)), seg_ids,
                                    num_segments=out_capacity + 1)[:out_capacity]
        elif how == AGG_MIN:
            if a.dtype == jnp.int64:
                v = grouped_minmax_i64(a, seg_ok, seg_ids, out_capacity + 1,
                                       is_min=True)[:out_capacity]
            else:
                ident = _max_ident(a.dtype)
                v = jax.ops.segment_min(jnp.where(seg_ok, a, ident), seg_ids,
                                        num_segments=out_capacity + 1)[:out_capacity]
        elif how == AGG_MAX:
            if a.dtype == jnp.int64:
                v = grouped_minmax_i64(a, seg_ok, seg_ids, out_capacity + 1,
                                       is_min=False)[:out_capacity]
            else:
                ident = _min_ident(a.dtype)
                v = jax.ops.segment_max(jnp.where(seg_ok, a, ident), seg_ids,
                                        num_segments=out_capacity + 1)[:out_capacity]
        else:
            raise ValueError(f"unknown agg {how}")
        out_vals.append(v)
    if i64_vals:
        sums = grouped_sums_i64(i64_vals, seg_ids, out_capacity + 1)
        for pos, s in zip(i64_positions, sums):
            out_vals[pos] = s[:out_capacity]

    out_keys = []
    for k in keys_s:
        # scatter each group's first (boundary) row into its slot; non-boundary
        # rows aim at the dump index and are dropped
        ok = jnp.zeros(out_capacity, dtype=k.dtype).at[
            jnp.where(boundary & seg_ok, seg, out_capacity)
        ].set(k, mode="drop")
        out_keys.append(ok)

    out_mask = jnp.arange(out_capacity) < jnp.minimum(num_groups, out_capacity)
    # out_capacity >= n makes overflow statically impossible: report None so
    # the host skips the flag check — a scalar device->host sync costs a
    # fixed ~75 ms over the axon tunnel, once per task
    overflow = (num_groups > out_capacity) if out_capacity < n else None
    return out_keys, out_vals, out_mask, overflow


# --------------------------------------------------------------------------
# int64 grouped reductions without 64-bit scatters
# --------------------------------------------------------------------------
#
# XLA's TPU scatter-add is the segment_sum lowering, and with x64 emulation
# an int64 segment_sum measured 18M rows/s — and the realistic multi-
# aggregate shape (8 int64 sums over one segment id vector, TPC-H q1's
# stage) collapsed to 1M rows/s, which made the aggregate the engine's
# dominant device cost.  int32 segment ops run ~200M rows/s and int32
# one-hot matmuls ride the MXU at effectively memory speed, so int64
# reductions decompose into exact 16-bit limbs:
#
# - sums: limb rows x one-hot(segment) matmul per row-chunk (chunk bound
#   keeps per-chunk limb sums inside int32), recombined in int64 — measured
#   ~1000x the segment_sum x8 shape; falls back to chunk-offset int32
#   segment_sums when the segment count makes one-hot tiles too large.
# - min/max: lexicographic two-pass over (hi32, lo32-with-flipped-sign)
#   int32 segment_min/max; identity values recombine to exactly the int64
#   idents, so empty slots stay mergeable (mesh pmin/pmax).
#
# The CPU backend keeps plain segment ops (its scatters are fast and the
# matmul would cost O(n*segments) scalar FLOPs on a host core).


@lru_cache(maxsize=1)
def _tpu_backend() -> bool:
    return jax.default_backend() == "tpu"


_MATMUL_SEG_LIMIT = 1024  # one-hot matmul while chunk x segments tiles fit
_SEG_CHUNK = 1 << 15      # max rows/chunk: 2^15 rows x 16-bit limbs < 2^31
# chunk-offset path ceiling on C*(S+1): keeps the per-limb scratch buffer
# <= 512 MB int32 AND far from the int32 id wrap at 2^31 (advisor r4:
# wrapped ids silently dropped rows -> wrong aggregates with no error)
_CHUNK_OFFSET_LIMIT = 1 << 27


def _i64_limbs(v: jnp.ndarray) -> List[jnp.ndarray]:
    """Four 16-bit limbs (int32, non-negative) of an int64 array's two's
    complement; limb-wise sums recombine exactly mod 2^64."""
    u = v.astype(jnp.uint64)
    return [((u >> (16 * i)) & jnp.uint64(0xFFFF)).astype(jnp.int32)
            for i in range(4)]


def _recombine_limbs(parts: jnp.ndarray) -> jnp.ndarray:
    """parts: int64[4, S] limb sums -> int64[S]."""
    return sum(parts[i] << (16 * i) for i in range(4))


def grouped_sums_i64(vals: List[jnp.ndarray], seg: jnp.ndarray,
                     num_segments: int) -> List[jnp.ndarray]:
    """Exact int64 grouped sums of pre-masked values (dead rows must
    already be 0).  ``seg`` is int32 in [0, num_segments); rows may also
    carry seg == num_segments-1 as a dump slot — this computes all slots
    and the caller slices."""
    if not _tpu_backend():
        return [jax.ops.segment_sum(v, seg, num_segments=num_segments)
                for v in vals]
    n = seg.shape[0]
    S = num_segments
    if S <= _MATMUL_SEG_LIMIT:
        chunk = min(_SEG_CHUNK, n)
        pad = (-n) % chunk
        if pad:
            # padded rows: seg == S matches no one-hot column -> contribute 0
            seg = jnp.concatenate([seg, jnp.full(pad, S, seg.dtype)])
        segc = seg.reshape(-1, chunk)
        rows = []
        for v in vals:
            if pad:
                v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
            rows.extend(_i64_limbs(v))
        lhs = jnp.stack(rows).reshape(len(rows), -1, chunk).transpose(1, 0, 2)
        iota_s = jnp.arange(S, dtype=jnp.int32)

        # carry-free scan (stacked per-chunk partials, summed after): a
        # zeros-initialized carry has no varying manual axes and trips
        # shard_map's vma check when this runs inside a mesh program
        def body(_, xs):
            l, sc = xs
            oh = (sc[:, None] == iota_s[None, :]).astype(jnp.int32)
            return None, jax.lax.dot_general(l, oh, (((1,), (0,)), ((), ())))

        _, parts = jax.lax.scan(body, None, (lhs, segc))
        acc = jnp.sum(parts.astype(jnp.int64), axis=0)
        return [_recombine_limbs(acc[4 * i:4 * i + 4])
                for i in range(len(vals))]
    # large segment count: chunk-offset int32 segment_sums per limb (per
    # chunk x segment a limb sum stays < 2^31), recombined in int64
    chunk = min(_SEG_CHUNK, n)
    S1 = S + 1  # one scratch slot for padded rows
    n_chunks = -(-n // chunk)
    if n_chunks * S1 > _CHUNK_OFFSET_LIMIT:
        # ids = seg + chunk_index*S1 wraps int32 past 2^31 — XLA would then
        # silently DROP the wrapped rows — and the C*S1 scratch buffer per
        # limb reaches multiple GB well before the wrap point.  All inputs
        # to this check are static shapes, so the guard costs nothing: fall
        # back to the plain int64 segment_sum (a slow 64-bit scatter, but
        # exact) rather than ever risking silent wrong aggregates.
        return [jax.ops.segment_sum(v, seg, num_segments=S) for v in vals]
    pad = (-n) % chunk
    if pad:
        seg = jnp.concatenate([seg, jnp.full(pad, S, seg.dtype)])
    C = seg.shape[0] // chunk
    ids = (seg.reshape(C, chunk)
           + (jnp.arange(C, dtype=jnp.int32) * S1)[:, None]).reshape(-1)
    out = []
    for v in vals:
        if pad:
            v = jnp.concatenate([v, jnp.zeros(pad, v.dtype)])
        parts = []
        for limb in _i64_limbs(v):
            p = jax.ops.segment_sum(limb, ids, num_segments=C * S1)
            parts.append(jnp.sum(p.reshape(C, S1).astype(jnp.int64),
                                 axis=0)[:S])
        out.append(_recombine_limbs(jnp.stack(parts)))
    return out


_I32_MAX = jnp.int32(2**31 - 1)
_I32_MIN = jnp.int32(-2**31)


def grouped_minmax_i64(v: jnp.ndarray, ok: jnp.ndarray, seg: jnp.ndarray,
                       num_segments: int, is_min: bool) -> jnp.ndarray:
    """Exact int64 grouped min/max via two int32 passes: first the high
    word, then the (unsigned-ordered) low word among rows matching the
    winning high word.  Empty slots recombine to exactly INT64_MAX /
    INT64_MIN — the same merge identities the int64 segment ops produce."""
    if not _tpu_backend():
        ident = _max_ident(v.dtype) if is_min else _min_ident(v.dtype)
        masked = jnp.where(ok, v, ident)
        op = jax.ops.segment_min if is_min else jax.ops.segment_max
        return op(masked, seg, num_segments=num_segments)
    hi = (v >> 32).astype(jnp.int32)
    # low word compared as unsigned: subtract 2^31 so int32 order matches
    lo = ((v & jnp.int64(0xFFFFFFFF)) - jnp.int64(1 << 31)).astype(jnp.int32)
    op = jax.ops.segment_min if is_min else jax.ops.segment_max
    ident = _I32_MAX if is_min else _I32_MIN
    hi_best = op(jnp.where(ok, hi, ident), seg, num_segments=num_segments)
    sel = ok & (hi == hi_best[seg])
    lo_best = op(jnp.where(sel, lo, ident), seg, num_segments=num_segments)
    lo_u = (lo_best.astype(jnp.int64) + jnp.int64(1 << 31)) \
        & jnp.int64(0xFFFFFFFF)
    return (hi_best.astype(jnp.int64) << 32) | lo_u


def _dense_strides(key_ranges):
    """Row-major packing of a dense key domain: per-key sizes and strides.
    The single owner of the packing convention — dense_group_states encodes
    fused keys with it and compact_dense_states decodes them."""
    sizes = [hi - lo + 1 for lo, hi in key_ranges]
    strides = [1] * len(sizes)
    for i in range(len(sizes) - 2, -1, -1):
        strides[i] = strides[i + 1] * sizes[i + 1]
    return sizes, strides


def dense_group_states(
    key_cols: List[jnp.ndarray],
    val_cols: List[Tuple[jnp.ndarray, str]],
    mask: jnp.ndarray,
    key_ranges: Tuple[Tuple[int, int], ...],
    domain: int,
):
    """Slot-aligned dense accumulators: slot d holds key combination d
    (row-major packing over ``key_ranges``), for EVERY d in the domain.

    Returns (dense_vals: list, exists_cnt: int32[domain], bad_rows: bool).
    Because slots are positionally aligned, states from different shards
    merge by pure elementwise reduction (psum/pmin/pmax) — the basis of the
    mesh reduce-collective aggregate (parallel/distributed.py)."""
    sizes, strides = _dense_strides(key_ranges)

    fused = jnp.zeros(mask.shape, dtype=jnp.int32)
    in_range = mask
    for k, (lo, hi), stride in zip(key_cols, key_ranges, strides):
        ki = k.astype(jnp.int32)
        in_range = in_range & (ki >= lo) & (ki <= hi)
        fused = fused + (ki - lo) * jnp.int32(stride)
    # rows outside the declared ranges (impossible for dict codes; would
    # indicate a batch/range mismatch) raise the overflow flag: capacity
    # retries won't help, but surfacing a CapacityError beats silently
    # dropping rows
    bad_rows = jnp.any(mask & ~in_range)
    seg = jnp.where(in_range, fused, domain).astype(jnp.int32)

    exists_cnt = jax.ops.segment_sum(
        jnp.where(in_range, 1, 0).astype(jnp.int32), seg,
        num_segments=domain + 1)[:domain]

    # int64 sums/counts batch through the limb path (one fused program for
    # every aggregate — the TPU-fast formulation, see grouped_sums_i64)
    i64_sums: List[Tuple[int, jnp.ndarray]] = []
    dense_vals: List[Optional[jnp.ndarray]] = []
    for arr, how in val_cols:
        if how == AGG_COUNT:
            i64_sums.append((len(dense_vals),
                             jnp.where(in_range, 1, 0).astype(jnp.int64)))
            dense_vals.append(None)
        elif how == AGG_SUM and arr.dtype == jnp.int64:
            i64_sums.append((len(dense_vals),
                             jnp.where(in_range, arr,
                                       jnp.zeros((), arr.dtype))))
            dense_vals.append(None)
        elif how == AGG_SUM:
            v = jax.ops.segment_sum(
                jnp.where(in_range, arr, jnp.zeros((), arr.dtype)), seg,
                num_segments=domain + 1)[:domain]
            dense_vals.append(v)
        elif how in (AGG_MIN, AGG_MAX):
            if arr.dtype == jnp.int64:
                v = grouped_minmax_i64(arr, in_range, seg, domain + 1,
                                       is_min=(how == AGG_MIN))[:domain]
            elif how == AGG_MIN:
                v = jax.ops.segment_min(
                    jnp.where(in_range, arr, _max_ident(arr.dtype)), seg,
                    num_segments=domain + 1)[:domain]
            else:
                v = jax.ops.segment_max(
                    jnp.where(in_range, arr, _min_ident(arr.dtype)), seg,
                    num_segments=domain + 1)[:domain]
            dense_vals.append(v)
        else:
            raise ValueError(f"unknown agg {how}")
    if i64_sums:
        sums = grouped_sums_i64([v for _, v in i64_sums], seg, domain + 1)
        for (pos, _), s in zip(i64_sums, sums):
            dense_vals[pos] = s[:domain]
    return dense_vals, exists_cnt, bad_rows


def compact_dense_states(
    key_cols_dtypes,
    dense_vals: List[jnp.ndarray],
    exists: jnp.ndarray,
    out_capacity: int,
    key_ranges: Tuple[Tuple[int, int], ...],
    domain: int,
):
    """Compact slot-aligned dense states into the (keys, vals, mask,
    overflow) shape the sort path produces: non-empty groups first, in
    ascending fused-key order, padded/truncated to ``out_capacity``.
    ``key_cols_dtypes``: output dtype per key column."""
    sizes, strides = _dense_strides(key_ranges)

    # compact non-empty groups to the front (stable: keeps ascending key
    # order); domain is small, so this sort is trivial
    order = jnp.argsort(~exists, stable=True)
    if domain > out_capacity:
        order = order[:out_capacity]
    num_groups = jnp.sum(exists)
    out_mask_full = exists[order]
    out_vals = [v[order] for v in dense_vals]
    out_keys = []
    for i, ((lo, hi), stride, dt) in enumerate(
            zip(key_ranges, strides, key_cols_dtypes)):
        dk = lo + (order.astype(jnp.int32) // jnp.int32(stride)) % jnp.int32(sizes[i])
        out_keys.append(dk.astype(dt))

    # pad up to out_capacity if the domain is smaller
    if domain < out_capacity:
        pad = out_capacity - domain
        out_mask_full = jnp.concatenate([out_mask_full, jnp.zeros(pad, dtype=bool)])
        out_vals = [jnp.concatenate([v, jnp.zeros(pad, dtype=v.dtype)]) for v in out_vals]
        out_keys = [jnp.concatenate([k, jnp.zeros(pad, dtype=k.dtype)]) for k in out_keys]

    overflow = num_groups > out_capacity
    return out_keys, out_vals, out_mask_full, overflow


def _grouped_aggregate_dense(
    key_cols: List[jnp.ndarray],
    val_cols: List[Tuple[jnp.ndarray, str]],
    mask: jnp.ndarray,
    out_capacity: int,
    key_ranges: Tuple[Tuple[int, int], ...],
    domain: int,
):
    """Dense-domain grouping: every key combination is enumerable, so the
    fused (row-major packed) key is the segment id directly.  Output groups
    come out in ascending fused-key order — the same ascending key order the
    sort path produces."""
    dense_vals, exists_cnt, bad_rows = dense_group_states(
        key_cols, val_cols, mask, key_ranges, domain)
    out_keys, out_vals, out_mask, overflow = compact_dense_states(
        [k.dtype for k in key_cols], dense_vals, exists_cnt > 0,
        out_capacity, key_ranges, domain)
    if domain <= out_capacity:
        # overflow is statically impossible (num_groups <= domain) and the
        # bad_rows guard is structurally excluded for caller-built ranges
        # (dict codes < len(dict) <= rounded range; bool in {0,1}): return
        # None so the host skips the ~75 ms-per-task flag sync on
        # remote-attached devices
        return out_keys, out_vals, out_mask, None
    return out_keys, out_vals, out_mask, overflow | bad_rows


def overflow_flag(x):
    """Normalize a grouped_aggregate overflow result for jit-traced
    combinators: None (statically impossible) becomes a constant False
    scalar so flags can be |'d and psum'd uniformly."""
    return jnp.zeros((), bool) if x is None else x


def _max_ident(dtype):
    if dtype.kind == "f":
        return jnp.array(jnp.inf, dtype=dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype=dtype)


def _min_ident(dtype):
    if dtype.kind == "f":
        return jnp.array(-jnp.inf, dtype=dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype=dtype)


# --------------------------------------------------------------------------
# join (sorted build + searchsorted probe + offset-inversion expansion)
# --------------------------------------------------------------------------


def build_side_sort(build_keys: List[jnp.ndarray], build_mask: jnp.ndarray):
    """Sort the build side by mixed 64-bit key; dead rows get I64_MAX-as-uint.

    Returns (hash_sorted: uint64, order: int32 permutation, n_build).
    """
    h = hash64(build_keys)
    h = jnp.where(build_mask, h, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    order = jnp.argsort(h)
    return h[order], order, jnp.sum(build_mask)


def probe_join(
    probe_hash: jnp.ndarray,
    probe_mask: jnp.ndarray,
    build_hash_sorted: jnp.ndarray,
    out_capacity: int,
):
    """Match probe rows against the sorted build hashes.

    Returns (probe_idx, build_pos, pair_valid, total_pairs):
    - ``probe_idx[j]``: which probe row pair j belongs to,
    - ``build_pos[j]``: position in the *sorted* build array,
    - ``pair_valid[j]``: pair j is within the real match set,
    - ``total_pairs``: dynamic count (<= out_capacity or overflow).
    Callers MUST verify real key equality afterwards (hash collisions).
    """
    lo = jnp.searchsorted(build_hash_sorted, probe_hash, side="left")
    hi = jnp.searchsorted(build_hash_sorted, probe_hash, side="right")
    counts = jnp.where(probe_mask, hi - lo, 0)
    offsets = jnp.cumsum(counts)  # inclusive
    total = offsets[-1]
    starts = offsets - counts

    j = jnp.arange(out_capacity)
    # probe row for output slot j: first i with offsets[i] > j
    probe_idx = jnp.searchsorted(offsets, j, side="right")
    probe_idx = jnp.clip(probe_idx, 0, probe_hash.shape[0] - 1)
    k = j - starts[probe_idx]
    build_pos = lo[probe_idx] + k
    pair_valid = (j < total) & (k >= 0) & (k < counts[probe_idx])
    build_pos = jnp.clip(build_pos, 0, build_hash_sorted.shape[0] - 1)
    return probe_idx, build_pos, pair_valid, total


def segment_any(values: jnp.ndarray, seg_ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    """Per-segment logical OR (used for semi/anti reduction)."""
    return jax.ops.segment_max(values.astype(jnp.int32), seg_ids, num_segments=num_segments) > 0


# --------------------------------------------------------------------------
# calendar (EXTRACT) — civil-from-days, pure integer ops
# --------------------------------------------------------------------------


def civil_from_days(days, xp=jnp):
    """Epoch days -> (year, month, day), vectorized (Howard Hinnant's algo).

    ``xp`` is jnp (device) or numpy (host-finalize expression mode).
    """
    z = days.astype("int64") + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype("int32"), m.astype("int32"), d.astype("int32")


def extract_field(days, field: str, xp=jnp):
    y, m, d = civil_from_days(days, xp)
    if field == "year":
        return y
    if field == "month":
        return m
    if field == "day":
        return d
    raise ValueError(f"unsupported EXTRACT field {field}")


# --------------------------------------------------------------------------
# top-k (sort + limit fusion)
# --------------------------------------------------------------------------


def topk_order(keys, mask, k: int) -> jnp.ndarray:
    """First k positions of the sort order (full sort; XLA's sort is fast)."""
    return sort_order(keys, mask)[:k]
